"""Fleet SLO gossip — per-replica objective status over the TCPStore.

An :class:`~.slo.SLOEngine` is process-local: each replica evaluates its
own objectives against its own time-series store, so "is the *fleet*
inside its error budget" has no single answer surface.  Each replica
therefore publishes its engine's :meth:`~.slo.SLOEngine.status` payload
— objectives, live burn rates, remaining budget, alert states, recent
transitions — and rank 0 folds every replica's view into one merged
payload behind ``/slo?fleet=1``.  The transport is the same
:class:`~.aggregate.StorePublisher` machinery every per-rank publisher
rides: one TCPStore key per replica, overwritten in place, a daemon
thread that survives a flaky store, nothing started on import.

Correctness note: gossip is *advisory* and staleness-tolerant.  A lost
or stale status means the fleet view temporarily misses that replica's
objectives — the fold reports every replica it can see (and which ones
those were), and the next publish heals the view.  Nothing
alerting-critical reads the merged payload: each replica's own engine
keeps firing its own pages regardless.

Merge semantics (:func:`merge_fleet_slo`): fleet ``page_active`` is the
OR over replicas; per-objective, the fold keeps each replica's live
burn rates and budget, the *worst* (minimum) remaining budget wins
``error_budget_ratio``, active alerts are listed with their replica,
and the transition logs interleave by time (each entry tagged with its
replica) so one timeline shows which replica fired first.

Wiring::

    # each replica process
    SLOStatusPublisher(engine, replica_id=r, store=store).start(1.0)

    # rank 0
    start_telemetry_server(
        fleet_slo=lambda: collect_fleet_slo(store, range(n_replicas)))
"""
from __future__ import annotations

import json
import time

from .aggregate import StorePublisher

__all__ = ["SLOStatusPublisher", "collect_slo_statuses",
           "merge_fleet_slo", "collect_fleet_slo"]

#: newest interleaved transitions kept in the merged payload
_MAX_FLEET_TRANSITIONS = 256


def _replica_key(prefix, replica_id):
    return f"{prefix}/replica_{int(replica_id)}"


class SLOStatusPublisher(StorePublisher):
    """Publish one engine's ``/slo`` status under its fleet key.

    ``publish()`` pushes once; ``start(interval_s)`` runs the inherited
    daemon loop.  The payload is exactly :meth:`~.slo.SLOEngine.status`
    plus the replica id and a wall-clock stamp for staleness
    filtering."""

    def __init__(self, engine, replica_id, store, key_prefix="slo",
                 clock=None):
        super().__init__(store, _replica_key(key_prefix, replica_id),
                         clock=clock)
        self.engine = engine
        self.replica_id = int(replica_id)
        self.thread_name = f"slo-gossip-{self.replica_id}"

    def payload(self):
        return {"replica": self.replica_id, "time": self._clock(),
                "status": self.engine.status()}


def collect_slo_statuses(store, replica_ids, key_prefix="slo",
                         stale_after_s=None, clock=None):
    """Read every replica's published status in ONE ``mget`` round
    trip.  Returns ``[(source_label, status)]`` pairs.  Replicas that
    never published, published garbage, or whose stamp is older than
    ``stale_after_s`` (publisher wall clock) are simply absent.
    Non-blocking by construction: a scrape never waits on a slow
    store."""
    replica_ids = list(replica_ids)
    keys = [_replica_key(key_prefix, r) for r in replica_ids]
    out = []
    now = (clock or time.time)()
    for rid, raw in zip(replica_ids, store.mget(keys)):
        if raw is None:
            continue
        try:
            payload = json.loads(raw)
        except (ValueError, TypeError):
            continue            # torn/garbled publish: treat as absent
        if stale_after_s is not None and \
                now - float(payload.get("time") or 0.0) > stale_after_s:
            continue
        status = payload.get("status")
        if isinstance(status, dict):
            out.append((f"replica{int(rid)}", status))
    return out


def merge_fleet_slo(statuses):
    """Fold ``[(source_label, status)]`` pairs into the
    ``/slo?fleet=1`` payload (see the module docstring for the
    semantics)."""
    replicas, objectives, transitions = {}, {}, []
    page_active = False
    for label, status in statuses:
        page = bool(status.get("page_active"))
        page_active = page_active or page
        replicas[label] = {
            "page_active": page,
            "evaluations": status.get("evaluations"),
        }
        for name, spec in (status.get("slos") or {}).items():
            obj = objectives.get(name)
            if obj is None:
                obj = objectives[name] = {
                    "target": spec.get("target"),
                    "description": spec.get("description"),
                    "replicas": {},
                    "error_budget_ratio": None,
                    "alerts_active": [],
                }
            last = spec.get("last") or {}
            budget = last.get("error_budget_ratio")
            obj["replicas"][label] = {
                "burn_rates": last.get("burn_rates"),
                "error_budget_ratio": budget,
            }
            if budget is not None:
                worst = obj["error_budget_ratio"]
                if worst is None or budget < worst:
                    obj["error_budget_ratio"] = budget
            for alert in spec.get("alerts") or ():
                if alert.get("active"):
                    obj["alerts_active"].append(
                        {"replica": label,
                         "severity": alert.get("severity"),
                         "since": alert.get("since")})
        for tr in status.get("transitions") or ():
            transitions.append(dict(tr, replica=label))
    transitions.sort(key=lambda tr: tr.get("time") or 0.0)
    return {"fleet": True,
            "replicas": dict(sorted(replicas.items())),
            "page_active": page_active,
            "slos": dict(sorted(objectives.items())),
            "transitions": transitions[-_MAX_FLEET_TRANSITIONS:]}


def collect_fleet_slo(store, replica_ids, key_prefix="slo",
                      stale_after_s=None, clock=None, extra=()):
    """The fleet view: every replica's published status merged by
    objective (:func:`merge_fleet_slo`).  ``extra`` appends in-process
    statuses — e.g. ``[("rank0", engine.status())]`` so the collector
    rank's own objectives land in the same fold without a store round
    trip."""
    statuses = collect_slo_statuses(store, replica_ids,
                                    key_prefix=key_prefix,
                                    stale_after_s=stale_after_s,
                                    clock=clock)
    return merge_fleet_slo(list(extra) + statuses)
