"""In-process time-series store — windowed history over the registry.

Every consumer of fleet telemetry so far (the autoscaler, the soak
assertions, ``/healthz``, a human scraping ``/fleet``) reads the
MetricsRegistry *instantaneously*: there is no history, no windowed
rate, and no way to ask "what was TTFT p99 over the last 60 s" as
opposed to "over the whole process lifetime".  The
:class:`TimeSeriesStore` closes that gap with a fixed-budget in-process
ring:

- **scrape, don't instrument**: :meth:`scrape_once` walks the attached
  :class:`~.metrics.MetricsRegistry` and appends one ``(timestamp,
  value)`` point per live series — counters and gauges by value,
  histograms by their full cumulative bucket vector — onto a bounded
  per-series deque (``max_points`` newest points, ``retention_s``
  newest seconds, ``max_series`` series total: the budget is fixed no
  matter how long the process runs).
- **counter-reset detection**: ``ServingMetrics`` (and friends) rebuild
  with ``register(replace=True)``, so a raw counter can go *backwards*
  between scrapes.  The store keeps a per-series monotonic adjustment:
  a scraped value below the previous one means the series restarted
  from zero, the previous value is folded into a base offset, and every
  stored point carries the *adjusted* cumulative value — windowed
  deltas stay non-negative across an engine rebuild mid-soak.
- **windowed queries** on an injectable clock: :meth:`rate` /
  :meth:`delta` (counters, summed across a label family),
  :meth:`avg` / :meth:`slope` (gauges — ``slope`` is the least-squares
  per-second trend that answers "when did memory start growing"), and
  :meth:`quantile` (histogram-bucket deltas over the window with
  linear interpolation inside the crossing bucket — the Prometheus
  ``histogram_quantile`` shape), so "TTFT p99 over the last 60 s"
  exists distinct from the lifetime reservoir percentile.
- **opt-in thread** (the ResourceSampler/StorePublisher discipline):
  nothing starts on import or construction; :meth:`start` runs
  :meth:`scrape_once` on a daemon thread, tests and the soak harness
  drive it synchronously on a manual clock.

The store powers the :mod:`.slo` engine's burn-rate windows, the
``/timeseries`` exporter endpoint, and the autoscaler's windowed
shed/goodput signals (replacing its ad-hoc between-poll counter
deltas).
"""
from __future__ import annotations

import threading
import time

from .metrics import default_registry

__all__ = ["TimeSeriesStore"]


class _Series:
    """One scraped series: the bounded point ring plus the reset
    bookkeeping that keeps counter/histogram points monotonic.  All
    fields are guarded by the owning store's lock."""

    __slots__ = ("kind", "points", "resets",
                 "last_value", "offset",
                 "buckets", "last_counts", "last_total", "last_sum",
                 "offset_counts", "offset_total", "offset_sum")

    def __init__(self, kind):
        self.kind = kind
        self.points = []        # guarded-by: store._lock
        self.resets = 0         # guarded-by: store._lock
        # counters/gauges
        self.last_value = None  # guarded-by: store._lock
        self.offset = 0.0       # guarded-by: store._lock
        # histograms
        self.buckets = None     # guarded-by: store._lock
        self.last_counts = None     # guarded-by: store._lock
        self.last_total = 0     # guarded-by: store._lock
        self.last_sum = 0.0     # guarded-by: store._lock
        self.offset_counts = None   # guarded-by: store._lock
        self.offset_total = 0   # guarded-by: store._lock
        self.offset_sum = 0.0   # guarded-by: store._lock


class TimeSeriesStore:
    """Fixed-budget ring of scraped registry samples with windowed
    queries.

    ``registry`` defaults to the process-wide one; ``clock`` is
    injectable (tests and the soak drive the store on a manual clock).
    ``max_points`` bounds every series' ring, ``retention_s`` drops
    points older than the window anyone can query, ``max_series``
    bounds the series population (new series beyond it are counted in
    ``dropped_series``, never stored — the budget is fixed)."""

    def __init__(self, registry=None, clock=None, *, interval_s=1.0,
                 max_points=512, retention_s=600.0, max_series=1024):
        self.registry = registry or default_registry()
        self._clock = clock or time.perf_counter
        self.interval_s = float(interval_s)
        self.max_points = int(max_points)
        self.retention_s = float(retention_s)
        self.max_series = int(max_series)
        # the scrape thread mutates, query/exporter threads read — one
        # lock guards all mutable store state.  Taken AFTER the
        # registry/metric locks are released (scrape reads child values
        # first, then appends under the store lock) and never while
        # calling out, so no ordering cycle exists.
        self._lock = threading.Lock()
        self._series = {}       # (name, labelvalues) -> _Series; guarded-by: self._lock
        self._families = {}     # name -> {kind, labelnames, keys}; guarded-by: self._lock
        self._scrapes = 0       # guarded-by: self._lock
        self._dropped_series = 0    # guarded-by: self._lock
        self._thread = None
        self._stop = threading.Event()

    # ------------------------------------------------------------- scrape
    def scrape_once(self):
        """Walk the registry, append one timestamped point per series
        (reset-adjusted), trim to budget.  Returns the number of series
        touched."""
        self.registry._run_collectors()
        now = self._clock()
        # read every child's value OUTSIDE the store lock (metric locks
        # are taken by .value / the histogram copy), then publish the
        # batch under one store-lock hold
        batch = []
        for m in self.registry.metrics():
            for lv, child in m._series():
                if m.kind == "histogram":
                    with child._lock:
                        val = (list(child.counts), child.total,
                               child.sum, list(child.buckets))
                else:
                    val = child.value
                batch.append((m.name, m.kind, tuple(m.labelnames),
                              lv, val))
        with self._lock:
            for name, kind, labelnames, lv, val in batch:
                self._record_locked(now, name, kind, labelnames, lv, val)
            self._scrapes += 1
            return len(batch)

    def _record_locked(self, now, name, kind, labelnames, lv, val):
        key = (name, lv)
        ser = self._series.get(key)
        if ser is None:
            if len(self._series) >= self.max_series:
                self._dropped_series += 1
                return
            ser = self._series[key] = _Series(kind)
            fam = self._families.setdefault(
                name, {"kind": kind, "labelnames": labelnames,
                       "keys": []})
            fam["keys"].append(key)
        if kind == "counter":
            raw = float(val)
            if ser.last_value is not None and raw < ser.last_value:
                # series replaced (register(replace=True)): it restarted
                # from zero — fold the pre-reset value into the offset
                # so the adjusted cumulative stays monotonic
                ser.offset += ser.last_value
                ser.resets += 1
            ser.last_value = raw
            ser.points.append((now, ser.offset + raw))
        elif kind == "histogram":
            counts, total, hsum, buckets = val
            if ser.buckets is None or len(ser.buckets) != len(buckets):
                # first sight, or a rebuild changed the bucket layout:
                # restart the adjustment bookkeeping on the new shape
                if ser.buckets is not None:
                    ser.resets += 1
                ser.buckets = list(buckets)
                ser.offset_counts = [0] * len(counts)
                ser.last_counts = None
            if ser.last_counts is not None and total < ser.last_total:
                ser.resets += 1
                for i, c in enumerate(ser.last_counts):
                    ser.offset_counts[i] += c
                ser.offset_total += ser.last_total
                ser.offset_sum += ser.last_sum
            ser.last_counts = counts
            ser.last_total = total
            ser.last_sum = hsum
            adj = tuple(o + c for o, c in zip(ser.offset_counts, counts))
            ser.points.append((now, adj, ser.offset_total + total,
                               ser.offset_sum + hsum))
        else:                           # gauge
            ser.last_value = float(val)
            ser.points.append((now, ser.last_value))
        pts = ser.points
        if len(pts) > self.max_points:
            del pts[:len(pts) - self.max_points]
        cutoff = now - self.retention_s
        drop = 0
        while drop < len(pts) and pts[drop][0] < cutoff:
            drop += 1
        if drop:
            del pts[:drop]

    # ------------------------------------------------------------ queries
    def _resolve_locked(self, name, labels):
        """[(key, _Series)] the query covers: the single child matching
        ``labels``, or every series of the family when ``labels`` is
        None (counter/histogram queries sum across the family)."""
        fam = self._families.get(name)
        if fam is None:
            return []
        if labels is None:
            return [(k, self._series[k]) for k in fam["keys"]]
        labelnames = fam["labelnames"]
        if set(labels) != set(labelnames):
            raise ValueError(f"{name} expects labels {labelnames}, "
                             f"got {tuple(labels)}")
        key = (name, tuple(str(labels[k]) for k in labelnames))
        ser = self._series.get(key)
        return [(key, ser)] if ser is not None else []

    @staticmethod
    def _window_start_locked(ser, now, window_s):
        """Index of the first point with ``t >= now - window_s``
        (binary search on the monotonic timestamps — every burn-rate
        window query walks through here)."""
        cutoff = now - window_s
        pts = ser.points
        lo, hi = 0, len(pts)
        while lo < hi:
            mid = (lo + hi) // 2
            if pts[mid][0] < cutoff:
                lo = mid + 1
            else:
                hi = mid
        return lo

    @classmethod
    def _window_locked(cls, ser, now, window_s):
        """The series' points with ``t >= now - window_s``."""
        return ser.points[cls._window_start_locked(ser, now, window_s):]

    def delta(self, name, labels=None, window_s=60.0):
        """Counter increase over the window (reset-adjusted; summed
        across the label family when ``labels`` is None).  None until
        two scrapes fall inside the window."""
        now = self._clock()
        with self._lock:
            total, seen = 0.0, False
            for _key, ser in self._resolve_locked(name, labels):
                pts = ser.points
                lo = self._window_start_locked(ser, now, window_s)
                if len(pts) - lo < 2:
                    continue
                idx = 2 if ser.kind == "histogram" else 1
                total += pts[-1][idx] - pts[lo][idx]
                seen = True
            return total if seen else None

    def rate(self, name, labels=None, window_s=60.0):
        """Per-second increase over the window — per-series
        ``delta / elapsed`` summed across the family (the
        ``sum(rate(...))`` shape).  None until two scrapes fall inside
        the window."""
        now = self._clock()
        with self._lock:
            total, seen = 0.0, False
            for _key, ser in self._resolve_locked(name, labels):
                pts = ser.points
                lo = self._window_start_locked(ser, now, window_s)
                if len(pts) - lo < 2 or pts[-1][0] <= pts[lo][0]:
                    continue
                idx = 2 if ser.kind == "histogram" else 1
                total += ((pts[-1][idx] - pts[lo][idx])
                          / (pts[-1][0] - pts[lo][0]))
                seen = True
            return total if seen else None

    def avg(self, name, labels=None, window_s=60.0):
        """Mean of a gauge's samples in the window (one series — pass
        ``labels`` for a family child).  None with no samples."""
        now = self._clock()
        with self._lock:
            sers = self._resolve_locked(name, labels)
            if len(sers) != 1:
                if not sers:
                    return None
                raise ValueError(
                    f"avg({name!r}) is ambiguous across "
                    f"{len(sers)} series — pass labels")
            pts = self._window_locked(sers[0][1], now, window_s)
            vals = [p[1] for p in pts]
            return sum(vals) / len(vals) if vals else None

    def slope(self, name, labels=None, window_s=60.0):
        """Least-squares per-second trend of a gauge over the window —
        the "when did memory start growing" query.  None until two
        distinct-time samples fall inside the window."""
        now = self._clock()
        with self._lock:
            sers = self._resolve_locked(name, labels)
            if len(sers) != 1:
                if not sers:
                    return None
                raise ValueError(
                    f"slope({name!r}) is ambiguous across "
                    f"{len(sers)} series — pass labels")
            pts = self._window_locked(sers[0][1], now, window_s)
        if len(pts) < 2:
            return None
        t0 = pts[0][0]
        ts = [p[0] - t0 for p in pts]
        vs = [float(p[1]) for p in pts]
        n = len(pts)
        mt = sum(ts) / n
        mv = sum(vs) / n
        var = sum((t - mt) ** 2 for t in ts)
        if var == 0.0:
            return None
        return sum((t - mt) * (v - mv) for t, v in zip(ts, vs)) / var

    def latest(self, name, labels=None):
        """Newest stored value of one series (counters: the
        reset-adjusted cumulative).  None if never scraped."""
        with self._lock:
            sers = self._resolve_locked(name, labels)
            if len(sers) != 1 or not sers[0][1].points:
                return None
            ser = sers[0][1]
            p = ser.points[-1]
            return p[2] if ser.kind == "histogram" else p[1]

    def quantile(self, name, p, labels=None, window_s=60.0):
        """Histogram quantile (``p`` in 0..100, matching
        ``Histogram.percentile``) over the bucket-count *deltas* inside
        the window — the windowed TTFT p99, distinct from the lifetime
        reservoir.  Linear interpolation inside the crossing bucket
        (the ``histogram_quantile`` convention); observations above the
        top bucket clamp to its upper bound.  Summed across the family
        when ``labels`` is None; None until two scrapes with traffic
        between them fall inside the window."""
        now = self._clock()
        with self._lock:
            sers = [(k, s) for k, s in self._resolve_locked(name, labels)
                    if s.kind == "histogram"]
            buckets = None
            counts_delta = None
            total_delta = 0
            for _key, ser in sers:
                pts = ser.points
                lo_i = self._window_start_locked(ser, now, window_s)
                if len(pts) - lo_i < 2:
                    continue
                first, last = pts[lo_i], pts[-1]
                if buckets is None:
                    buckets = list(ser.buckets)
                    counts_delta = [0] * len(first[1])
                elif list(ser.buckets) != buckets or \
                        len(first[1]) != len(counts_delta):
                    continue        # mismatched layout: skip, don't lie
                for i in range(len(counts_delta)):
                    counts_delta[i] += last[1][i] - first[1][i]
                total_delta += last[2] - first[2]
        if buckets is None or total_delta <= 0:
            return None
        rank = p / 100.0 * total_delta
        cum = 0
        for i, ub in enumerate(buckets):
            c = counts_delta[i]
            if c and cum + c >= rank:
                lo = buckets[i - 1] if i > 0 else 0.0
                return lo + (ub - lo) * (rank - cum) / c
            cum += c
        return buckets[-1]

    def good_below(self, name, threshold, labels=None, window_s=60.0):
        """``(good, total)`` observation deltas over the window for a
        histogram: ``good`` counts observations in buckets whose upper
        bound is at or under ``threshold`` (the snap-down is
        conservative — an observation between the last included bound
        and the threshold reads as bad, never the reverse).  The
        latency-SLO primitive: ``good/total ≥ target`` is "p(target)
        under the threshold" in budget-burnable form.  Summed across
        the family when ``labels`` is None; ``(0, 0)`` until two
        scrapes fall inside the window."""
        now = self._clock()
        with self._lock:
            good = total = 0.0
            for _key, ser in self._resolve_locked(name, labels):
                if ser.kind != "histogram":
                    continue
                pts = ser.points
                lo = self._window_start_locked(ser, now, window_s)
                if len(pts) - lo < 2:
                    continue
                first, last = pts[lo], pts[-1]
                total += last[2] - first[2]
                for i, ub in enumerate(ser.buckets):
                    if ub <= threshold * (1.0 + 1e-9):
                        good += last[1][i] - first[1][i]
            return good, total

    # ------------------------------------------------------------ surface
    def query(self, name, labels=None, window_s=60.0):
        """Everything the store can say about one name over the window
        — the ``/timeseries?name=...`` payload."""
        with self._lock:
            fam = self._families.get(name)
            kind = fam["kind"] if fam else None
        out = {"name": name, "kind": kind,
               "window_seconds": float(window_s)}
        if kind is None:
            return out
        if kind == "gauge":
            out["latest"] = self.latest(name, labels)
            out["avg"] = self.avg(name, labels, window_s)
            out["slope_per_s"] = self.slope(name, labels, window_s)
        elif kind == "counter":
            out["latest"] = self.latest(name, labels)
            out["delta"] = self.delta(name, labels, window_s)
            out["rate_per_s"] = self.rate(name, labels, window_s)
        else:
            out["count_delta"] = self.delta(name, labels, window_s)
            out["rate_per_s"] = self.rate(name, labels, window_s)
            out["p50"] = self.quantile(name, 50, labels, window_s)
            out["p99"] = self.quantile(name, 99, labels, window_s)
        return out

    def stats(self):
        """The ``/timeseries`` summary payload: the fixed budget and
        how much of it is in use, plus per-series shape (no raw
        points — scrape :meth:`query` for values)."""
        with self._lock:
            series = []
            for (name, lv), ser in sorted(self._series.items()):
                labelnames = self._families[name]["labelnames"]
                series.append({
                    "name": name, "kind": ser.kind,
                    "labels": dict(zip(labelnames, lv)),
                    "points": len(ser.points),
                    "resets": ser.resets,
                    "first_t": ser.points[0][0] if ser.points else None,
                    "last_t": ser.points[-1][0] if ser.points else None,
                })
            return {
                "scrapes": self._scrapes,
                "series": len(self._series),
                "points": sum(len(s.points)
                              for s in self._series.values()),
                "resets": sum(s.resets for s in self._series.values()),
                "dropped_series": self._dropped_series,
                "budget": {"max_points": self.max_points,
                           "retention_seconds": self.retention_s,
                           "max_series": self.max_series},
                "names": series,
            }

    # ------------------------------------------------------------- thread
    def start(self, interval_s=None):
        """Scrape on a daemon thread every ``interval_s`` (default: the
        constructor's).  Strictly opt-in — nothing starts on import or
        construction; the soak harness and tests drive
        :meth:`scrape_once` inline instead."""
        if self._thread is not None:
            return self
        beat = float(interval_s if interval_s is not None
                     else self.interval_s)
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, args=(beat,),
                                        name="timeseries-store",
                                        daemon=True)
        self._thread.start()
        return self

    def _run(self, interval_s):
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception:
                pass    # silent-ok: a flaky scrape must not kill the
                #         loop; the next beat re-reads live state
            self._stop.wait(interval_s)

    def stop(self):
        t, self._thread = self._thread, None
        if t is not None:
            self._stop.set()
            t.join(timeout=5.0)
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
