"""Fleet trace gossip — per-replica trace rings over the TCPStore plane.

The tracer's retention ring is process-local; a failed-over request's
timeline lives *split* across the router's tracer and two replicas'
tracers (more when the fleet is real processes).  Each replica therefore
publishes its bounded completed-trace ring — trace ids are globally
unique (process-nonce-prefixed, see :mod:`.tracing`), so rings merge by
trace_id with zero coordination.  The transport is the same
:class:`~.aggregate.StorePublisher` machinery every per-rank publisher
rides (metric snapshots, heartbeats, prefix summaries): one TCPStore
key per replica, overwritten in place, a daemon thread that survives a
flaky store, nothing started on import.

Correctness note: gossip is *advisory* and staleness-tolerant.  A lost
or stale ring means the fleet view temporarily misses that replica's
segments of a trace — the collector still returns every other segment,
and the next publish heals the view.  Nothing routing- or
serving-critical reads these payloads.

Clock note: spans carry each publisher's own clock values
(``perf_counter`` by default), so cross-process timestamps are only as
comparable as the clocks are.  Each payload carries ``clock_offset_s``
(wall time minus tracer clock at publish) so a consumer that needs one
wall timeline can rebase; the collector itself merges by trace_id and
never rewrites timestamps.

Wiring::

    # each replica process
    TraceRingPublisher(tracer, replica_id=r, store=store).start(1.0)

    # the operator/collector process
    fleet = collect_fleet_traces(store, range(n_replicas))
"""
from __future__ import annotations

import json
import time

from .aggregate import StorePublisher
from .tracing import merge_traces

__all__ = ["TraceRingPublisher", "collect_trace_rings",
           "collect_fleet_traces"]


def _replica_key(prefix, replica_id):
    return f"{prefix}/replica_{int(replica_id)}"


class TraceRingPublisher(StorePublisher):
    """Publish one tracer's completed-trace ring under its fleet key.

    ``publish()`` pushes once; ``start(interval_s)`` runs the inherited
    daemon loop.  ``max_traces`` bounds the payload regardless of the
    tracer's own ring size (the newest traces win the slots — the
    tracer's tail-retention already decided *which* traces those
    are)."""

    def __init__(self, tracer, replica_id, store, key_prefix="traces",
                 max_traces=64, clock=None):
        super().__init__(store, _replica_key(key_prefix, replica_id),
                         clock=clock)
        self.tracer = tracer
        self.replica_id = int(replica_id)
        self.max_traces = int(max_traces)
        self.thread_name = f"trace-gossip-{self.replica_id}"

    def payload(self):
        return {"replica": self.replica_id, "time": self._clock(),
                "clock_offset_s": time.time() - self.tracer.clock(),
                "traces": self.tracer.traces(limit=self.max_traces)}


def collect_trace_rings(store, replica_ids, key_prefix="traces",
                        stale_after_s=None, clock=None):
    """Read every replica's published ring in ONE ``mget`` round trip.
    Returns ``[(source_label, traces)]`` pairs — the
    :func:`~.tracing.merge_traces` input shape.  Replicas that never
    published, published garbage, or whose stamp is older than
    ``stale_after_s`` (publisher wall clock) are simply absent.
    Non-blocking by construction: a scrape never waits on a slow
    store."""
    replica_ids = list(replica_ids)
    keys = [_replica_key(key_prefix, r) for r in replica_ids]
    rings = []
    now = (clock or time.time)()
    for rid, raw in zip(replica_ids, store.mget(keys)):
        if raw is None:
            continue
        try:
            payload = json.loads(raw)
        except (ValueError, TypeError):
            continue            # torn/garbled publish: treat as absent
        if stale_after_s is not None and \
                now - float(payload.get("time") or 0.0) > stale_after_s:
            continue
        traces = payload.get("traces")
        if isinstance(traces, list):
            rings.append((f"replica{int(rid)}", traces))
    return rings


def collect_fleet_traces(store, replica_ids, key_prefix="traces",
                         stale_after_s=None, clock=None,
                         extra_rings=()):
    """The fleet view: every replica's published ring merged by
    trace_id (:func:`~.tracing.merge_traces`) into one trace list
    where a failed-over request is ONE entry whose spans carry their
    source replica.  ``extra_rings`` appends in-process rings — e.g.
    ``[("router", router.tracer.traces())]`` so the dispatch/failover
    segments land in the same merge."""
    rings = collect_trace_rings(store, replica_ids,
                                key_prefix=key_prefix,
                                stale_after_s=stale_after_s, clock=clock)
    return merge_traces(list(extra_rings) + rings)
