"""Request-level tracing — the flight recorder's span model.

Metrics (histograms, counters) answer "how slow is the p95"; they cannot
answer "why was *this* request slow".  A :class:`Tracer` records one
bounded-memory timeline per logical operation — a serving request's full
lifecycle (``queued → admitted → chunk[i] → decode[i] →
finished|evicted|shed``), a training step — as a tree of :class:`Span`\\ s
sharing a ``trace_id``.  Design points:

- **thread-safe, bounded**: spans mutate under the tracer's lock; a
  completed trace (its root span ended) moves into a ring buffer of the
  newest ``max_traces`` traces, so a serving process that handles
  millions of requests holds a constant-size flight record.
- **injectable clock**: the tracer reads time from a ``clock`` callable
  (seconds, ``time.perf_counter`` by default) — the serving engine hands
  its own clock over, so deadline tests drive spans deterministically
  and span timestamps share the engine's timebase.
- **chrome-trace export**: :meth:`Tracer.export_chrome` renders every
  completed trace as one track (``tid`` = trace id, labelled with the
  root span's name) of nested ``"X"`` events via the profiler's
  exporter — the same perf_counter timebase as ``ProfilerStep#N``
  instants, so request timelines and profiler step marks correlate in
  one Perfetto view.
- **JSON export**: :meth:`Tracer.traces` returns completed traces as
  JSON-able dicts — the telemetry server's ``/traces`` payload and the
  bench's embedded trace summary.

Nothing here starts threads or opens sockets; the process-wide
:func:`default_tracer` is a plain object created at import.
"""
from __future__ import annotations

import contextlib
import threading
import time

__all__ = ["Span", "Tracer", "default_tracer", "traces_to_chrome_events"]


class Span:
    """One timed operation inside a trace.

    Created via :meth:`Tracer.start_trace` (root) or
    :meth:`Tracer.start_span` (child); ``end()`` stamps the end time and,
    for a root span, finalizes the whole trace into the tracer's ring
    buffer.  Usable as a context manager.  ``attributes`` is a JSON-able
    dict (page-pool occupancy, batch slot, epoch/step, ...).
    """

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_s",
                 "end_s", "attributes", "_tracer")

    def __init__(self, name, trace_id, span_id, parent_id, start_s,
                 tracer, attributes=None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_s = start_s
        self.end_s = None
        self.attributes = dict(attributes or {})
        self._tracer = tracer

    @property
    def is_root(self):
        return self.parent_id is None

    @property
    def ended(self):
        return self.end_s is not None

    def set_attribute(self, key, value):
        self.attributes[key] = value
        return self

    def set_attributes(self, mapping):
        self.attributes.update(mapping)
        return self

    def end(self, end_s=None):
        self._tracer._end_span(self, end_s)
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.attributes.setdefault("error", repr(exc))
        self.end()
        return False

    def to_dict(self):
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "start_s": self.start_s, "end_s": self.end_s,
                "attributes": dict(self.attributes)}

    def __repr__(self):
        state = "ended" if self.ended else "open"
        return (f"Span({self.name!r}, trace={self.trace_id}, "
                f"span={self.span_id}, {state})")


class Tracer:
    """Span factory + bounded ring buffer of completed traces.

    ``clock`` is a zero-arg callable returning seconds (defaults to
    ``time.perf_counter`` — the profiler's timebase); ``max_traces``
    bounds the completed-trace ring.  A trace completes when its root
    span ends; any still-open child is force-ended at the root's end
    time with ``attributes["unfinished"] = True`` (a crash-truncated
    request still yields a readable timeline).
    """

    def __init__(self, clock=None, max_traces=256):
        self.clock = clock or time.perf_counter
        self.max_traces = int(max_traces)
        self._lock = threading.Lock()
        self._next_trace_id = 1    # guarded-by: self._lock
        self._next_span_id = 1     # guarded-by: self._lock
        # _live: trace_id -> [Span, ...] (root first)
        self._live = {}            # guarded-by: self._lock
        self._completed = []       # ring, oldest first; guarded-by: self._lock
        self._n_completed = 0      # lifetime count; guarded-by: self._lock

    # ---- span lifecycle -------------------------------------------------
    def start_trace(self, name, attributes=None, start_s=None):
        """Open a new trace; returns its root span."""
        with self._lock:
            tid = self._next_trace_id
            self._next_trace_id += 1
            sid = self._next_span_id
            self._next_span_id += 1
            span = Span(name, tid, sid, None,
                        self.clock() if start_s is None else start_s,
                        self, attributes)
            self._live[tid] = [span]
        return span

    def start_span(self, name, parent, attributes=None, start_s=None):
        """Open a child span under ``parent`` (a Span of this tracer)."""
        with self._lock:
            sid = self._next_span_id
            self._next_span_id += 1
            span = Span(name, parent.trace_id, sid, parent.span_id,
                        self.clock() if start_s is None else start_s,
                        self, attributes)
            spans = self._live.get(parent.trace_id)
            if spans is not None:
                spans.append(span)
        return span

    @contextlib.contextmanager
    def trace(self, name, attributes=None):
        """``with tracer.trace("hapi::step", {...}) as span:`` — a whole
        root-span trace scoped to the block."""
        span = self.start_trace(name, attributes)
        try:
            yield span
        except BaseException as e:
            span.attributes.setdefault("error", repr(e))
            raise
        finally:
            span.end()

    @contextlib.contextmanager
    def span(self, name, parent, attributes=None):
        """Child-span context manager."""
        span = self.start_span(name, parent, attributes)
        try:
            yield span
        finally:
            span.end()

    def _end_span(self, span, end_s=None):
        with self._lock:
            if span.ended:
                return
            span.end_s = self.clock() if end_s is None else end_s
            if not span.is_root:
                return
            spans = self._live.pop(span.trace_id, None)
            if spans is None:
                return
            for s in spans:
                if not s.ended:                 # truncated child
                    s.end_s = span.end_s
                    s.attributes["unfinished"] = True
            self._completed.append({
                "trace_id": span.trace_id, "name": span.name,
                "start_s": span.start_s, "end_s": span.end_s,
                "duration_s": span.end_s - span.start_s,
                "spans": [s.to_dict() for s in spans],
            })
            self._n_completed += 1
            if len(self._completed) > self.max_traces:
                del self._completed[:len(self._completed) -
                                    self.max_traces]

    # ---- readers --------------------------------------------------------
    def live_spans(self):
        """Open (in-flight) spans across live traces, as dicts — what a
        hung process was in the middle of.  The hang watchdog's debug
        bundle carries these: a crash-truncated trace never reaches the
        completed ring, so the live view is the only record."""
        with self._lock:
            return [s.to_dict()
                    for spans in self._live.values()
                    for s in spans if not s.ended]

    def traces(self, limit=None):
        """Completed traces (oldest → newest), each a JSON-able dict;
        ``limit`` keeps only the newest N."""
        with self._lock:
            out = list(self._completed)
        if limit is not None:
            out = out[-int(limit):]
        return out

    def summary(self):
        """Aggregate over the ring: lifetime completed count plus
        per-root-name count/total duration — the bench's embedded
        trace digest."""
        # one locked read: the lifetime count and the ring must come
        # from the same instant, or "completed" can lag a trace that
        # "buffered" already shows (racing _end_span)
        with self._lock:
            completed = self._n_completed
            ring = list(self._completed)
        by_name = {}
        for tr in ring:
            # request#N / decode[i] collapse to one aggregate key each
            key = tr["name"].split("#")[0].split("[")[0]
            cnt, tot = by_name.get(key, (0, 0.0))
            by_name[key] = (cnt + 1, tot + tr["duration_s"])
        return {"completed": completed,
                "buffered": len(ring),
                "by_name": {k: {"count": c, "total_s": t}
                            for k, (c, t) in sorted(by_name.items())}}

    def reset(self):
        with self._lock:
            self._live.clear()
            self._completed.clear()
            self._n_completed = 0

    # ---- chrome export --------------------------------------------------
    def export_chrome(self, path, extra_events=()):
        """Write completed traces as chrome-trace JSON, one labelled
        track per trace.  ``extra_events`` (profiler recorder tuples,
        e.g. a drained Profiler's ``_events``) are merged in, so request
        tracks and ``ProfilerStep#N`` instants share the file."""
        from ..profiler.profiler import export_events_chrome

        events, names = traces_to_chrome_events(self.traces())
        export_events_chrome(list(extra_events) + events, path,
                             thread_names=names)
        return path


def traces_to_chrome_events(traces):
    """Lower trace dicts to profiler recorder tuples.

    Returns ``(events, thread_names)``: ``("X", name, start_ns, end_ns,
    tid)`` spans with ``tid`` = trace id (one track per trace) and a
    ``{tid: label}`` map naming each track after its root span."""
    events, names = [], {}
    for tr in traces:
        tid = tr["trace_id"]
        names[tid] = tr["name"]
        for s in tr["spans"]:
            end_s = s["end_s"] if s["end_s"] is not None else s["start_s"]
            events.append(("X", s["name"], int(s["start_s"] * 1e9),
                           int(end_s * 1e9), tid))
    return events, names


_DEFAULT = Tracer()


def default_tracer() -> Tracer:
    """The process-wide tracer: hapi fit steps and default-clock serving
    engines record here, and the telemetry server's ``/traces`` serves
    it (mirrors ``metrics.default_registry``)."""
    return _DEFAULT
