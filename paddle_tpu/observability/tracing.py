"""Request-level tracing — the flight recorder's span model.

Metrics (histograms, counters) answer "how slow is the p95"; they cannot
answer "why was *this* request slow".  A :class:`Tracer` records one
bounded-memory timeline per logical operation — a serving request's full
lifecycle (``queued → admitted → chunk[i] → decode[i] →
finished|evicted|shed``), a training step — as a tree of :class:`Span`\\ s
sharing a ``trace_id``.  Design points:

- **globally unique IDs**: trace and span ids are strings prefixed with
  a per-tracer *nonce* (pid + random bytes), so ids minted by different
  processes — or different tracers in one process — never collide.  The
  fleet trace collector merges per-replica rings **by trace_id**; with
  counter ids every process's "trace 1" would alias.
- **context propagation**: :meth:`Span.context` snapshots a span as a
  :class:`TraceContext` (trace_id + parent span id), and
  :meth:`Tracer.start_trace` accepts ``context=`` to continue a trace
  started elsewhere.  A continued trace records a *segment* in this
  tracer's ring under the original trace_id with its root span parented
  to the remote span — the router's dispatch span, a replica's request
  segment, and the failover re-dispatch all share one trace.
- **tail-based retention**: the completed ring is a *policy* ring, not
  newest-N.  :class:`TailRetention` classifies each finished trace —
  errors, injected faults, shed/evicted/evacuated requests, failovers,
  missed deadlines, above-threshold latency are always retained; boring
  fast traces are probabilistically sampled, and under ring pressure
  sampled entries are evicted before interesting ones.  A soak's worst
  requests stay inspectable after millions of good ones.
- **thread-safe, bounded**: spans mutate under the tracer's lock; a
  completed trace (its segment root ended) moves into the ring of at
  most ``max_traces`` traces, so a serving process that handles
  millions of requests holds a constant-size flight record.
- **injectable clock**: the tracer reads time from a ``clock`` callable
  (seconds, ``time.perf_counter`` by default) — the serving engine hands
  its own clock over, so deadline tests drive spans deterministically
  and span timestamps share the engine's timebase.
- **zero-cost disable**: ``Tracer(enabled=False)`` returns a shared
  no-op span from every ``start_*`` call — no lock, no allocation —
  the bench's "tracing off" baseline.
- **chrome-trace export**: :meth:`Tracer.export_chrome` renders every
  completed trace as one track (labelled with the root span's name) of
  nested ``"X"`` events via the profiler's exporter — the same
  perf_counter timebase as ``ProfilerStep#N`` instants, so request
  timelines and profiler step marks correlate in one Perfetto view.
- **JSON export**: :meth:`Tracer.traces` returns completed traces as
  JSON-able dicts — the telemetry server's ``/traces`` payload and the
  bench's embedded trace summary.

Nothing here starts threads or opens sockets; the process-wide
:func:`default_tracer` is a plain object created at import.
"""
from __future__ import annotations

import contextlib
import os
import random
import threading
import time

__all__ = ["Span", "TraceContext", "TailRetention", "Tracer",
           "default_tracer", "active_span", "activate",
           "active_span_for_thread",
           "traces_to_chrome_events", "merge_traces",
           "export_traces_chrome"]


class TraceContext:
    """The portable identity of a point in a trace: ``trace_id`` plus
    the ``span_id`` new work should parent to.  JSON-able via
    :meth:`to_dict` / :meth:`from_dict`, so it rides request objects,
    store payloads, and failover re-dispatch unchanged across process
    boundaries."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id, span_id=None):
        self.trace_id = trace_id
        self.span_id = span_id

    def to_dict(self):
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_dict(cls, d):
        if d is None:
            return None
        return cls(d.get("trace_id"), d.get("span_id"))

    def __eq__(self, other):
        return (isinstance(other, TraceContext)
                and other.trace_id == self.trace_id
                and other.span_id == self.span_id)

    def __hash__(self):
        return hash((self.trace_id, self.span_id))

    def __repr__(self):
        return f"TraceContext({self.trace_id!r}, {self.span_id!r})"


class Span:
    """One timed operation inside a trace.

    Created via :meth:`Tracer.start_trace` (root) or
    :meth:`Tracer.start_span` (child); ``end()`` stamps the end time and,
    for a segment root, finalizes the whole trace into the tracer's ring
    buffer.  Usable as a context manager.  ``attributes`` is a JSON-able
    dict (page-pool occupancy, batch slot, epoch/step, ...).
    """

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_s",
                 "end_s", "attributes", "_tracer")

    def __init__(self, name, trace_id, span_id, parent_id, start_s,
                 tracer, attributes=None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_s = start_s
        self.end_s = None
        self.attributes = dict(attributes or {})
        self._tracer = tracer

    @property
    def is_root(self):
        return self.parent_id is None

    @property
    def ended(self):
        return self.end_s is not None

    def context(self):
        """This span as a :class:`TraceContext` — hand it to another
        tracer's ``start_trace(context=...)`` (or serialize it across a
        process boundary) to parent further work here."""
        return TraceContext(self.trace_id, self.span_id)

    def set_attribute(self, key, value):
        self.attributes[key] = value
        return self

    def set_attributes(self, mapping):
        self.attributes.update(mapping)
        return self

    def end(self, end_s=None):
        self._tracer._end_span(self, end_s)
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.attributes.setdefault("error", repr(exc))
        self.end()
        return False

    def to_dict(self):
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "start_s": self.start_s, "end_s": self.end_s,
                "attributes": dict(self.attributes)}

    def __repr__(self):
        state = "ended" if self.ended else "open"
        return (f"Span({self.name!r}, trace={self.trace_id}, "
                f"span={self.span_id}, {state})")


class _NullSpan:
    """The shared no-op span a disabled tracer hands out.  Every mutator
    is a no-op; ``attributes`` is a fresh throwaway dict per access so
    callers that ``setdefault`` into it neither crash nor accumulate
    state.  ``context()`` is None — disabled tracing propagates no
    context, and downstream exemplar/attribution code treats that as
    "no trace"."""

    __slots__ = ()

    name = None
    trace_id = None
    span_id = None
    parent_id = None
    start_s = None
    end_s = None
    is_root = False
    ended = True

    @property
    def attributes(self):
        return {}

    def context(self):
        return None

    def set_attribute(self, key, value):
        return self

    def set_attributes(self, mapping):
        return self

    def end(self, end_s=None):
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def to_dict(self):
        return {"name": None, "trace_id": None, "span_id": None,
                "parent_id": None, "start_s": None, "end_s": None,
                "attributes": {}}


_NULL_SPAN = _NullSpan()

# States a request trace can end in that make it unconditionally worth
# keeping: shed (rejected / retry_after), evicted, evacuated, plus the
# blast-radius terminals — failed (per-row isolation pinned an error on
# the request) and quarantined (convicted poison) — the tail the ring
# exists to preserve.
_INTERESTING_STATES = ("rejected", "retry_after", "evicted", "evacuated",
                       "failed", "quarantined")


class TailRetention:
    """Tail-based retention policy for the completed-trace ring.

    ``classify(entry)`` names why a finished trace is interesting
    (``error`` / ``fault`` / its terminal state / ``failover`` /
    ``deadline`` / ``slow`` / ``flagged``) or returns None for a boring
    trace; boring traces are kept with probability ``sample_rate``
    (seeded — runs reproduce).  ``slow_threshold_s=None`` disables the
    latency criterion.  The default policy (``sample_rate=1.0``) keeps
    everything, matching the old newest-N ring for light use."""

    def __init__(self, slow_threshold_s=None, sample_rate=1.0, seed=0):
        self.slow_threshold_s = slow_threshold_s
        self.sample_rate = float(sample_rate)
        # Driven only under the owning tracer's lock (_end_span).
        self._rng = random.Random(seed)

    def classify(self, entry):
        """Retention reason for a completed-trace dict, or None."""
        spans = entry.get("spans") or ()
        for s in spans:
            attrs = s.get("attributes") or {}
            if "error" in attrs:
                return "error"
            if attrs.get("faults"):
                return "fault"
            if attrs.get("retain"):
                return "flagged"
            state = attrs.get("state")
            if state in _INTERESTING_STATES:
                return str(state)
            if attrs.get("redispatches") or attrs.get("redispatched"):
                return "failover"
            if attrs.get("finish_reason") in ("deadline",
                                              "deadline_exceeded"):
                return "deadline"
            if "failover" in (s.get("name") or ""):
                return "failover"
        if self.slow_threshold_s is not None and \
                entry.get("duration_s", 0.0) >= self.slow_threshold_s:
            return "slow"
        return None

    def sample(self):
        """Whether to keep one boring trace (seeded coin flip)."""
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        return self._rng.random() < self.sample_rate


def _new_nonce():
    # pid for debuggability + random bytes so forked twins and multiple
    # tracers inside one process still get distinct prefixes
    return f"{os.getpid():x}-{os.urandom(4).hex()}"


class Tracer:
    """Span factory + bounded policy ring of completed traces.

    ``clock`` is a zero-arg callable returning seconds (defaults to
    ``time.perf_counter`` — the profiler's timebase); ``max_traces``
    bounds the completed-trace ring; ``retention`` is the
    :class:`TailRetention` policy (keep-everything by default);
    ``enabled=False`` turns every ``start_*`` into a lock-free no-op
    returning the shared null span.  ``nonce`` overrides the generated
    id prefix (tests forcing collisions/determinism).

    A trace *segment* completes when its first local span (the segment
    root — a true root, or a ``context=``-continued span) ends; any
    still-open child is force-ended at the root's end time with
    ``attributes["unfinished"] = True`` (a crash-truncated request still
    yields a readable timeline).
    """

    def __init__(self, clock=None, max_traces=256, retention=None,
                 enabled=True, nonce=None):
        self.clock = clock or time.perf_counter
        self.max_traces = int(max_traces)
        self.enabled = bool(enabled)
        self.retention = retention or TailRetention()
        self.nonce = nonce or _new_nonce()
        self._lock = threading.Lock()
        self._next_trace_id = 1    # guarded-by: self._lock
        self._next_span_id = 1     # guarded-by: self._lock
        # _live: trace_id -> [Span, ...] (segment root first)
        self._live = {}            # guarded-by: self._lock
        self._completed = []       # ring, oldest first; guarded-by: self._lock
        self._n_completed = 0      # lifetime count; guarded-by: self._lock
        self._n_dropped = 0        # sampled-out count; guarded-by: self._lock

    # ---- span lifecycle -------------------------------------------------
    def start_trace(self, name, attributes=None, start_s=None,
                    context=None):
        """Open a trace; returns its (segment-)root span.

        With ``context=None`` this mints a fresh globally-unique
        trace_id.  With a :class:`TraceContext` (or its dict form) the
        span *continues* that trace: same trace_id, parented to the
        context's span.  If the context's trace is live in THIS tracer
        the span joins it as an ordinary child; otherwise it roots a new
        local segment that the fleet collector later merges with the
        other processes' segments by trace_id."""
        if not self.enabled:
            return _NULL_SPAN
        if isinstance(context, dict):
            context = TraceContext.from_dict(context)
        with self._lock:
            sid = f"{self.nonce}.s{self._next_span_id}"
            self._next_span_id += 1
            t0 = self.clock() if start_s is None else start_s
            if context is not None and context.trace_id is not None:
                tid = context.trace_id
                span = Span(name, tid, sid, context.span_id, t0, self,
                            attributes)
                spans = self._live.get(tid)
                if spans is not None:
                    spans.append(span)      # joined a live local trace
                else:
                    self._live[tid] = [span]    # new local segment
            else:
                tid = f"{self.nonce}.t{self._next_trace_id}"
                self._next_trace_id += 1
                span = Span(name, tid, sid, None, t0, self, attributes)
                self._live[tid] = [span]
        return span

    def start_span(self, name, parent, attributes=None, start_s=None):
        """Open a child span under ``parent`` (a Span of this tracer)."""
        if not self.enabled or parent is _NULL_SPAN:
            return _NULL_SPAN
        with self._lock:
            sid = f"{self.nonce}.s{self._next_span_id}"
            self._next_span_id += 1
            span = Span(name, parent.trace_id, sid, parent.span_id,
                        self.clock() if start_s is None else start_s,
                        self, attributes)
            spans = self._live.get(parent.trace_id)
            if spans is not None:
                spans.append(span)
        return span

    @contextlib.contextmanager
    def trace(self, name, attributes=None, context=None):
        """``with tracer.trace("hapi::step", {...}) as span:`` — a whole
        root-span trace scoped to the block."""
        span = self.start_trace(name, attributes, context=context)
        try:
            yield span
        except BaseException as e:
            span.attributes.setdefault("error", repr(e))
            raise
        finally:
            span.end()

    @contextlib.contextmanager
    def span(self, name, parent, attributes=None):
        """Child-span context manager."""
        span = self.start_span(name, parent, attributes)
        try:
            yield span
        finally:
            span.end()

    def _end_span(self, span, end_s=None):
        with self._lock:
            if span.ended:
                return
            span.end_s = self.clock() if end_s is None else end_s
            spans = self._live.get(span.trace_id)
            if spans is None or spans[0] is not span:
                return              # a child ended; segment still open
            self._live.pop(span.trace_id)
            for s in spans:
                if not s.ended:                 # truncated child
                    s.end_s = span.end_s
                    s.attributes["unfinished"] = True
            entry = {
                "trace_id": span.trace_id, "name": span.name,
                "start_s": span.start_s, "end_s": span.end_s,
                "duration_s": span.end_s - span.start_s,
                "spans": [s.to_dict() for s in spans],
            }
            self._n_completed += 1
            reason = self.retention.classify(entry)
            if reason is None:
                if not self.retention.sample():
                    self._n_dropped += 1
                    return
                reason = "sampled"
            entry["retained"] = reason
            self._completed.append(entry)
            while len(self._completed) > self.max_traces:
                self._evict_one_locked()

    def _evict_one_locked(self):
        # guarded-by: self._lock (called from _end_span only).  Policy:
        # the oldest *sampled* (boring) entry goes first; only when the
        # whole ring is interesting does the oldest interesting one go.
        for i, tr in enumerate(self._completed):
            if tr.get("retained") == "sampled":
                del self._completed[i]
                return
        del self._completed[0]

    # ---- readers --------------------------------------------------------
    def live_spans(self):
        """Open (in-flight) spans across live traces, as dicts — what a
        hung process was in the middle of.  The hang watchdog's debug
        bundle carries these: a crash-truncated trace never reaches the
        completed ring, so the live view is the only record."""
        with self._lock:
            return [s.to_dict()
                    for spans in self._live.values()
                    for s in spans if not s.ended]

    def traces(self, limit=None):
        """Completed traces (oldest → newest), each a JSON-able dict;
        ``limit`` keeps only the newest N."""
        with self._lock:
            out = list(self._completed)
        if limit is not None:
            out = out[-int(limit):]
        return out

    def summary(self):
        """Aggregate over the ring: lifetime completed count plus
        per-root-name count/total duration — the bench's embedded
        trace digest."""
        # one locked read: the lifetime count and the ring must come
        # from the same instant, or "completed" can lag a trace that
        # "buffered" already shows (racing _end_span)
        with self._lock:
            completed = self._n_completed
            dropped = self._n_dropped
            ring = list(self._completed)
        by_name, by_reason = {}, {}
        for tr in ring:
            # request#N / decode[i] collapse to one aggregate key each
            key = tr["name"].split("#")[0].split("[")[0]
            cnt, tot = by_name.get(key, (0, 0.0))
            by_name[key] = (cnt + 1, tot + tr["duration_s"])
            reason = tr.get("retained", "sampled")
            by_reason[reason] = by_reason.get(reason, 0) + 1
        return {"completed": completed,
                "buffered": len(ring),
                "dropped": dropped,
                "by_name": {k: {"count": c, "total_s": t}
                            for k, (c, t) in sorted(by_name.items())},
                "retained_by_reason": dict(sorted(by_reason.items()))}

    def reset(self):
        with self._lock:
            self._live.clear()
            self._completed.clear()
            self._n_completed = 0
            self._n_dropped = 0

    # ---- chrome export --------------------------------------------------
    def export_chrome(self, path, extra_events=()):
        """Write completed traces as chrome-trace JSON, one labelled
        track per trace.  ``extra_events`` (profiler recorder tuples,
        e.g. a drained Profiler's ``_events``) are merged in, so request
        tracks and ``ProfilerStep#N`` instants share the file."""
        from ..profiler.profiler import export_events_chrome

        events, names = traces_to_chrome_events(self.traces())
        export_events_chrome(list(extra_events) + events, path,
                             thread_names=names)
        return path


# ---- active-span ambient context ---------------------------------------
_ACTIVE = threading.local()

# tid -> that thread's activation stack (the SAME list object as its
# _ACTIVE.stack).  threading.local cannot be enumerated from another
# thread, but the sampling profiler must read every thread's ambient
# span; this registry is the cross-thread view.  Mutated only by the
# owning thread with GIL-atomic dict ops; readers tolerate a raced
# pop (one misattributed sample, never corruption).
_ACTIVE_STACKS = {}


def active_span():
    """The innermost span activated on this thread via :func:`activate`
    (None outside any activation).  Instrumentation that cannot thread a
    span through its call path — fault injection, deep library hooks —
    reads the ambient span here."""
    stack = getattr(_ACTIVE, "stack", None)
    return stack[-1] if stack else None


def active_span_for_thread(tid):
    """The innermost span thread ``tid`` currently has activated, or
    None — the sampling profiler's cross-thread attribution read.  Best
    effort by design: the owning thread may pop concurrently."""
    stack = _ACTIVE_STACKS.get(tid)
    if not stack:
        return None
    try:
        return stack[-1]
    except IndexError:      # raced the owning thread's deactivation
        return None


@contextlib.contextmanager
def activate(span):
    """Make ``span`` the thread's ambient span for the block, so
    :func:`active_span` callers underneath (e.g. a firing fault point)
    can attach events to it without plumbing."""
    stack = _ACTIVE.__dict__.setdefault("stack", [])
    tid = threading.get_ident()
    _ACTIVE_STACKS[tid] = stack     # idempotent re-registration
    stack.append(span)
    try:
        yield span
    finally:
        stack.pop()
        if not stack:
            # drop the registry entry so a dead (or reused) thread id
            # never shows a stale stack
            _ACTIVE_STACKS.pop(tid, None)


# ---- merging + export ----------------------------------------------------
def merge_traces(rings):
    """Merge per-source trace rings into one fleet view, grouped by
    trace_id.  ``rings`` is an iterable of ``(source_label, traces)``
    pairs (each ``traces`` a :meth:`Tracer.traces`-shaped list).  A
    trace that crossed sources — router dispatch, first replica,
    failover, second replica — comes back as ONE entry whose ``spans``
    carry a ``source`` field, whose window is the union of its
    segments', and whose ``name``/``retained`` come from the
    originating segment (the one whose root has no remote parent) with
    the strongest retention reason winning over ``sampled``.  Ordering:
    by merged start time, ties by trace_id."""
    merged = {}
    for source, traces in rings:
        for tr in traces or ():
            tid = tr.get("trace_id")
            m = merged.get(tid)
            if m is None:
                m = merged[tid] = {
                    "trace_id": tid, "name": tr.get("name"),
                    "start_s": tr.get("start_s"),
                    "end_s": tr.get("end_s"),
                    "spans": [], "segments": [],
                    "retained": tr.get("retained", "sampled"),
                }
            seg_spans = tr.get("spans") or ()
            local_ids = {s.get("span_id") for s in seg_spans}
            # originating segment: its root's parent is not a span of
            # any segment — approximated per-segment as "root has no
            # parent at all"
            seg_root = seg_spans[0] if seg_spans else None
            if seg_root is not None and seg_root.get("parent_id") is None:
                m["name"] = tr.get("name")
            for s in seg_spans:
                d = dict(s)
                d["source"] = source
                m["spans"].append(d)
            m["segments"].append({
                "source": source, "name": tr.get("name"),
                "start_s": tr.get("start_s"), "end_s": tr.get("end_s"),
                "root_local": (seg_root is not None
                               and seg_root.get("parent_id") is None),
                "n_spans": len(local_ids),
            })
            for key, pick in (("start_s", min), ("end_s", max)):
                a, b = m[key], tr.get(key)
                if b is not None:
                    m[key] = b if a is None else pick(a, b)
            if m["retained"] == "sampled" and \
                    tr.get("retained", "sampled") != "sampled":
                m["retained"] = tr.get("retained")
    out = []
    for m in merged.values():
        if m["start_s"] is not None and m["end_s"] is not None:
            m["duration_s"] = m["end_s"] - m["start_s"]
        else:
            m["duration_s"] = None
        m["spans"].sort(key=lambda s: (s.get("start_s") or 0.0))
        out.append(m)
    out.sort(key=lambda m: (m["start_s"] or 0.0, str(m["trace_id"])))
    return out


def traces_to_chrome_events(traces):
    """Lower trace dicts to profiler recorder tuples.

    Returns ``(events, thread_names)``: ``("X", name, start_ns, end_ns,
    tid)`` spans with one integer track per trace (trace ids are
    strings; the chrome exporter sorts tids, so they are enumerated)
    and a ``{tid: label}`` map naming each track after its root span.
    Spans carrying a ``source`` (merged fleet traces) keep it in the
    event name, so a failed-over request reads ``router: dispatch →
    replica0: decode → replica1: decode`` on one track."""
    events, names, tids = [], {}, {}
    for tr in traces:
        tid = tids.setdefault(tr["trace_id"], len(tids) + 1)
        names[tid] = tr["name"]
        for s in tr["spans"]:
            end_s = s["end_s"] if s["end_s"] is not None else s["start_s"]
            label = s["name"]
            if s.get("source") is not None:
                label = f"{s['source']}: {label}"
            events.append(("X", label, int(s["start_s"] * 1e9),
                           int(end_s * 1e9), tid))
    return events, names


def export_traces_chrome(traces, path, extra_events=()):
    """Write an arbitrary trace list (e.g. a merged fleet view) as
    chrome-trace JSON — the function behind the fleet collector's
    one-track-per-request timeline."""
    from ..profiler.profiler import export_events_chrome

    events, names = traces_to_chrome_events(traces)
    export_events_chrome(list(extra_events) + events, path,
                         thread_names=names)
    return path


_DEFAULT = Tracer()


def default_tracer() -> Tracer:
    """The process-wide tracer: hapi fit steps and default-clock serving
    engines record here, and the telemetry server's ``/traces`` serves
    it (mirrors ``metrics.default_registry``)."""
    return _DEFAULT
