"""ONNX export (reference: python/paddle/onnx/export.py over paddle2onnx).

Design decision (documented, deliberate): the portable serving artifact
of this framework is the **versioned StableHLO program** produced by
``jit.save``/``jax.export`` — it replays on any XLA runtime (TPU, GPU,
CPU) with the calling convention embedded, and is what the Predictor
(inference/) and the reference-parity ``jit.load`` consume.  An ONNX
emitter would re-introduce the op-by-op converter matrix (paddle2onnx
maintains ~200 converters against a GPU-centric opset) for no TPU-side
gain.  ``paddle_tpu.onnx.export`` therefore produces the StableHLO
artifact at the requested path and says so; consumers that genuinely
need ``.onnx`` convert offline from StableHLO with third-party tooling.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=None, **configs):
    """paddle.onnx.export signature parity; emits the StableHLO artifact
    (see module docstring for why).  Returns the artifact prefix."""
    from ..jit import save as jit_save

    if input_spec is None:
        raise ValueError(
            "onnx.export needs input_spec (example inputs) to trace the "
            "program — same requirement as the reference exporter")
    if path.endswith(".onnx"):
        path = path[:-5]
    jit_save(layer, path, example_inputs=list(input_spec))
    return path
