"""Op corpus.

TPU-native replacement for the reference's operator layers
(paddle/fluid/operators + paddle/phi/kernels + the generated
paddle::experimental C++ API from python/paddle/utils/code_gen/api.yaml).
Every op here is a pure jax function registered through
core.dispatch.register_op, giving it the eager autograd wrapper and a
registry entry for the OpTest conformance harness.
"""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .reduction import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .activation import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .random_ops import *  # noqa: F401,F403
from .nn_ops import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403

from ..core.dispatch import OP_REGISTRY, get_op, list_ops  # noqa: F401
