"""Activation ops (parity: python/paddle/nn/functional/activation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import register_op


@register_op("relu")
def relu(x):
    return jnp.maximum(x, 0)


@register_op("relu6")
def relu6(x):
    return jnp.clip(x, 0, 6)


@register_op("leaky_relu")
def leaky_relu(x, negative_slope=0.01):
    return jnp.where(x >= 0, x, negative_slope * x)


@register_op("prelu")
def prelu(x, weight):
    return jnp.where(x >= 0, x, weight * x)


@register_op("elu")
def elu(x, alpha=1.0):
    return jnp.where(x > 0, x, alpha * jnp.expm1(x))


@register_op("selu")
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@register_op("celu")
def celu(x, alpha=1.0):
    return jnp.maximum(x, 0) + jnp.minimum(0, alpha * jnp.expm1(x / alpha))


@register_op("gelu")
def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


@register_op("silu")
def silu(x):
    return jax.nn.silu(x)


swish = silu


@register_op("hardswish")
def hardswish(x):
    return x * jnp.clip(x + 3, 0, 6) / 6


@register_op("hardsigmoid")
def hardsigmoid(x, slope=1.0 / 6.0, offset=0.5):
    return jnp.clip(slope * x + offset, 0, 1)


@register_op("hardtanh")
def hardtanh(x, min=-1.0, max=1.0):  # noqa: A002
    return jnp.clip(x, min, max)


@register_op("hardshrink")
def hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0)


@register_op("softshrink")
def softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0))


@register_op("tanhshrink")
def tanhshrink(x):
    return x - jnp.tanh(x)


@register_op("thresholded_relu")
def thresholded_relu(x, threshold=1.0):
    return jnp.where(x > threshold, x, 0)


@register_op("log_sigmoid")
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


@register_op("maxout")
def maxout(x, groups, axis=1):
    shape = list(x.shape)
    c = shape[axis]
    shape[axis : axis + 1] = [c // groups, groups]
    return jnp.max(x.reshape(tuple(shape)), axis=axis + 1)


@register_op("softmax")
def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


@register_op("log_softmax")
def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


@register_op("softsign")
def softsign(x):
    return x / (1 + jnp.abs(x))


@register_op("mish")
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@register_op("glu")
def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


@register_op("gumbel_softmax")
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, key=None):
    if key is None:
        from ..core.random import split_key

        key = split_key()
    g = jax.random.gumbel(key, x.shape, dtype=x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        # straight-through: hard one-hot forward, soft gradient backward
        y_hard = jax.nn.one_hot(
            jnp.argmax(y, axis=axis), y.shape[axis], dtype=y.dtype, axis=axis
        )
        y = y + jax.lax.stop_gradient(y_hard - y)
    return y
