"""Attention ops.

Parity: the reference's fused attention stack
(paddle/fluid/operators/fused/fused_attention_op.cu,
fused_multi_transformer_op.cu) — rebuilt TPU-first: the hot path is a Pallas
flash-attention kernel (paddle_tpu/kernels/flash_attention.py); the reference
semantics (naive softmax(QK^T)V) remain as the XLA fallback that also serves
CPU tests.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import register_op


def _naive_attention(q, k, v, mask=None, dropout_p=0.0, causal=False, scale=None,
                     training=True, key=None):
    # q,k,v: [batch, heads, seq, head_dim]
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    from .linalg import mxu_precision

    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32,
        precision=mxu_precision(q, k)
    ) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(causal_mask, logits, -1e30)
    if mask is not None:
        logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training:
        if key is None:
            from ..core.random import split_key

            key = split_key()
        keep = 1.0 - dropout_p
        drop_mask = jax.random.bernoulli(key, p=keep, shape=probs.shape)
        probs = jnp.where(drop_mask, probs / keep, 0.0).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v,
                      precision=mxu_precision(probs, v))


@register_op("scaled_dot_product_attention")
def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, scale=None, training=True,
                                 use_flash=True):
    """q/k/v: [batch, heads, seq, head_dim].

    Dispatches to the Pallas flash-attention kernel on TPU when shapes allow,
    else the XLA softmax path (which XLA still fuses well).  Attention
    dropout forces the naive path (the flash kernel is dropout-free, like the
    reference's fused_attention fast path).
    """
    if use_flash and (dropout_p == 0.0 or not training):
        try:
            from ..kernels.flash_attention import flash_attention_available, flash_attention

            if flash_attention_available(q, k, v, attn_mask, causal=is_causal):
                return flash_attention(q, k, v, causal=is_causal, scale=scale)
        except ImportError:
            pass
    return _naive_attention(q, k, v, mask=attn_mask, dropout_p=dropout_p,
                            causal=is_causal, scale=scale, training=training)
