"""Tensor creation ops (parity: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Tensor


def _dt(dtype, default_float=True):
    if dtype is None:
        return get_default_dtype() if default_float else jnp.int64
    return convert_dtype(dtype)


@register_op("zeros", differentiable=False)
def zeros(shape, dtype=None):
    return jnp.zeros(tuple(shape), dtype=_dt(dtype))


@register_op("ones", differentiable=False)
def ones(shape, dtype=None):
    return jnp.ones(tuple(shape), dtype=_dt(dtype))


@register_op("full", differentiable=False)
def full(shape, fill_value, dtype=None):
    return jnp.full(tuple(shape), fill_value, dtype=_dt(dtype))


@register_op("zeros_like")
def zeros_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=convert_dtype(dtype))


@register_op("ones_like")
def ones_like(x, dtype=None):
    return jnp.ones_like(x, dtype=convert_dtype(dtype))


@register_op("full_like")
def full_like(x, fill_value, dtype=None):
    return jnp.full_like(x, fill_value, dtype=convert_dtype(dtype))


@register_op("arange", differentiable=False)
def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    return jnp.arange(start, end, step, dtype=convert_dtype(dtype))


@register_op("linspace", differentiable=False)
def linspace(start, stop, num, dtype=None):
    return jnp.linspace(start, stop, int(num), dtype=_dt(dtype))


@register_op("eye", differentiable=False)
def eye(num_rows, num_columns=None, dtype=None):
    return jnp.eye(num_rows, num_columns, dtype=_dt(dtype))


@register_op("diag")
def diag(x, offset=0):
    return jnp.diag(x, k=offset)


@register_op("diagflat")
def diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


@register_op("tril")
def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@register_op("triu")
def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


@register_op("meshgrid")
def meshgrid(*args):
    return tuple(jnp.meshgrid(*args, indexing="ij"))


@register_op("assign")
def assign(x):
    return jnp.asarray(x)


@register_op("clone")
def clone(x):
    return jnp.asarray(x)


@register_op("empty", differentiable=False)
def empty(shape, dtype=None):
    return jnp.zeros(tuple(shape), dtype=_dt(dtype))


@register_op("empty_like", differentiable=False)
def empty_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=convert_dtype(dtype))


@register_op("complex")
def complex(real, imag):  # noqa: A001
    return jax_lax_complex(real, imag)


def jax_lax_complex(real, imag):
    import jax.lax as lax

    return lax.complex(real, imag)


def tensor_ctor(data, dtype=None, place=None, stop_gradient=True):
    from ..core.tensor import to_tensor

    return to_tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
