"""Linear algebra ops (parity: python/paddle/tensor/linalg.py).

Matmuls go straight to the MXU via lax.dot_general; ``preferred_element_type``
keeps accumulation in fp32 when operands are bf16.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import register_op


def mxu_precision(*arrays):
    """MXU precision policy: f32 operands get true-f32 accuracy (multi-pass);
    bf16 operands use the native bf16-multiply/f32-accumulate path, which is
    the fast mode this framework's AMP targets."""
    for a in arrays:
        if hasattr(a, "dtype") and a.dtype == jnp.float32:
            return jax.lax.Precision.HIGHEST
    return None


@register_op("matmul")
def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim >= 2 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim >= 2 else y
    pet = jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else None
    out = jnp.matmul(x, y, preferred_element_type=pet,
                     precision=mxu_precision(x, y))
    return out.astype(x.dtype) if pet is not None else out


@register_op("mm")
def mm(x, y):
    return jnp.matmul(x, y, precision=mxu_precision(x, y))


@register_op("bmm")
def bmm(x, y):
    return jnp.matmul(x, y, precision=mxu_precision(x, y))


@register_op("dot")
def dot(x, y):
    return jnp.sum(x * y, axis=-1)


@register_op("inner")
def inner(x, y):
    return jnp.inner(x, y)


@register_op("outer")
def outer(x, y):
    return jnp.outer(x, y)


@register_op("cross")
def cross(x, y, axis=None):
    if axis is None:
        axis = -1
        for i, s in enumerate(x.shape):
            if s == 3:
                axis = i
                break
    return jnp.cross(x, y, axis=axis)


@register_op("t")
def t(x):
    if x.ndim < 2:
        return x
    return jnp.swapaxes(x, -1, -2)


@register_op("norm")
def norm(x, p="fro", axis=None, keepdim=False):
    if p == "fro":
        if axis is None:
            return jnp.sqrt(jnp.sum(jnp.square(x)))
        return jnp.linalg.norm(x, ord="fro" if isinstance(axis, (tuple, list)) else None,
                               axis=tuple(axis) if isinstance(axis, list) else axis,
                               keepdims=keepdim)
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)


@register_op("dist")
def dist(x, y, p=2.0):
    d = x - y
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == 0:
        return jnp.sum(d != 0).astype(x.dtype)
    return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)


@register_op("trace_op")
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@register_op("kron")
def kron(x, y):
    return jnp.kron(x, y)


@register_op("matrix_power")
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


@register_op("inverse")
def inverse(x):
    return jnp.linalg.inv(x)


@register_op("pinv")
def pinv(x, rcond=1e-15):
    return jnp.linalg.pinv(x, rtol=rcond)


@register_op("det")
def det(x):
    return jnp.linalg.det(x)


@register_op("slogdet")
def slogdet(x):
    sign, logabs = jnp.linalg.slogdet(x)
    return sign, logabs


@register_op("cholesky")
def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


@register_op("qr")
def qr(x, mode="reduced"):
    q, r = jnp.linalg.qr(x, mode=mode)
    return q, r


@register_op("svd")
def svd(x, full_matrices=False):
    u, s, vh = jnp.linalg.svd(x, full_matrices=full_matrices)
    return u, s, vh


@register_op("eigh")
def eigh(x, UPLO="L"):
    w, v = jnp.linalg.eigh(x, UPLO=UPLO)
    return w, v


@register_op("eigvalsh")
def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@register_op("matrix_rank", differentiable=False)
def matrix_rank(x, tol=None):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@register_op("solve")
def solve(x, y):
    return jnp.linalg.solve(x, y)


@register_op("triangular_solve")
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@register_op("lstsq")
def lstsq(x, y, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@register_op("multi_dot")
def multi_dot(xs):
    return jnp.linalg.multi_dot(list(xs))


@register_op("einsum")
def einsum(equation, *operands):
    return jnp.einsum(equation, *operands,
                      precision=mxu_precision(*operands))


@register_op("mv")
def mv(x, vec):
    return jnp.matmul(x, vec, precision=mxu_precision(x, vec))


@register_op("histogram", differentiable=False)
def histogram(x, bins=100, min=0, max=0):  # noqa: A002
    lo, hi = (min, max) if (min != 0 or max != 0) else (None, None)
    if lo is None:
        lo = jnp.min(x)
        hi = jnp.max(x)
    hist, _ = jnp.histogram(x, bins=bins, range=(lo, hi))
    return hist


@register_op("bincount", differentiable=False)
def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=minlength,
                        length=None)
