"""Loss ops (parity: python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import register_op


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@register_op("cross_entropy")
def cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                  reduction="mean", axis=-1, weight=None, use_softmax=True):
    if use_softmax:
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
    else:
        logp = jnp.log(jnp.maximum(logits.astype(jnp.float32), 1e-30))
    if soft_label:
        if weight is not None:
            logp = logp * weight  # per-class weights broadcast over the axis
        loss = -jnp.sum(label * logp, axis=axis)
    else:
        label = label.astype(jnp.int32)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(jnp.maximum(label, 0), axis), axis=axis)
        loss = -jnp.squeeze(picked, axis)
        valid = label != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if weight is not None:
            w = jnp.take(weight, jnp.maximum(label, 0))
            loss = loss * jnp.where(valid, w, 0.0)
        if reduction == "mean":
            if weight is not None:
                denom = jnp.maximum(jnp.sum(jnp.where(valid, jnp.take(weight, jnp.maximum(label, 0)), 0.0)), 1e-12)
            else:
                denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
            return jnp.sum(loss) / denom
    return _reduce(loss, reduction)


@register_op("softmax_with_cross_entropy")
def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lbl = label.astype(jnp.int32)
        if lbl.ndim == logits.ndim:
            lbl = jnp.squeeze(lbl, axis)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(jnp.maximum(lbl, 0), axis), axis=axis)
        loss = -picked
        loss = jnp.where(jnp.expand_dims(lbl, axis) != ignore_index, loss, 0.0)
    if return_softmax:
        return loss, jax.nn.softmax(logits, axis=axis)
    return loss


@register_op("nll_loss")
def nll_loss(log_prob, label, weight=None, ignore_index=-100, reduction="mean"):
    picked = jnp.take_along_axis(
        log_prob, jnp.expand_dims(jnp.maximum(label, 0), -1), axis=-1)
    loss = -jnp.squeeze(picked, -1)
    valid = label != ignore_index
    loss = jnp.where(valid, loss, 0.0)
    if weight is not None:
        loss = loss * jnp.take(weight, jnp.maximum(label, 0))
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    return _reduce(loss, reduction)


@register_op("mse_loss")
def mse_loss(input, label, reduction="mean"):  # noqa: A002
    return _reduce(jnp.square(input - label), reduction)


@register_op("l1_loss")
def l1_loss(input, label, reduction="mean"):  # noqa: A002
    return _reduce(jnp.abs(input - label), reduction)


@register_op("smooth_l1_loss")
def smooth_l1_loss(input, label, reduction="mean", delta=1.0):  # noqa: A002
    d = jnp.abs(input - label)
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce(loss, reduction)


@register_op("binary_cross_entropy")
def binary_cross_entropy(input, label, weight=None, reduction="mean"):  # noqa: A002
    eps = 1e-12
    loss = -(label * jnp.log(jnp.maximum(input, eps)) +
             (1 - label) * jnp.log(jnp.maximum(1 - input, eps)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@register_op("binary_cross_entropy_with_logits")
def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    max_val = jnp.maximum(-logit, 0)
    if pos_weight is not None:
        log_w = (pos_weight - 1) * label + 1
        loss = (1 - label) * logit + log_w * (
            jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)
    else:
        loss = (1 - label) * logit + jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@register_op("kl_div")
def kl_div(input, label, reduction="mean"):  # noqa: A002
    loss = label * (jnp.log(jnp.maximum(label, 1e-12)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


@register_op("margin_ranking_loss")
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):  # noqa: A002
    loss = jnp.maximum(-label * (input - other) + margin, 0)
    return _reduce(loss, reduction)


@register_op("hinge_embedding_loss")
def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):  # noqa: A002
    loss = jnp.where(label == 1, input, jnp.maximum(margin - input, 0))
    return _reduce(loss, reduction)


@register_op("cosine_similarity")
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.maximum(jnp.sum(x1 * x1, axis=axis), eps * eps))
    n2 = jnp.sqrt(jnp.maximum(jnp.sum(x2 * x2, axis=axis), eps * eps))
    return dot / (n1 * n2)


@register_op("square_error_cost")
def square_error_cost(input, label):  # noqa: A002
    return jnp.square(input - label)


@register_op("sigmoid_focal_loss")
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum"):
    p = jax.nn.sigmoid(logit)
    ce = (1 - label) * logit + jnp.log1p(jnp.exp(-jnp.abs(logit))) + jnp.maximum(-logit, 0)
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    loss = a_t * jnp.power(1 - p_t, gamma) * ce
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


@register_op("label_smooth")
def label_smooth(label, prior_dist=None, epsilon=0.1):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k


@register_op("ctc_loss")
def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Connectionist Temporal Classification loss (reference:
    paddle/fluid/operators/warpctc_op.cc wrapping warp-ctc;
    paddle.nn.functional.ctc_loss semantics).

    log_probs: [T, B, C] log-softmax'd frame predictions; labels: [B, S]
    int targets (padded arbitrarily past label_lengths); input_lengths /
    label_lengths: [B].  Returns per-sample negative log likelihood
    ([B]; reduced per ``reduction``, mean = sum/label_len then mean like
    the reference).

    TPU-first: the alpha recursion runs as ONE lax.scan over time on the
    extended-label lattice [B, 2S+1] in log space — no Python loop, no
    data-dependent shapes (length masking freezes alpha past
    input_length).
    """
    lp = log_probs if not hasattr(log_probs, "data") else log_probs.data
    lp = jnp.asarray(lp, jnp.float32)
    lab = jnp.asarray(labels if not hasattr(labels, "data")
                      else labels.data, jnp.int32)
    T, B, C = lp.shape
    S = lab.shape[1]
    in_len = jnp.asarray(input_lengths if not hasattr(input_lengths, "data")
                         else input_lengths.data, jnp.int32)
    lab_len = jnp.asarray(label_lengths if not hasattr(label_lengths, "data")
                          else label_lengths.data, jnp.int32)

    NEG = -1e30
    # extended labels: blank, l1, blank, l2, ..., blank  -> [B, 2S+1]
    L = 2 * S + 1
    pos = jnp.arange(L)
    ext = jnp.where(pos % 2 == 0, blank,
                    lab[:, jnp.minimum(pos // 2, S - 1)])
    ext_len = 2 * lab_len + 1

    # skip transition (i-2 -> i) allowed where ext[i] != blank and
    # ext[i] != ext[i-2]
    ext_m2 = jnp.concatenate(
        [jnp.full((B, 2), blank, jnp.int32), ext[:, :-2]], axis=1)
    can_skip = (pos[None, :] % 2 == 1) & (ext != ext_m2) & (pos[None, :] >= 2)

    def emit(t):
        return jnp.take_along_axis(lp[t], ext, axis=1)     # [B, L]

    alpha0 = jnp.full((B, L), NEG)
    alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
    first_lab = jnp.take_along_axis(lp[0], lab[:, :1], axis=1)[:, 0]
    alpha0 = alpha0.at[:, 1].set(jnp.where(lab_len > 0, first_lab, NEG))

    def lse(*xs):
        st = jnp.stack(xs, 0)
        m = jnp.max(st, 0)
        safe = jnp.where(m <= NEG / 2, NEG, m)
        return jnp.where(
            m <= NEG / 2, NEG,
            safe + jnp.log(jnp.sum(jnp.exp(st - safe), 0)))

    def step(alpha, t):
        prev1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], 1)
        prev2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], 1)
        prev2 = jnp.where(can_skip, prev2, NEG)
        a = lse(alpha, prev1, prev2) + emit(t)
        # past this sample's input length the lattice freezes
        live = (t < in_len)[:, None]
        a = jnp.where(live, a, alpha)
        return a, None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
    # NLL: logsumexp of the last two lattice positions at t = in_len - 1
    last = jnp.take_along_axis(alpha, (ext_len - 1)[:, None], 1)[:, 0]
    last2 = jnp.take_along_axis(
        alpha, jnp.maximum(ext_len - 2, 0)[:, None], 1)[:, 0]
    nll = -lse(last, jnp.where(lab_len > 0, last2, NEG))
    if norm_by_times:
        nll = nll / jnp.maximum(in_len.astype(jnp.float32), 1.0)
    if reduction == "none":
        return nll
    if reduction == "sum":
        return nll.sum()
    # 'mean': divide each sample by its label length, then batch-mean
    # (paddle/torch zero_infinity=False semantics)
    return (nll / jnp.maximum(lab_len.astype(jnp.float32), 1.0)).mean()
