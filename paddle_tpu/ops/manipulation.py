"""Shape/layout manipulation ops (parity: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.dtype import convert_dtype


@register_op("cast")
def cast(x, dtype):
    return x.astype(convert_dtype(dtype))


@register_op("reshape")
def reshape(x, shape):
    return jnp.reshape(x, tuple(int(s) for s in shape))


@register_op("flatten")
def flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return jnp.reshape(x, (1,))
    start = start_axis % nd
    stop = stop_axis % nd
    shape = list(x.shape)
    merged = 1
    for s in shape[start : stop + 1]:
        merged *= s
    new_shape = shape[:start] + [merged] + shape[stop + 1 :]
    return jnp.reshape(x, tuple(new_shape))


@register_op("transpose")
def transpose(x, perm):
    return jnp.transpose(x, axes=tuple(perm))


@register_op("transpose_last2")
def transpose_last2(x):
    if x.ndim < 2:
        return x
    perm = list(range(x.ndim))
    perm[-1], perm[-2] = perm[-2], perm[-1]
    return jnp.transpose(x, axes=perm)


@register_op("moveaxis")
def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


@register_op("swapaxes")
def swapaxes(x, axis0, axis1):
    return jnp.swapaxes(x, axis0, axis1)


@register_op("unsqueeze")
def unsqueeze(x, axis):
    if isinstance(axis, (list, tuple)):
        out = x
        for a in sorted(axis):
            out = jnp.expand_dims(out, a)
        return out
    return jnp.expand_dims(x, axis)


@register_op("squeeze")
def squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, (list, tuple)):
        axes = tuple(a for a in axis if x.shape[a] == 1)
        return jnp.squeeze(x, axis=axes) if axes else x
    if x.shape[axis] != 1:
        return x
    return jnp.squeeze(x, axis=axis)


@register_op("concat")
def concat(xs, axis=0):
    return jnp.concatenate(list(xs), axis=axis)


@register_op("stack")
def stack(xs, axis=0):
    return jnp.stack(list(xs), axis=axis)


@register_op("unstack")
def unstack(x, axis=0, num=None):
    n = num if num is not None else x.shape[axis]
    return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis))


@register_op("split")
def split(x, num_or_sections, axis=0):
    if isinstance(num_or_sections, int):
        return tuple(jnp.split(x, num_or_sections, axis=axis))
    sections = list(num_or_sections)
    total = x.shape[axis]
    # paddle allows one -1 entry
    if -1 in sections:
        known = sum(s for s in sections if s != -1)
        sections[sections.index(-1)] = total - known
    offsets, acc = [], 0
    for s in sections[:-1]:
        acc += s
        offsets.append(acc)
    return tuple(jnp.split(x, offsets, axis=axis))


@register_op("chunk")
def chunk(x, chunks, axis=0):
    return tuple(jnp.split(x, chunks, axis=axis))


@register_op("tile")
def tile(x, repeat_times):
    return jnp.tile(x, tuple(repeat_times))


@register_op("expand")
def expand(x, shape):
    shape = list(shape)
    # -1 means keep this dim
    x_shape = [1] * (len(shape) - x.ndim) + list(x.shape)
    out_shape = tuple(
        x_shape[i] if s == -1 else int(s) for i, s in enumerate(shape)
    )
    return jnp.broadcast_to(x.reshape(tuple(x_shape)), out_shape)


@register_op("expand_as")
def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


@register_op("broadcast_to")
def broadcast_to(x, shape):
    return jnp.broadcast_to(x, tuple(shape))


@register_op("flip")
def flip(x, axis):
    return jnp.flip(x, axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis)


@register_op("roll")
def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


@register_op("rot90")
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@register_op("gather")
def gather(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


@register_op("gather_nd")
def gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@register_op("take_along_axis")
def take_along_axis(x, indices, axis):
    return jnp.take_along_axis(x, indices, axis=axis)


@register_op("put_along_axis")
def put_along_axis(x, indices, values, axis, reduce="assign"):
    values = jnp.broadcast_to(jnp.asarray(values, dtype=x.dtype), indices.shape)
    # build scatter indices from take_along_axis semantics
    it = jnp.meshgrid(*[jnp.arange(s) for s in indices.shape], indexing="ij")
    full_idx = list(it)
    full_idx[axis % x.ndim] = indices
    flat_idx = tuple(full_idx)
    if reduce == "assign":
        return x.at[flat_idx].set(values)
    if reduce == "add":
        return x.at[flat_idx].add(values)
    if reduce in ("mul", "multiply"):
        return x.at[flat_idx].multiply(values)
    raise ValueError(f"unknown reduce: {reduce}")


@register_op("scatter")
def scatter(x, index, updates, overwrite=True):
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


@register_op("scatter_nd_add")
def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


@register_op("index_select")
def index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


@register_op("index_sample")
def index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


@register_op("masked_select")
def masked_select(x, mask):
    # dynamic output shape — only usable in eager mode, not under jit
    import numpy as np

    xn = np.asarray(x)
    mn = np.asarray(mask)
    return jnp.asarray(xn[np.broadcast_to(mn, xn.shape)])


@register_op("masked_fill")
def masked_fill(x, mask, value):
    return jnp.where(mask, jnp.asarray(value, dtype=x.dtype), x)


@register_op("pad")
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    pad = list(pad)
    if len(pad) == 2 * x.ndim:
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(x.ndim)]
    else:
        # paddle convention: pad applies to last len(pad)//2 spatial dims,
        # ordered from the last dim backwards in (before, after) pairs
        n_spatial = len(pad) // 2
        cfg = [(0, 0)] * x.ndim
        for i in range(n_spatial):
            dim = x.ndim - 1 - i
            cfg[dim] = (pad[2 * i], pad[2 * i + 1])
    if mode == "constant":
        return jnp.pad(x, cfg, mode="constant", constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    return jnp.pad(x, cfg, mode=jmode)


@register_op("getitem")
def getitem(x, idx):
    if isinstance(idx, (list, tuple)):
        idx = tuple(
            jnp.asarray(i) if hasattr(i, "__jax_array__") else i for i in idx
        )
    return x[idx]


@register_op("slice")
def slice(x, axes, starts, ends):  # noqa: A001
    idx = [jnp.s_[:]] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = jnp.s_[st:en]
    return x[tuple(idx)]


@register_op("strided_slice")
def strided_slice(x, axes, starts, ends, strides):
    idx = [jnp.s_[:]] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = jnp.s_[st:en:sd]
    return x[tuple(idx)]


@register_op("repeat_interleave")
def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register_op("unbind")
def unbind(x, axis=0):
    return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(x, x.shape[axis], axis=axis))


@register_op("as_real", differentiable=False)
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@register_op("as_complex", differentiable=False)
def as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


@register_op("one_hot", differentiable=False)
def one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)


@register_op("unique", differentiable=False)
def unique(x):
    # dynamic shape: eager-only
    import numpy as np

    return jnp.asarray(np.unique(np.asarray(x)))


@register_op("nonzero", differentiable=False)
def nonzero(x):
    import numpy as np

    nz = np.nonzero(np.asarray(x))
    return jnp.stack([jnp.asarray(i) for i in nz], axis=1)


@register_op("shard_index", differentiable=False)
def shard_index(x, index_num, nshards, shard_id, ignore_value=-1):
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    hi = (shard_id + 1) * shard_size
    in_shard = (x >= lo) & (x < hi)
    return jnp.where(in_shard, x - lo, ignore_value)
