"""Elementwise & scalar math ops (parity: python/paddle/tensor/math.py).

Each op is a pure jax function; XLA fuses chains of these into single
HBM-bandwidth-bound kernels, so there is no per-op fusion work to do here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import register_op

# ----------------------------------------------------------------- binary


@register_op("add")
def add(x, y):
    return jnp.add(x, y)


@register_op("subtract")
def subtract(x, y):
    return jnp.subtract(x, y)


@register_op("multiply")
def multiply(x, y):
    return jnp.multiply(x, y)


@register_op("divide")
def divide(x, y):
    return jnp.divide(x, y)


@register_op("floor_divide")
def floor_divide(x, y):
    return jnp.floor_divide(x, y)


@register_op("remainder")
def remainder(x, y):
    return jnp.remainder(x, y)


mod = remainder


@register_op("pow")
def pow(x, y):  # noqa: A001
    return jnp.power(x, y)


@register_op("maximum")
def maximum(x, y):
    return jnp.maximum(x, y)


@register_op("minimum")
def minimum(x, y):
    return jnp.minimum(x, y)


@register_op("fmax")
def fmax(x, y):
    return jnp.fmax(x, y)


@register_op("fmin")
def fmin(x, y):
    return jnp.fmin(x, y)


@register_op("atan2")
def atan2(x, y):
    return jnp.arctan2(x, y)


@register_op("hypot")
def hypot(x, y):
    return jnp.hypot(x, y)


# ------------------------------------------------------------------ unary


@register_op("scale")
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


@register_op("abs")
def abs(x):  # noqa: A001
    return jnp.abs(x)


@register_op("neg")
def neg(x):
    return jnp.negative(x)


@register_op("sign")
def sign(x):
    return jnp.sign(x)


@register_op("exp")
def exp(x):
    return jnp.exp(x)


@register_op("expm1")
def expm1(x):
    return jnp.expm1(x)


@register_op("log")
def log(x):
    return jnp.log(x)


@register_op("log2")
def log2(x):
    return jnp.log2(x)


@register_op("log10")
def log10(x):
    return jnp.log10(x)


@register_op("log1p")
def log1p(x):
    return jnp.log1p(x)


@register_op("sqrt")
def sqrt(x):
    return jnp.sqrt(x)


@register_op("rsqrt")
def rsqrt(x):
    return jax.lax.rsqrt(x)


@register_op("square")
def square(x):
    return jnp.square(x)


@register_op("reciprocal")
def reciprocal(x):
    return jnp.reciprocal(x)


@register_op("sin")
def sin(x):
    return jnp.sin(x)


@register_op("cos")
def cos(x):
    return jnp.cos(x)


@register_op("tan")
def tan(x):
    return jnp.tan(x)


@register_op("asin")
def asin(x):
    return jnp.arcsin(x)


@register_op("acos")
def acos(x):
    return jnp.arccos(x)


@register_op("atan")
def atan(x):
    return jnp.arctan(x)


@register_op("sinh")
def sinh(x):
    return jnp.sinh(x)


@register_op("cosh")
def cosh(x):
    return jnp.cosh(x)


@register_op("tanh")
def tanh(x):
    return jnp.tanh(x)


@register_op("asinh")
def asinh(x):
    return jnp.arcsinh(x)


@register_op("acosh")
def acosh(x):
    return jnp.arccosh(x)


@register_op("atanh")
def atanh(x):
    return jnp.arctanh(x)


@register_op("ceil")
def ceil(x):
    return jnp.ceil(x)


@register_op("floor")
def floor(x):
    return jnp.floor(x)


@register_op("round")
def round(x):  # noqa: A001
    return jnp.round(x)


@register_op("trunc")
def trunc(x):
    return jnp.trunc(x)


@register_op("frac")
def frac(x):
    return x - jnp.trunc(x)


@register_op("erf")
def erf(x):
    return jax.lax.erf(x)


@register_op("erfinv")
def erfinv(x):
    return jax.lax.erf_inv(x)


@register_op("lgamma")
def lgamma(x):
    return jax.lax.lgamma(x)


@register_op("digamma")
def digamma(x):
    return jax.lax.digamma(x)


@register_op("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@register_op("logit")
def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


@register_op("clip")
def clip(x, min=None, max=None):  # noqa: A002
    return jnp.clip(x, min, max)


@register_op("isnan", differentiable=False)
def isnan(x):
    return jnp.isnan(x)


@register_op("isinf", differentiable=False)
def isinf(x):
    return jnp.isinf(x)


@register_op("isfinite", differentiable=False)
def isfinite(x):
    return jnp.isfinite(x)


@register_op("nan_to_num")
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


# -------------------------------------------------------------- compound


@register_op("multiply_add")
def multiply_add(x, y, z):
    """fused multiply-add: x*y + z (XLA fuses this on the VPU)."""
    return x * y + z


@register_op("stanh")
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


@register_op("softplus")
def softplus(x, beta=1.0, threshold=20.0):
    bx = beta * x
    return jnp.where(bx > threshold, x, jnp.log1p(jnp.exp(bx)) / beta)


@register_op("logsumexp")
def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


@register_op("lerp")
def lerp(x, y, weight):
    return x + weight * (y - x)


@register_op("cumsum")
def cumsum(x, axis=None):
    if axis is None:
        return jnp.cumsum(x.reshape(-1), axis=0)
    return jnp.cumsum(x, axis=axis)


@register_op("cumprod")
def cumprod(x, dim=None):
    if dim is None:
        return jnp.cumprod(x.reshape(-1), axis=0)
    return jnp.cumprod(x, axis=dim)


@register_op("cummax", differentiable=False)
def cummax(x, axis=-1):
    return jax.lax.cummax(x, axis=axis)


@register_op("cummin", differentiable=False)
def cummin(x, axis=-1):
    return jax.lax.cummin(x, axis=axis)


@register_op("diff")
def diff(x, n=1, axis=-1):
    return jnp.diff(x, n=n, axis=axis)


@register_op("gcd", differentiable=False)
def gcd(x, y):
    return jnp.gcd(x, y)


@register_op("lcm", differentiable=False)
def lcm(x, y):
    return jnp.lcm(x, y)


# ----------------------------------------------------------------- logic


@register_op("equal", differentiable=False)
def equal(x, y):
    return jnp.equal(x, y)


@register_op("not_equal", differentiable=False)
def not_equal(x, y):
    return jnp.not_equal(x, y)


@register_op("less_than", differentiable=False)
def less_than(x, y):
    return jnp.less(x, y)


@register_op("less_equal", differentiable=False)
def less_equal(x, y):
    return jnp.less_equal(x, y)


@register_op("greater_than", differentiable=False)
def greater_than(x, y):
    return jnp.greater(x, y)


@register_op("greater_equal", differentiable=False)
def greater_equal(x, y):
    return jnp.greater_equal(x, y)


@register_op("logical_and", differentiable=False)
def logical_and(x, y):
    return jnp.logical_and(x, y)


@register_op("logical_or", differentiable=False)
def logical_or(x, y):
    return jnp.logical_or(x, y)


@register_op("logical_xor", differentiable=False)
def logical_xor(x, y):
    return jnp.logical_xor(x, y)


@register_op("logical_not", differentiable=False)
def logical_not(x):
    return jnp.logical_not(x)


@register_op("bitwise_and", differentiable=False)
def bitwise_and(x, y):
    return jnp.bitwise_and(x, y)


@register_op("bitwise_or", differentiable=False)
def bitwise_or(x, y):
    return jnp.bitwise_or(x, y)


@register_op("bitwise_xor", differentiable=False)
def bitwise_xor(x, y):
    return jnp.bitwise_xor(x, y)


@register_op("bitwise_not", differentiable=False)
def bitwise_not(x):
    return jnp.bitwise_not(x)


@register_op("allclose", differentiable=False)
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@register_op("isclose", differentiable=False)
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@register_op("equal_all", differentiable=False)
def equal_all(x, y):
    return jnp.array_equal(x, y)


@register_op("where")
def where(condition, x, y):
    return jnp.where(condition, x, y)


@register_op("heaviside")
def heaviside(x, y):
    return jnp.heaviside(x, y)
