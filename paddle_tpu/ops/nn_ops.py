"""Neural-net functional ops: conv/pool/norm/embedding/dropout/interpolate.

Parity targets: python/paddle/nn/functional/{conv,pooling,norm,common}.py and
the corresponding PHI kernels.  Convs/pools lower to lax.conv_general_dilated /
lax.reduce_window, which XLA tiles onto the MXU; layout assignment is XLA's
job so the public API stays NCHW like the reference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from .linalg import mxu_precision
from ..core.random import split_key


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


def _conv_padding(padding, k, stride, dilation, nd):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if len(padding) == nd:
        return [(p, p) for p in padding]
    if len(padding) == 2 * nd:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(nd)]
    raise ValueError(f"bad padding: {padding}")


@register_op("conv2d")
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    stride = _pair(stride)
    dilation = _pair(dilation)
    pad = _conv_padding(padding, weight.shape[-2:], stride, dilation, 2)
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "HWIO", "NHWC"),
    )
    # NOTE no preferred_element_type=f32 here: the TPU MXU accumulates
    # partial sums in f32 for bf16 operands regardless, and the conv
    # TRANSPOSE of a pet=f32 bf16 conv builds a mixed (f32 cotangent,
    # bf16 weight) conv that lax rejects — AMP training hits it
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups,
        precision=mxu_precision(x, weight))
    if bias is not None:
        shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        out = out + bias.reshape(shape)
    return out


@register_op("conv1d")
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    stride = _pair(stride, 1)
    dilation = _pair(dilation, 1)
    pad = _conv_padding(padding, weight.shape[-1:], stride, dilation, 1)
    dn = jax.lax.conv_dimension_numbers(x.shape, weight.shape, ("NCH", "OIH", "NCH"))
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups,
        precision=mxu_precision(x, weight))
    if bias is not None:
        out = out + bias.reshape(1, -1, 1)
    return out


@register_op("conv3d")
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    stride = _pair(stride, 3)
    dilation = _pair(dilation, 3)
    pad = _conv_padding(padding, weight.shape[-3:], stride, dilation, 3)
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape, ("NCDHW", "OIDHW", "NCDHW"))
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups,
        precision=mxu_precision(x, weight))
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1, 1)
    return out


@register_op("conv2d_transpose")
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCHW"):
    stride = _pair(stride)
    dilation = _pair(dilation)
    opad = _pair(output_padding)
    if isinstance(padding, str):
        raise NotImplementedError("string padding for conv_transpose")
    p = _conv_padding(padding, weight.shape[-2:], stride, dilation, 2)
    kh, kw = weight.shape[-2], weight.shape[-1]
    # gradient-of-conv formulation: lhs_dilation = stride
    pad_t = [
        (dilation[0] * (kh - 1) - p[0][0], dilation[0] * (kh - 1) - p[0][1] + opad[0]),
        (dilation[1] * (kw - 1) - p[1][0], dilation[1] * (kw - 1) - p[1][1] + opad[1]),
    ]
    # weight layout is (in, out/groups, kh, kw) in paddle; flip spatial and
    # swap io for the transposed conv
    w = jnp.flip(weight, axis=(-2, -1))
    if groups == 1:
        w = jnp.swapaxes(w, 0, 1)  # -> (out, in, kh, kw)
    else:
        ci, cog = weight.shape[0], weight.shape[1]
        w = w.reshape(groups, ci // groups, cog, kh, kw)
        w = jnp.swapaxes(w, 1, 2).reshape(groups * cog, ci // groups, kh, kw)
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, ("NCHW", "OIHW", "NCHW"))
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=pad_t,
        lhs_dilation=stride, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups,
        precision=mxu_precision(x, w))
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


# ----------------------------------------------------------------- pooling


def _ceil_extra_pad(size, k, s, pad_lo, pad_hi):
    """Extra trailing pad so reduce_window emits ceil-mode output size."""
    import math

    out_ceil = math.ceil((size + pad_lo + pad_hi - k) / s) + 1
    needed = (out_ceil - 1) * s + k - (size + pad_lo + pad_hi)
    return max(needed, 0)


@register_op("max_pool2d")
def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NCHW"):
    k = _pair(kernel_size)
    s = _pair(stride) if stride is not None else k
    p = _conv_padding(padding, k, s, (1, 1), 2)
    if isinstance(p, str):
        pads = p
    else:
        p = list(p)
        if ceil_mode:
            h, w = x.shape[2], x.shape[3]
            p[0] = (p[0][0], p[0][1] + _ceil_extra_pad(h, k[0], s[0], *p[0]))
            p[1] = (p[1][0], p[1][1] + _ceil_extra_pad(w, k[1], s[1], *p[1]))
        pads = [(0, 0), (0, 0)] + p
    window = (1, 1) + k
    strides = (1, 1) + s
    neg_inf = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    return jax.lax.reduce_window(x, neg_inf, jax.lax.max, window, strides, pads)


@register_op("avg_pool2d")
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, data_format="NCHW"):
    k = _pair(kernel_size)
    s = _pair(stride) if stride is not None else k
    p = _conv_padding(padding, k, s, (1, 1), 2)
    if not isinstance(p, str):
        p = list(p)
        if ceil_mode:
            h, w = x.shape[2], x.shape[3]
            p[0] = (p[0][0], p[0][1] + _ceil_extra_pad(h, k[0], s[0], *p[0]))
            p[1] = (p[1][0], p[1][1] + _ceil_extra_pad(w, k[1], s[1], *p[1]))
    pads = p if isinstance(p, str) else [(0, 0), (0, 0)] + list(p)
    window = (1, 1) + k
    strides = (1, 1) + s
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
    if exclusive and not isinstance(pads, str):
        counts = jax.lax.reduce_window(
            jnp.ones_like(x), 0.0, jax.lax.add, window, strides, pads)
        return summed / jnp.maximum(counts, 1.0)
    return summed / (k[0] * k[1])


@register_op("adaptive_avg_pool2d")
def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    oh, ow = _pair(output_size)
    n, c, h, w = x.shape
    if h % oh == 0 and w % ow == 0:
        return jnp.mean(
            x.reshape(n, c, oh, h // oh, ow, w // ow), axis=(3, 5))
    # general case: interpolate-style pooling windows
    rows = [(int(jnp.floor(i * h / oh)), int(jnp.ceil((i + 1) * h / oh))) for i in range(oh)]
    cols = [(int(jnp.floor(j * w / ow)), int(jnp.ceil((j + 1) * w / ow))) for j in range(ow)]
    out = jnp.stack([
        jnp.stack([jnp.mean(x[:, :, r0:r1, c0:c1], axis=(2, 3)) for (c0, c1) in cols], axis=-1)
        for (r0, r1) in rows
    ], axis=-2)
    return out


@register_op("adaptive_max_pool2d")
def adaptive_max_pool2d(x, output_size, data_format="NCHW"):
    oh, ow = _pair(output_size)
    n, c, h, w = x.shape
    if h % oh == 0 and w % ow == 0:
        return jnp.max(x.reshape(n, c, oh, h // oh, ow, w // ow), axis=(3, 5))
    raise NotImplementedError("non-divisible adaptive_max_pool2d")


@register_op("global_avg_pool2d")
def global_avg_pool2d(x, data_format="NCHW"):
    axes = (2, 3) if data_format == "NCHW" else (1, 2)
    return jnp.mean(x, axis=axes, keepdims=True)


# ------------------------------------------------------------------- norms


@register_op("layer_norm")
def layer_norm(x, weight=None, bias=None, epsilon=1e-5, normalized_ndim=1):
    axes = tuple(range(x.ndim - normalized_ndim, x.ndim))
    mean = jnp.mean(x.astype(jnp.float32), axis=axes, keepdims=True)
    var = jnp.var(x.astype(jnp.float32), axis=axes, keepdims=True)
    out = (x.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


@register_op("rms_norm")
def rms_norm(x, weight=None, epsilon=1e-6):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    out = (x.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
    if weight is not None:
        out = out * weight
    return out


@register_op("batch_norm_infer")
def batch_norm_infer(x, running_mean, running_var, weight=None, bias=None,
                     epsilon=1e-5, data_format="NCHW"):
    shape = [1, -1] + [1] * (x.ndim - 2) if data_format.startswith("NC") else \
            [1] * (x.ndim - 1) + [-1]
    inv = jax.lax.rsqrt(running_var.reshape(shape) + epsilon)
    out = (x - running_mean.reshape(shape)) * inv
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@register_op("batch_norm_train")
def batch_norm_train(x, weight=None, bias=None, epsilon=1e-5,
                     data_format="NCHW"):
    """Returns (out, batch_mean, batch_var) — caller updates running stats."""
    if data_format.startswith("NC"):
        axes = (0,) + tuple(range(2, x.ndim))
        shape = [1, -1] + [1] * (x.ndim - 2)
    else:
        axes = tuple(range(x.ndim - 1))
        shape = [1] * (x.ndim - 1) + [-1]
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes)
    var = jnp.var(xf, axis=axes)
    out = (xf - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out, mean, var


@register_op("instance_norm")
def instance_norm(x, weight=None, bias=None, epsilon=1e-5):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    shape = [1, -1] + [1] * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@register_op("group_norm")
def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW"):
    n, c = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    xg = x.reshape(n, num_groups, c // num_groups, *spatial)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x.shape)
    shape = [1, -1] + [1] * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@register_op("local_response_norm")
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0):
    sq = jnp.square(x)
    half = size // 2
    c = x.shape[1]
    padded = jnp.pad(sq, [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (x.ndim - 2))
    acc = sum(padded[:, i : i + c] for i in range(size))
    return x / jnp.power(k + alpha * acc / size, beta)


# --------------------------------------------------------------- embedding


@register_op("embedding")
def embedding(ids, weight, padding_idx=None, sparse=False):
    out = jnp.take(weight, ids, axis=0)
    if padding_idx is not None:
        mask = (ids != padding_idx)[..., None].astype(out.dtype)
        out = out * mask
    return out


# ----------------------------------------------------------------- dropout


@register_op("dropout")
def dropout(x, p=0.5, training=True, mode="upscale_in_train", key=None):
    if not training:
        # downscale_in_infer: train keeps raw mask, infer scales by keep-prob
        return x if mode == "upscale_in_train" else x * (1.0 - p)
    if p == 0.0:
        return x
    if key is None:
        key = split_key()
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, p=keep, shape=x.shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, 0).astype(x.dtype)
    return jnp.where(mask, x, 0).astype(x.dtype)


@register_op("dropout2d")
def dropout2d(x, p=0.5, training=True, data_format="NCHW", key=None):
    if not training or p == 0.0:
        return x
    if key is None:
        key = split_key()
    keep = 1.0 - p
    mask_shape = (x.shape[0], x.shape[1], 1, 1) if data_format == "NCHW" else \
                 (x.shape[0], 1, 1, x.shape[3])
    mask = jax.random.bernoulli(key, p=keep, shape=mask_shape)
    return jnp.where(mask, x / keep, 0).astype(x.dtype)


# ------------------------------------------------------------- interpolate


@register_op("interpolate")
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format="NCHW"):
    n, c, h, w = x.shape
    if size is None:
        sf = _pair(scale_factor)
        size = (int(h * sf[0]), int(w * sf[1]))
    oh, ow = _pair(size)
    method = {"nearest": "nearest", "bilinear": "bilinear", "bicubic": "cubic",
              "linear": "linear", "area": "linear"}[mode]
    xt = jnp.transpose(x, (0, 2, 3, 1))
    out = jax.image.resize(xt, (n, oh, ow, c), method=method)
    return jnp.transpose(out, (0, 3, 1, 2)).astype(x.dtype)


@register_op("pixel_shuffle")
def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = upscale_factor
    n, c, h, w = x.shape
    out = x.reshape(n, c // (r * r), r, r, h, w)
    out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
    return out.reshape(n, c // (r * r), h * r, w * r)


@register_op("unfold")
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    k = _pair(kernel_sizes)
    s = _pair(strides)
    p = _pair(paddings)
    d = _pair(dilations)
    n, c, h, w = x.shape
    xp = jnp.pad(x, [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
    oh = (h + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
    ow = (w + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
    patches = []
    for i in range(k[0]):
        for j in range(k[1]):
            patches.append(
                xp[:, :, i * d[0] : i * d[0] + oh * s[0] : s[0],
                   j * d[1] : j * d[1] + ow * s[1] : s[1]])
    out = jnp.stack(patches, axis=2)  # n, c, k*k, oh, ow
    return out.reshape(n, c * k[0] * k[1], oh * ow)
