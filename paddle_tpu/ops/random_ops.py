"""Random sampling ops (parity: python/paddle/tensor/random.py).

Eager calls draw fresh subkeys from the framework's stateful stream
(core/random.py); under jit an explicit ``key=`` must be threaded, keeping
the pure/functional contract XLA needs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.dtype import convert_dtype, get_default_dtype
from ..core.random import split_key


def _key(key):
    return split_key() if key is None else key


@register_op("uniform", differentiable=False)
def uniform(shape, dtype=None, min=-1.0, max=1.0, key=None):  # noqa: A002
    dt = convert_dtype(dtype) or get_default_dtype()
    return jax.random.uniform(_key(key), tuple(shape), dtype=dt, minval=min, maxval=max)


@register_op("randn", differentiable=False)
def randn(shape, dtype=None, key=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    return jax.random.normal(_key(key), tuple(shape), dtype=dt)


@register_op("normal", differentiable=False)
def normal(mean=0.0, std=1.0, shape=None, key=None):
    base = jax.random.normal(_key(key), tuple(shape or ()), dtype=get_default_dtype())
    return base * std + mean


@register_op("randint", differentiable=False)
def randint(low=0, high=None, shape=(1,), dtype="int64", key=None):
    if high is None:
        low, high = 0, low
    return jax.random.randint(_key(key), tuple(shape), low, high,
                              dtype=convert_dtype(dtype))


@register_op("randperm", differentiable=False)
def randperm(n, dtype="int64", key=None):
    return jax.random.permutation(_key(key), n).astype(convert_dtype(dtype))


@register_op("rand", differentiable=False)
def rand(shape, dtype=None, key=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    return jax.random.uniform(_key(key), tuple(shape), dtype=dt)


@register_op("bernoulli", differentiable=False)
def bernoulli(x, key=None):
    return jax.random.bernoulli(_key(key), p=x).astype(x.dtype)


@register_op("multinomial", differentiable=False)
def multinomial(x, num_samples=1, replacement=False, key=None):
    logits = jnp.log(jnp.maximum(x, 1e-30))
    if replacement:
        if x.ndim == 1:
            return jax.random.categorical(_key(key), logits, shape=(num_samples,)).astype(jnp.int64)
        return jax.random.categorical(
            _key(key), logits[:, None, :], axis=-1, shape=(x.shape[0], num_samples)
        ).astype(jnp.int64)
    # without replacement: Gumbel top-k trick
    k = _key(key)
    g = jax.random.gumbel(k, x.shape)
    _, idx = jax.lax.top_k(logits + g, num_samples)
    return idx.astype(jnp.int64)


@register_op("poisson", differentiable=False)
def poisson(x, key=None):
    return jax.random.poisson(_key(key), x).astype(get_default_dtype())


@register_op("standard_normal", differentiable=False)
def standard_normal(shape, dtype=None, key=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    return jax.random.normal(_key(key), tuple(shape), dtype=dt)


@register_op("exponential", differentiable=False)
def exponential(x, lam=1.0, key=None):
    return jax.random.exponential(_key(key), x.shape, dtype=x.dtype) / lam
