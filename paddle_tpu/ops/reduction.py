"""Reduction & statistics ops (parity: python/paddle/tensor/math.py + stat.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import register_op


def _axis(axis):
    if isinstance(axis, list):
        return tuple(axis)
    return axis


@register_op("sum")
def sum(x, axis=None, keepdim=False, dtype=None):  # noqa: A001
    out = jnp.sum(x, axis=_axis(axis), keepdims=keepdim)
    return out.astype(dtype) if dtype is not None else out


@register_op("mean")
def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=_axis(axis), keepdims=keepdim)


@register_op("max")
def max(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@register_op("min")
def min(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


@register_op("prod")
def prod(x, axis=None, keepdim=False, dtype=None):
    out = jnp.prod(x, axis=_axis(axis), keepdims=keepdim)
    return out.astype(dtype) if dtype is not None else out


@register_op("amax")
def amax(x, axis=None, keepdim=False):
    return jnp.amax(x, axis=_axis(axis), keepdims=keepdim)


@register_op("amin")
def amin(x, axis=None, keepdim=False):
    return jnp.amin(x, axis=_axis(axis), keepdims=keepdim)


@register_op("std")
def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


@register_op("var")
def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


@register_op("median")
def median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=_axis(axis), keepdims=keepdim)


@register_op("quantile")
def quantile(x, q, axis=None, keepdim=False):
    return jnp.quantile(x, q, axis=_axis(axis), keepdims=keepdim)


@register_op("nanmean")
def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=_axis(axis), keepdims=keepdim)


@register_op("nansum")
def nansum(x, axis=None, keepdim=False):
    return jnp.nansum(x, axis=_axis(axis), keepdims=keepdim)


@register_op("all", differentiable=False)
def all(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.all(x, axis=_axis(axis), keepdims=keepdim)


@register_op("any", differentiable=False)
def any(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.any(x, axis=_axis(axis), keepdims=keepdim)


@register_op("count_nonzero", differentiable=False)
def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=_axis(axis), keepdims=keepdim)


@register_op("argmax", differentiable=False)
def argmax(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(jnp.dtype(dtype))


@register_op("argmin", differentiable=False)
def argmin(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmin(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(jnp.dtype(dtype))
