"""Search / sort ops (parity: python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import register_op


@register_op("sort")
def sort(x, axis=-1, descending=False):
    out = jnp.sort(x, axis=axis)
    if descending:
        out = jnp.flip(out, axis=axis)
    return out


@register_op("argsort", differentiable=False)
def argsort(x, axis=-1, descending=False):
    idx = jnp.argsort(x, axis=axis)
    if descending:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(jnp.int64)


@register_op("topk")
def topk(x, k, axis=-1, largest=True, sorted=True):  # noqa: A002
    if axis != -1 and axis != x.ndim - 1:
        xm = jnp.moveaxis(x, axis, -1)
        vals, idx = jax.lax.top_k(xm if largest else -xm, k)
        if not largest:
            vals = -vals
        return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis).astype(jnp.int64)
    vals, idx = jax.lax.top_k(x if largest else -x, k)
    if not largest:
        vals = -vals
    return vals, idx.astype(jnp.int64)


@register_op("kthvalue")
def kthvalue(x, k, axis=-1, keepdim=False):
    sorted_x = jnp.sort(x, axis=axis)
    idx_sorted = jnp.argsort(x, axis=axis)
    vals = jnp.take(sorted_x, k - 1, axis=axis)
    idx = jnp.take(idx_sorted, k - 1, axis=axis)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idx = jnp.expand_dims(idx, axis)
    return vals, idx.astype(jnp.int64)


@register_op("mode")
def mode(x, axis=-1, keepdim=False):
    import numpy as np
    import scipy.stats

    xn = np.asarray(x)
    m = scipy.stats.mode(xn, axis=axis, keepdims=keepdim)
    return jnp.asarray(m.mode), jnp.asarray(m.count)


@register_op("searchsorted", differentiable=False)
def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    if sorted_sequence.ndim == 1:
        out = jnp.searchsorted(sorted_sequence, values, side=side)
    else:
        out = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))(
            sorted_sequence.reshape(-1, sorted_sequence.shape[-1]),
            values.reshape(-1, values.shape[-1]),
        ).reshape(values.shape)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@register_op("index_of_max", differentiable=False)
def index_of_max(x):
    return jnp.argmax(x)


@register_op("bucketize", differentiable=False)
def bucketize(x, sorted_sequence, out_int32=False, right=False):
    side = "right" if right else "left"
    out = jnp.searchsorted(sorted_sequence, x, side=side)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


def beam_search_step(pre_scores, log_probs, beam_size, end_id,
                     finished=None):
    """One beam expansion (reference role:
    paddle/fluid/operators/beam_search_op.cc): combine accumulated beam
    scores [B, K] with next-token log-probs [B, K, V], take the global
    top-K over K*V, and return (token [B, K], parent [B, K],
    scores [B, K], finished [B, K]).

    Finished beams (``finished`` mask) are frozen: their only expansion
    is ``end_id`` at unchanged score, so they compete with live beams
    but never grow."""
    import jax

    B, K, V = log_probs.shape
    if finished is None:
        finished = jnp.zeros((B, K), bool)
    frozen = jnp.full((V,), -jnp.inf).at[end_id].set(0.0)
    lp = jnp.where(finished[..., None], frozen[None, None, :], log_probs)
    total = pre_scores[..., None] + lp
    # beam_size is the OUTPUT width (may differ from the incoming K,
    # e.g. expanding one seed beam into beam_size candidates)
    top, idx = jax.lax.top_k(total.reshape(B, K * V), int(beam_size))
    parent = idx // V
    token = idx % V
    new_fin = jnp.take_along_axis(finished, parent, 1) | (token == end_id)
    return token, parent, top, new_fin


def beam_search(step_fn, bos_id, end_id, beam_size, max_len, batch_size=1,
                vocab_size=None, length_penalty=0.0):
    """Full beam-search decode under ONE lax.scan (reference role:
    beam_search + beam_search_decode_op.cc backtrace, and the dygraph
    nn BeamSearchDecoder).

    ``step_fn(history, t) -> log_probs``: history [B, K, max_len+1] of
    token ids (prefix valid through position t), returns [B, K, V]
    next-token log-probs.  The decoded history is re-gathered by parent
    every step, so no separate backtrace pass is needed (the TPU-native
    replacement for the reference's LoD backtrace op).

    Returns (sequences [B, K, max_len+1], scores [B, K]) sorted
    best-first; positions past a beam's end_id are filled with end_id.
    """
    import jax

    B, K = batch_size, beam_size
    hist0 = jnp.full((B, K, max_len + 1), end_id, jnp.int32)
    hist0 = hist0.at[:, :, 0].set(bos_id)
    # only beam 0 starts live: identical beams would duplicate the top-K
    scores0 = jnp.where(jnp.arange(K)[None, :] == 0, 0.0, -jnp.inf)
    scores0 = jnp.broadcast_to(scores0, (B, K)).astype(jnp.float32)
    fin0 = jnp.zeros((B, K), bool)

    def tick(carry, t):
        hist, scores, fin = carry
        lp = step_fn(hist, t)
        token, parent, scores, fin = beam_search_step(
            scores, lp, K, end_id, fin)
        hist = jnp.take_along_axis(hist, parent[..., None], 1)
        hist = jax.vmap(lambda h, tok, tt: h.at[:, tt].set(tok),
                        in_axes=(0, 0, None))(hist, token, t + 1)
        return (hist, scores, fin), None

    (hist, scores, fin), _ = jax.lax.scan(
        tick, (hist0, scores0, fin0), jnp.arange(max_len))
    if length_penalty:
        lengths = (hist != end_id).sum(-1).astype(jnp.float32)
        scores = scores / jnp.power(lengths, length_penalty)
        order = jnp.argsort(-scores, axis=1)
        hist = jnp.take_along_axis(hist, order[..., None], 1)
        scores = jnp.take_along_axis(scores, order, 1)
    return hist, scores
