"""Search / sort ops (parity: python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import register_op


@register_op("sort")
def sort(x, axis=-1, descending=False):
    out = jnp.sort(x, axis=axis)
    if descending:
        out = jnp.flip(out, axis=axis)
    return out


@register_op("argsort", differentiable=False)
def argsort(x, axis=-1, descending=False):
    idx = jnp.argsort(x, axis=axis)
    if descending:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(jnp.int64)


@register_op("topk")
def topk(x, k, axis=-1, largest=True, sorted=True):  # noqa: A002
    if axis != -1 and axis != x.ndim - 1:
        xm = jnp.moveaxis(x, axis, -1)
        vals, idx = jax.lax.top_k(xm if largest else -xm, k)
        if not largest:
            vals = -vals
        return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis).astype(jnp.int64)
    vals, idx = jax.lax.top_k(x if largest else -x, k)
    if not largest:
        vals = -vals
    return vals, idx.astype(jnp.int64)


@register_op("kthvalue")
def kthvalue(x, k, axis=-1, keepdim=False):
    sorted_x = jnp.sort(x, axis=axis)
    idx_sorted = jnp.argsort(x, axis=axis)
    vals = jnp.take(sorted_x, k - 1, axis=axis)
    idx = jnp.take(idx_sorted, k - 1, axis=axis)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idx = jnp.expand_dims(idx, axis)
    return vals, idx.astype(jnp.int64)


@register_op("mode")
def mode(x, axis=-1, keepdim=False):
    import numpy as np
    import scipy.stats

    xn = np.asarray(x)
    m = scipy.stats.mode(xn, axis=axis, keepdims=keepdim)
    return jnp.asarray(m.mode), jnp.asarray(m.count)


@register_op("searchsorted", differentiable=False)
def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    if sorted_sequence.ndim == 1:
        out = jnp.searchsorted(sorted_sequence, values, side=side)
    else:
        out = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))(
            sorted_sequence.reshape(-1, sorted_sequence.shape[-1]),
            values.reshape(-1, values.shape[-1]),
        ).reshape(values.shape)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@register_op("index_of_max", differentiable=False)
def index_of_max(x):
    return jnp.argmax(x)


@register_op("bucketize", differentiable=False)
def bucketize(x, sorted_sequence, out_int32=False, right=False):
    side = "right" if right else "left"
    out = jnp.searchsorted(sorted_sequence, x, side=side)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)
