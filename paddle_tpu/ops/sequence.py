"""Sequence operators (reference: paddle/fluid/operators/sequence_ops/ —
51 LoD-tensor kernels).

TPU-first redesign: the reference's sequence ops run on LoD (ragged)
tensors whose row offsets live in host metadata.  Ragged shapes cannot
be jitted, so the TPU-native contract is PADDED DENSE + LENGTHS: every
op takes [B, T, ...] plus lengths [B], masks arithmetic instead of
slicing rows, and compiles to one fused vectorized program.  The
pad/unpad pair converts between the reference's flat-concatenated
layout and the padded one at the host boundary.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import register_op

__all__ = ["sequence_mask", "sequence_pad", "sequence_unpad",
           "sequence_softmax", "sequence_reverse", "sequence_expand",
           "sequence_pool"]


@register_op("sequence_mask")
def sequence_mask(lengths, maxlen=None, dtype="bool"):
    """[B] lengths -> [B, maxlen] validity mask (reference
    sequence_ops/sequence_mask_op.cc)."""
    ln = jnp.asarray(lengths if not hasattr(lengths, "data")
                     else lengths.data, jnp.int32)
    m = int(maxlen) if maxlen is not None else int(jnp.max(ln))
    return (jnp.arange(m)[None, :] < ln[:, None]).astype(dtype)


def sequence_pad(x, lengths, maxlen=None, pad_value=0.0):
    """Flat-concatenated rows [sum(lengths), ...] -> padded
    [B, maxlen, ...] (reference sequence_pad_op.cc; LoD -> dense)."""
    x = jnp.asarray(x if not hasattr(x, "data") else x.data)
    ln = np.asarray(lengths, np.int64)
    m = int(maxlen) if maxlen is not None else int(ln.max())
    offs = np.concatenate([[0], np.cumsum(ln)[:-1]])
    # gather index per (b, t): offs[b] + min(t, len-1); padded slots are
    # overwritten with pad_value by the mask
    idx = offs[:, None] + np.minimum(np.arange(m)[None, :], ln[:, None] - 1)
    out = x[jnp.asarray(idx, jnp.int32)]
    mask = jnp.asarray(np.arange(m)[None, :] < ln[:, None])
    shape = mask.shape + (1,) * (out.ndim - 2)
    return jnp.where(mask.reshape(shape), out, pad_value)


def sequence_unpad(x, lengths):
    """Padded [B, T, ...] -> flat rows [sum(lengths), ...] (reference
    sequence_unpad_op.cc).  Output length is data-dependent, so this is
    a host-boundary op (eager; not jittable)."""
    x = np.asarray(x if not hasattr(x, "data") else x.data)
    ln = np.asarray(lengths, np.int64)
    return np.concatenate([x[b, :ln[b]] for b in range(len(ln))], axis=0)


@register_op("sequence_softmax")
def sequence_softmax(x, lengths=None):
    """Per-row softmax over the valid prefix only (reference
    sequence_softmax_op.cc): padded positions get probability 0."""
    a = jnp.asarray(x if not hasattr(x, "data") else x.data)
    if lengths is None:
        return jax.nn.softmax(a, axis=-1)
    ln = jnp.asarray(lengths if not hasattr(lengths, "data")
                     else lengths.data, jnp.int32)
    mask = jnp.arange(a.shape[1])[None, :] < ln[:, None]
    z = jnp.where(mask, a, -jnp.inf)
    p = jax.nn.softmax(z, axis=1)
    return jnp.where(mask, p, 0.0)


@register_op("sequence_reverse")
def sequence_reverse(x, lengths=None):
    """Reverse each row's valid prefix, keeping padding in place
    (reference sequence_reverse_op.cc)."""
    a = jnp.asarray(x if not hasattr(x, "data") else x.data)
    T = a.shape[1]
    if lengths is None:
        return jnp.flip(a, axis=1)
    ln = jnp.asarray(lengths if not hasattr(lengths, "data")
                     else lengths.data, jnp.int32)
    t = jnp.arange(T)[None, :]
    src = jnp.where(t < ln[:, None], ln[:, None] - 1 - t, t)
    return jnp.take_along_axis(
        a, src.reshape(src.shape + (1,) * (a.ndim - 2)), axis=1)


def sequence_expand(x, repeats, maxlen=None):
    """Repeat row b of x [B, ...] repeats[b] times (reference
    sequence_expand_op.cc: expand by the ref LoD).  Static-shape form:
    pass ``maxlen`` = total output rows under jit (sum(repeats) must
    equal it); defaults to the host-computed sum."""
    a = jnp.asarray(x if not hasattr(x, "data") else x.data)
    r = jnp.asarray(repeats if not hasattr(repeats, "data")
                    else repeats.data, jnp.int32)
    total = int(maxlen) if maxlen is not None else int(np.sum(np.asarray(r)))
    idx = jnp.repeat(jnp.arange(a.shape[0]), r, total_repeat_length=total)
    return a[idx]


@register_op("sequence_pool")
def sequence_pool(x, pool_type="sum", lengths=None):
    """Masked pooling over the time axis (reference sequence_pool_op.cc:
    SUM/AVERAGE/SQRT/MAX/FIRST/LAST over each LoD row)."""
    a = jnp.asarray(x if not hasattr(x, "data") else x.data)
    B, T = a.shape[0], a.shape[1]
    if lengths is None:
        ln = jnp.full((B,), T, jnp.int32)
    else:
        ln = jnp.asarray(lengths if not hasattr(lengths, "data")
                         else lengths.data, jnp.int32)
    mask = (jnp.arange(T)[None, :] < ln[:, None])
    mshape = mask.shape + (1,) * (a.ndim - 2)
    mf = mask.reshape(mshape).astype(a.dtype)
    kind = pool_type.lower()
    if kind == "sum":
        return (a * mf).sum(axis=1)
    if kind in ("average", "mean", "avg"):
        return (a * mf).sum(axis=1) / jnp.maximum(
            ln.reshape((B,) + (1,) * (a.ndim - 2)).astype(a.dtype), 1)
    if kind == "sqrt":
        return (a * mf).sum(axis=1) / jnp.sqrt(jnp.maximum(
            ln.reshape((B,) + (1,) * (a.ndim - 2)).astype(a.dtype), 1))
    if kind == "max":
        neg = jnp.finfo(a.dtype).min if jnp.issubdtype(a.dtype, jnp.floating) \
            else jnp.iinfo(a.dtype).min
        return jnp.where(mask.reshape(mshape), a, neg).max(axis=1)
    if kind == "first":
        return a[:, 0]
    if kind == "last":
        idx = jnp.maximum(ln - 1, 0).reshape((B, 1) + (1,) * (a.ndim - 2))
        return jnp.take_along_axis(a, idx, axis=1)[:, 0]
    raise ValueError(f"sequence_pool: unknown pool_type {pool_type!r}")
