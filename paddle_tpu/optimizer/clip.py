"""Gradient clipping (parity: python/paddle/fluid/clip.py — ClipGradBy*).

Clip objects are callables over lists of raw grad arrays, usable both from
the eager optimizer step and inside jitted train steps.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "clip_grads_by_global_norm"]


class ClipGradByValue:
    def __init__(self, max, min=None):  # noqa: A002
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, grads):
        return [jnp.clip(g, self.min, self.max) for g in grads]


class ClipGradByNorm:
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        out = []
        for g in grads:
            n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            factor = jnp.minimum(1.0, self.clip_norm / jnp.maximum(n, 1e-12))
            out.append((g.astype(jnp.float32) * factor).astype(g.dtype))
        return out


class ClipGradByGlobalNorm:
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        return clip_grads_by_global_norm(grads, self.clip_norm)


def clip_grads_by_global_norm(grads, clip_norm):
    gn_sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads)
    gn = jnp.sqrt(gn_sq)
    factor = jnp.minimum(1.0, clip_norm / jnp.maximum(gn, 1e-12))
    return [(g.astype(jnp.float32) * factor).astype(g.dtype) for g in grads]
