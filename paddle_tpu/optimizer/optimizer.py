"""Optimizer base.

Parity: python/paddle/optimizer/optimizer.py.  TPU-first design: each
optimizer defines a *pure functional update rule* (``init_slots`` /
``update``) over jax arrays.  The eager ``step()`` applies it to ``p.grad``
per parameter; the jit/pjit training path calls ``apply_gradients`` on whole
parameter pytrees inside the compiled step (where ZeRO sharding of the slot
pytree is just a sharding annotation — the stage-1/2 bookkeeping of the
reference's sharding optimizers collapses into GSPMD).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["Optimizer"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        from .lr import LRScheduler

        self._lr = learning_rate
        self._lr_scheduler = learning_rate if isinstance(learning_rate, LRScheduler) else None
        self._parameter_list = list(parameters) if parameters is not None else None
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._step_count = 0
        self._slots: dict[int, dict] = {}  # id(param) -> slot dict
        self._master_weights: dict[int, jnp.ndarray] = {}
        self._accumulators_built = False

    # ------------------------------------------------------------ subclasses
    def init_slots(self, param: jnp.ndarray) -> dict:
        """Return the slot arrays (momentum/moments/…) for one parameter."""
        return {}

    def update(self, param, grad, slots, lr, step):
        """Pure update rule: returns (new_param, new_slots)."""
        raise NotImplementedError

    # --------------------------------------------------------------- lr plumbing
    def get_lr(self):
        if self._lr_scheduler is not None:
            return self._lr_scheduler()
        return float(self._lr)

    def set_lr(self, value):
        if self._lr_scheduler is not None:
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = value

    # --------------------------------------------------------------- eager path
    def _param_lr(self, p, lr):
        return lr * p.optimize_attr.get("learning_rate", 1.0)

    def step(self):
        params = self._parameter_list
        if params is None:
            raise ValueError("Optimizer constructed without parameters")
        lr = self.get_lr()
        step = self._step_count + 1

        grads = [(p, p.grad.data) for p in params
                 if (not p.stop_gradient) and p.grad is not None]
        if self._grad_clip is not None and grads:
            clipped = self._grad_clip([g for _, g in grads])
            grads = [(p, g) for (p, _), g in zip(grads, clipped)]
        for p, g in grads:
            g = self._apply_decay(p.data, g)
            pid = id(p)
            if pid not in self._slots:
                self._slots[pid] = self.init_slots(p.data)
                if self._multi_precision and p.data.dtype in (jnp.bfloat16, jnp.float16):
                    self._master_weights[pid] = p.data.astype(jnp.float32)
            slots = self._slots[pid]
            if pid in self._master_weights:
                master = self._master_weights[pid]
                new_master, new_slots = self.update(
                    master, g.astype(jnp.float32), slots,
                    self._param_lr(p, lr), step)
                self._master_weights[pid] = new_master
                p.data = new_master.astype(p.data.dtype)
            else:
                new_param, new_slots = self.update(
                    p.data, g.astype(p.data.dtype), slots,
                    self._param_lr(p, lr), step)
                p.data = new_param
            self._slots[pid] = new_slots
        self._step_count = step

    def clear_grad(self):
        if self._parameter_list:
            for p in self._parameter_list:
                p.grad = None

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    # ----------------------------------------------------------------- state
    def state_dict(self):
        out = {"step": self._step_count}
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                pid = id(p)
                key = p.name or f"param_{i}"
                if pid in self._slots:
                    for sname, arr in self._slots[pid].items():
                        out[f"{key}.{sname}"] = Tensor(arr)
                if pid in self._master_weights:
                    out[f"{key}.master"] = Tensor(self._master_weights[pid])
        if self._lr_scheduler is not None:
            out["LR_Scheduler"] = self._lr_scheduler.state_dict()
        return out

    def set_state_dict(self, state):
        self._step_count = int(state.get("step", 0))
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                key = p.name or f"param_{i}"
                pid = id(p)
                slots = self.init_slots(p.data)
                found = False
                for sname in list(slots):
                    k = f"{key}.{sname}"
                    if k in state:
                        v = state[k]
                        slots[sname] = v.data if isinstance(v, Tensor) else jnp.asarray(v)
                        found = True
                if found:
                    self._slots[pid] = slots
                mk = f"{key}.master"
                if mk in state:
                    v = state[mk]
                    self._master_weights[pid] = v.data if isinstance(v, Tensor) else jnp.asarray(v)
        if self._lr_scheduler is not None and "LR_Scheduler" in state:
            self._lr_scheduler.set_state_dict(state["LR_Scheduler"])

    # ------------------------------------------------------- functional path
    def init_state(self, params):
        """params: pytree of arrays → optimizer state pytree (for jit path)."""
        slots = jax.tree_util.tree_map(self.init_slots, params)
        return {"step": jnp.zeros((), jnp.int32), "slots": slots}

    def apply_gradients(self, params, grads, state, lr=None):
        """Pure: (params, grads, state) → (new_params, new_state).

        Usable inside jit/pjit; ``lr`` may be a traced scalar.
        """
        lr = self.get_lr() if lr is None else lr
        step = state["step"] + 1
        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_s = treedef.flatten_up_to(state["slots"])
        if self._grad_clip is not None:
            flat_g = self._grad_clip(flat_g)
        new_p, new_s = [], []
        for p, g, s in zip(flat_p, flat_g, flat_s):
            if g is None:
                new_p.append(p)
                new_s.append(s)
                continue
            g = self._apply_decay(p, g.astype(p.dtype))
            np_, ns_ = self.update(p, g, s, lr, step)
            new_p.append(np_)
            new_s.append(ns_)
        return (
            jax.tree_util.tree_unflatten(treedef, new_p),
            {"step": step, "slots": jax.tree_util.tree_unflatten(treedef, new_s)},
        )

    def _apply_decay(self, param, grad):
        """Coupled L2 (reference default); AdamW overrides for decoupled."""
        wd = self._weight_decay
        if wd is None or wd == 0.0 or not isinstance(wd, (int, float)):
            return grad
        return grad + jnp.asarray(wd, dtype=grad.dtype) * param.astype(grad.dtype)
