"""Concrete optimizers (parity: python/paddle/optimizer/{sgd,momentum,adam,adamw,lamb}.py
and the PHI kernels paddle/phi/kernels/*/{sgd,momentum,adam,...}_kernel.*)."""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adagrad", "RMSProp", "Adam", "AdamW", "Lamb",
           "Adadelta", "Adamax"]


class SGD(Optimizer):
    def update(self, param, grad, slots, lr, step):
        return param - lr * grad, slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def init_slots(self, param):
        return {"velocity": jnp.zeros_like(param)}

    def update(self, param, grad, slots, lr, step):
        v = self._momentum * slots["velocity"] + grad
        if self._nesterov:
            new_param = param - lr * (grad + self._momentum * v)
        else:
            new_param = param - lr * v
        return new_param, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def init_slots(self, param):
        return {"moment": jnp.full_like(param, self._init_acc)}

    def update(self, param, grad, slots, lr, step):
        m = slots["moment"] + grad * grad
        new_param = param - lr * grad / (jnp.sqrt(m) + self._epsilon)
        return new_param, {"moment": m}


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, parameters=None,
                 weight_decay=None, grad_clip=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def init_slots(self, param):
        s = {"mean_square": jnp.zeros_like(param),
             "momentum": jnp.zeros_like(param)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(param)
        return s

    def update(self, param, grad, slots, lr, step):
        ms = self._rho * slots["mean_square"] + (1 - self._rho) * grad * grad
        out = dict(slots)
        out["mean_square"] = ms
        if self._centered:
            mg = self._rho * slots["mean_grad"] + (1 - self._rho) * grad
            out["mean_grad"] = mg
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * slots["momentum"] + lr * grad / denom
        out["momentum"] = mom
        return param - mom, out


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def init_slots(self, param):
        # moments in fp32 for bf16 params: TPU-native mixed precision
        mdt = jnp.float32 if param.dtype in (jnp.bfloat16, jnp.float16) else param.dtype
        return {"moment1": jnp.zeros(param.shape, mdt),
                "moment2": jnp.zeros(param.shape, mdt)}

    def update(self, param, grad, slots, lr, step):
        b1, b2 = self._beta1, self._beta2
        gf = grad.astype(slots["moment1"].dtype)
        m = b1 * slots["moment1"] + (1 - b1) * gf
        v = b2 * slots["moment2"] + (1 - b2) * gf * gf
        # bias correction with traced step
        step_f = jnp.asarray(step, jnp.float32)
        m_hat = m / (1 - jnp.power(b1, step_f))
        v_hat = v / (1 - jnp.power(b2, step_f))
        upd = (lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)).astype(param.dtype)
        return param - upd, {"moment1": m, "moment2": v}


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision)
        self._decoupled_wd = weight_decay
        self._apply_decay_param_fun = apply_decay_param_fun

    def _apply_decay(self, param, grad):
        return grad  # decoupled — applied in update

    def update(self, param, grad, slots, lr, step):
        new_param, new_slots = super().update(param, grad, slots, lr, step)
        wd = self._decoupled_wd
        if wd:
            new_param = new_param - (lr * wd) * param
        return new_param, new_slots


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_slots(self, param):
        return {"moment1": jnp.zeros_like(param, jnp.float32),
                "moment2": jnp.zeros_like(param, jnp.float32)}

    def update(self, param, grad, slots, lr, step):
        b1, b2 = self._beta1, self._beta2
        gf = grad.astype(jnp.float32)
        pf = param.astype(jnp.float32)
        m = b1 * slots["moment1"] + (1 - b1) * gf
        v = b2 * slots["moment2"] + (1 - b2) * gf * gf
        step_f = jnp.asarray(step, jnp.float32)
        m_hat = m / (1 - jnp.power(b1, step_f))
        v_hat = v / (1 - jnp.power(b2, step_f))
        r = m_hat / (jnp.sqrt(v_hat) + self._epsilon) + self._wd * pf
        p_norm = jnp.linalg.norm(pf)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
        return (pf - lr * trust * r).astype(param.dtype), {"moment1": m, "moment2": v}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._rho = rho

    def init_slots(self, param):
        return {"avg_squared_grad": jnp.zeros_like(param),
                "avg_squared_update": jnp.zeros_like(param)}

    def update(self, param, grad, slots, lr, step):
        rho, eps = self._rho, self._epsilon
        asg = rho * slots["avg_squared_grad"] + (1 - rho) * grad * grad
        upd = grad * jnp.sqrt(slots["avg_squared_update"] + eps) / jnp.sqrt(asg + eps)
        asu = rho * slots["avg_squared_update"] + (1 - rho) * upd * upd
        return param - lr * upd, {"avg_squared_grad": asg, "avg_squared_update": asu}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def init_slots(self, param):
        return {"moment": jnp.zeros_like(param), "inf_norm": jnp.zeros_like(param)}

    def update(self, param, grad, slots, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment"] + (1 - b1) * grad
        u = jnp.maximum(b2 * slots["inf_norm"], jnp.abs(grad))
        step_f = jnp.asarray(step, jnp.float32)
        new_param = param - (lr / (1 - jnp.power(b1, step_f))) * m / (u + self._epsilon)
        return new_param, {"moment": m, "inf_norm": u}
