"""Profiler (parity: paddle/fluid/platform/profiler/ + python/paddle/profiler/).

Host-side RecordEvent tracing with chrome-trace export, composed with jax's
device profiler (which captures XLA/TPU activity the way CUPTI captures
kernels for the reference).
"""
from .profiler import Profiler, RecordEvent, export_chrome_tracing  # noqa: F401
from .timer import Benchmark  # noqa: F401
