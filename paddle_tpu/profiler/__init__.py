"""Profiler (parity: paddle/fluid/platform/profiler/ + python/paddle/profiler/).

Host-side RecordEvent tracing with chrome-trace export, composed with jax's
device profiler (which captures XLA/TPU activity the way CUPTI captures
kernels for the reference).  Step-aware scheduling (``make_scheduler``)
and metric counter tracks come from the paddle_tpu.observability layer.
"""
from .profiler import (  # noqa: F401
    Profiler,
    ProfilerState,
    ProfilerTarget,
    RecordEvent,
    export_chrome_tracing,
    make_scheduler,
)
from .timer import Benchmark  # noqa: F401
