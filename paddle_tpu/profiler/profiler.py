"""Host tracer + device profiler bridge.

Parity: platform/profiler/profiler.h:43 ``Profiler`` (HostTracer + CudaTracer
→ NodeTrees → ChromeTracingLogger) and python/paddle/profiler/profiler.py:270.

TPU design: host events are recorded in a ring buffer (HostEventRecorder
analog); device-side activity is captured by jax.profiler (XLA's tracer —
the CUPTI analog), exported as TensorBoard trace.  ``export_chrome_tracing``
writes the host events in chrome-trace JSON.

Step-aware profiling (reference ``make_scheduler``,
python/paddle/profiler/profiler.py:115): ``Profiler.step()`` marks batch
boundaries.  With a scheduler — ``make_scheduler(closed=, ready=,
record=, repeat=)`` or the torch-style aliases ``wait/warmup/active`` —
recording windows open and close on exact step numbers: CLOSED drops
events, READY runs the tracer but discards (tracer warmup), RECORD
keeps, and the last step of each window (RECORD_AND_RETURN) drains the
span and fires ``on_trace_ready``.  Every recorded step also emits a
step-boundary instant event and one chrome counter event (``"ph": "C"``)
per gauge in the default MetricsRegistry, so host spans, step marks and
e.g. page-pool occupancy land in one Perfetto timeline.
"""
from __future__ import annotations

import functools
import json
import os
import threading
import time

__all__ = ["Profiler", "ProfilerState", "RecordEvent",
           "export_chrome_tracing", "make_scheduler", "ProfilerTarget"]


class ProfilerTarget:
    CPU = "cpu"
    TPU = "tpu"


class ProfilerState:
    """Scheduler verdict for one step (reference ProfilerState enum)."""

    CLOSED = "closed"
    READY = "ready"
    RECORD = "record"
    RECORD_AND_RETURN = "record_and_return"   # last step of a window


def make_scheduler(*, closed=None, ready=None, record=None, repeat=0,
                   skip_first=0, wait=None, warmup=None, active=None):
    """Step-number → ProfilerState policy (reference
    python/paddle/profiler/profiler.py:115 ``make_scheduler``; the
    torch-style ``wait``/``warmup``/``active`` names are aliases for
    ``closed``/``ready``/``record``).

    After ``skip_first`` steps the cycle ``closed + ready + record``
    repeats ``repeat`` times (0 = forever): CLOSED steps drop events,
    READY steps run the tracer but their events are discarded (warmup),
    RECORD steps keep events, and the final RECORD step of each cycle is
    RECORD_AND_RETURN — the Profiler drains the window and fires
    ``on_trace_ready`` there."""
    closed = wait if closed is None else closed
    ready = warmup if ready is None else ready
    record = active if record is None else record
    closed, ready = int(closed or 0), int(ready or 0)
    if record is None or int(record) <= 0:
        raise ValueError("make_scheduler: record/active must be >= 1")
    record = int(record)
    cycle = closed + ready + record

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        step = step - skip_first
        if repeat and step >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = step % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


class _HostEventRecorder:
    """Ring of typed events: ("X", name, start_ns, end_ns, tid) spans,
    ("i", name, ts_ns, tid) instants, ("C", name, ts_ns, value) counter
    samples."""

    def __init__(self):
        self.events = []        # guarded-by: self.lock
        self.lock = threading.Lock()
        # lock-free sticky flag: record paths read it unlocked by
        # design (a stale read costs one dropped/extra event, never a
        # torn structure)
        self.enabled = False

    def record(self, name, start_ns, end_ns, tid):
        if not self.enabled:
            return
        with self.lock:
            self.events.append(("X", name, start_ns, end_ns, tid))

    def record_instant(self, name, ts_ns, tid):
        if not self.enabled:
            return
        with self.lock:
            self.events.append(("i", name, ts_ns, tid))

    def record_counter(self, name, ts_ns, value):
        if not self.enabled:
            return
        with self.lock:
            self.events.append(("C", name, ts_ns, float(value)))

    def drain(self):
        with self.lock:
            out, self.events = self.events, []
        return out


_recorder = _HostEventRecorder()


class RecordEvent:
    """Scoped host event (parity: platform::RecordEvent, event_tracing.h).

    Context manager, begin()/end() pair, or decorator::

        @RecordEvent("my_op")
        def my_op(...): ...
    """

    def __init__(self, name, event_type="UserDefined"):
        self.name = name
        self._start = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def __call__(self, fn):
        # decorator form: a FRESH scope per invocation (self carries
        # per-entry state, so reusing it would break reentrancy)
        name = self.name

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with RecordEvent(name):
                return fn(*args, **kwargs)

        return wrapper

    def begin(self):
        self._start = time.perf_counter_ns()

    def end(self):
        if self._start is None:
            return
        _recorder.record(self.name, self._start, time.perf_counter_ns(),
                         threading.get_ident())
        self._start = None


class Profiler:
    """``scheduler`` may be None (record everything between start/stop),
    a callable step→ProfilerState, or a ``(wait, warmup, active, repeat)``
    tuple passed through :func:`make_scheduler`.  ``emit_counters``
    samples every gauge of the default MetricsRegistry into the trace at
    each recorded ``step()``."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, with_device=True, emit_counters=True):
        self.targets = targets or [ProfilerTarget.CPU, ProfilerTarget.TPU]
        self.on_trace_ready = on_trace_ready
        self.with_device = with_device and ProfilerTarget.TPU in self.targets
        self.emit_counters = emit_counters
        if isinstance(scheduler, (tuple, list)):
            wait, warmup, active = scheduler[:3]
            repeat = scheduler[3] if len(scheduler) > 3 else 0
            scheduler = make_scheduler(wait=wait, warmup=warmup,
                                       active=active, repeat=repeat)
        self.scheduler = scheduler
        self._device_dir = None
        self._events = []
        self._step_num = 0
        self._state = ProfilerState.CLOSED

    # ---- lifecycle ------------------------------------------------------
    def start(self):
        self._events = []
        self._step_num = 0
        _recorder.drain()
        self._state = (self.scheduler(0) if self.scheduler
                       else ProfilerState.RECORD)
        _recorder.enabled = self._state != ProfilerState.CLOSED
        if _recorder.enabled:
            self._mark_step()
        if self.with_device:
            import tempfile

            import jax

            self._device_dir = tempfile.mkdtemp(prefix="pt_prof_")
            try:
                # lint-ok: span-discipline jax.profiler.start_trace is
                # the device profiler (returns None), closed by
                # jax.profiler.stop_trace() in stop() — not a tracer span
                jax.profiler.start_trace(self._device_dir)
            except Exception:
                self._device_dir = None

    def stop(self):
        pending = _recorder.drain()
        if self._state in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN):
            self._events.extend(pending)
        _recorder.enabled = False
        self._state = ProfilerState.CLOSED
        if self._device_dir is not None:
            import jax

            try:
                jax.profiler.stop_trace()
            except Exception:
                pass    # silent-ok: device trace may already be stopped
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # ---- step machine ---------------------------------------------------
    def _mark_step(self):
        now = time.perf_counter_ns()
        _recorder.record_instant(f"ProfilerStep#{self._step_num}", now,
                                 threading.get_ident())
        if self.emit_counters:
            from ..observability.metrics import default_registry

            for name, value in default_registry().gauges():
                _recorder.record_counter(name, now, value)

    def step(self):
        """Mark a step boundary and advance the scheduler.

        Without a scheduler this records the step instant + gauge counter
        samples (always-recording session).  With one, it drives the
        CLOSED→READY→RECORD window machine; leaving a window (the
        RECORD_AND_RETURN step) drains the span into the profiler and
        fires ``on_trace_ready``."""
        if self.scheduler is None:
            self._step_num += 1
            if _recorder.enabled:
                self._mark_step()
            return

        prev = self._state
        if prev == ProfilerState.RECORD_AND_RETURN:
            # window complete: keep its events, hand the trace over
            self._events.extend(_recorder.drain())
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)
        self._step_num += 1
        state = self.scheduler(self._step_num)
        if prev == ProfilerState.READY and state in (
                ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            _recorder.drain()                 # discard tracer warmup
        if prev in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) \
                and state in (ProfilerState.CLOSED, ProfilerState.READY):
            self._events.extend(_recorder.drain())
        self._state = state
        _recorder.enabled = state != ProfilerState.CLOSED
        if state in (ProfilerState.RECORD,
                     ProfilerState.RECORD_AND_RETURN):
            self._mark_step()

    @property
    def current_state(self):
        return self._state

    @property
    def step_num(self):
        return self._step_num

    # ---- output ---------------------------------------------------------
    def export(self, path, format="json"):  # noqa: A002
        export_events_chrome(self._events, path)

    def summary(self, sorted_by="total", detail=True):
        agg = {}
        for ev in self._events:
            if ev[0] != "X":
                continue
            _, name, s, e, _tid = ev
            tot, cnt = agg.get(name, (0, 0))
            agg[name] = (tot + (e - s), cnt + 1)
        rows = sorted(agg.items(), key=lambda kv: -kv[1][0])
        lines = [f"{'Name':<40} {'Calls':>8} {'Total(ms)':>12} {'Avg(us)':>10}"]
        for name, (tot, cnt) in rows:
            lines.append(f"{name:<40} {cnt:>8} {tot/1e6:>12.3f} {tot/1e3/max(cnt,1):>10.1f}")
        return "\n".join(lines)

    @property
    def device_trace_dir(self):
        return self._device_dir


def export_events_chrome(events, path, thread_names=None):
    """Chrome-trace JSON: "X" spans, "i" step instants, "C" counter
    tracks, plus process_name/thread_name metadata ("M") so Perfetto
    labels the tracks instead of showing raw pids/tids.

    ``thread_names`` ({tid: label}) overrides the default "host thread
    N" track labels — the tracing flight recorder uses one track per
    request (tid = trace id) labelled "request#N"."""
    pid = os.getpid()
    thread_names = thread_names or {}
    trace = {"traceEvents": [{
        "name": "process_name", "ph": "M", "pid": pid,
        "args": {"name": f"paddle_tpu host (pid {pid})"},
    }]}
    tids = set()
    for ev in events:
        kind = ev[0]
        if kind == "X":
            _, name, start_ns, end_ns, tid = ev
            tids.add(tid)
            trace["traceEvents"].append({
                "name": name, "ph": "X", "ts": start_ns / 1000.0,
                "dur": (end_ns - start_ns) / 1000.0, "pid": pid, "tid": tid,
                "cat": "host",
            })
        elif kind == "i":
            _, name, ts_ns, tid = ev
            tids.add(tid)
            trace["traceEvents"].append({
                "name": name, "ph": "i", "ts": ts_ns / 1000.0, "pid": pid,
                "tid": tid, "s": "p", "cat": "step",
            })
        elif kind == "C":
            _, name, ts_ns, value = ev
            trace["traceEvents"].append({
                "name": name, "ph": "C", "ts": ts_ns / 1000.0, "pid": pid,
                "args": {name: value}, "cat": "metrics",
            })
    for tid in sorted(tids):
        trace["traceEvents"].append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": thread_names.get(tid,
                                              f"host thread {tid}")},
        })
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    # lint-ok: atomic-writes chrome-trace export is a re-recordable
    # log artifact, not durable state — a torn trace is cosmetic
    with open(path, "w") as f:
        json.dump(trace, f)


def export_chrome_tracing(dir_name, worker_name=None):
    """Returns an on_trace_ready callback (parity:
    python/paddle/profiler/profiler.py:158)."""

    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        prof.export(os.path.join(dir_name, f"{name}.json"))

    return handler
