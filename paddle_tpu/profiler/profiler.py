"""Host tracer + device profiler bridge.

Parity: platform/profiler/profiler.h:43 ``Profiler`` (HostTracer + CudaTracer
→ NodeTrees → ChromeTracingLogger) and python/paddle/profiler/profiler.py:270.

TPU design: host events are recorded in a ring buffer (HostEventRecorder
analog); device-side activity is captured by jax.profiler (XLA's tracer —
the CUPTI analog), exported as TensorBoard trace.  ``export_chrome_tracing``
writes the host events in chrome-trace JSON.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time

__all__ = ["Profiler", "RecordEvent", "export_chrome_tracing", "ProfilerTarget"]


class ProfilerTarget:
    CPU = "cpu"
    TPU = "tpu"


class _HostEventRecorder:
    def __init__(self):
        self.events = []
        self.lock = threading.Lock()
        self.enabled = False

    def record(self, name, start_ns, end_ns, tid):
        if not self.enabled:
            return
        with self.lock:
            self.events.append((name, start_ns, end_ns, tid))

    def drain(self):
        with self.lock:
            out, self.events = self.events, []
        return out


_recorder = _HostEventRecorder()


class RecordEvent:
    """Scoped host event (parity: platform::RecordEvent, event_tracing.h)."""

    def __init__(self, name, event_type="UserDefined"):
        self.name = name
        self._start = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def begin(self):
        self._start = time.perf_counter_ns()

    def end(self):
        if self._start is None:
            return
        _recorder.record(self.name, self._start, time.perf_counter_ns(),
                         threading.get_ident())
        self._start = None


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, with_device=True):
        self.targets = targets or [ProfilerTarget.CPU, ProfilerTarget.TPU]
        self.on_trace_ready = on_trace_ready
        self.with_device = with_device and ProfilerTarget.TPU in self.targets
        self._device_dir = None
        self._events = []

    def start(self):
        _recorder.enabled = True
        _recorder.drain()
        if self.with_device:
            import tempfile

            import jax

            self._device_dir = tempfile.mkdtemp(prefix="pt_prof_")
            try:
                jax.profiler.start_trace(self._device_dir)
            except Exception:
                self._device_dir = None

    def stop(self):
        _recorder.enabled = False
        self._events = _recorder.drain()
        if self._device_dir is not None:
            import jax

            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def step(self):
        pass

    def export(self, path, format="json"):  # noqa: A002
        export_events_chrome(self._events, path)

    def summary(self, sorted_by="total", detail=True):
        agg = {}
        for name, s, e, _ in self._events:
            tot, cnt = agg.get(name, (0, 0))
            agg[name] = (tot + (e - s), cnt + 1)
        rows = sorted(agg.items(), key=lambda kv: -kv[1][0])
        lines = [f"{'Name':<40} {'Calls':>8} {'Total(ms)':>12} {'Avg(us)':>10}"]
        for name, (tot, cnt) in rows:
            lines.append(f"{name:<40} {cnt:>8} {tot/1e6:>12.3f} {tot/1e3/max(cnt,1):>10.1f}")
        return "\n".join(lines)

    @property
    def device_trace_dir(self):
        return self._device_dir


def export_events_chrome(events, path):
    trace = {"traceEvents": []}
    for name, start_ns, end_ns, tid in events:
        trace["traceEvents"].append({
            "name": name, "ph": "X", "ts": start_ns / 1000.0,
            "dur": (end_ns - start_ns) / 1000.0, "pid": os.getpid(), "tid": tid,
            "cat": "host",
        })
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)


def export_chrome_tracing(dir_name, worker_name=None):
    """Returns an on_trace_ready callback (parity:
    python/paddle/profiler/profiler.py:158)."""

    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        prof.export(os.path.join(dir_name, f"{name}.json"))

    return handler
