"""Benchmark timer (parity: python/paddle/profiler/timer.py:325 ``Benchmark``).

Reports steady-state ips (items/sec) skipping warmup, plus reader cost —
the in-repo throughput-metric mechanism used by every model benchmark.
"""
from __future__ import annotations

import time

__all__ = ["Benchmark"]


class _StepInfo:
    def __init__(self):
        self.reader_cost = 0.0
        self.batch_cost = 0.0
        self.samples = 0
        self.steps = 0

    @property
    def ips(self):
        return self.samples / self.batch_cost if self.batch_cost > 0 else 0.0


class Benchmark:
    def __init__(self, warmup_steps: int = 10):
        self.warmup_steps = warmup_steps
        self.reset()

    def reset(self):
        self._step = 0
        self._reader_start = None
        self._batch_start = None
        self._pending_reader_cost = 0.0
        self._info = _StepInfo()

    def before_reader(self):
        self._reader_start = time.perf_counter()

    def after_reader(self):
        if self._reader_start is None:
            return
        # stash; step_end commits reader + batch cost under ONE warmup
        # test, so no call-order/convention skew can make a boundary step
        # contribute reader cost but not batch cost (or vice versa)
        self._pending_reader_cost += time.perf_counter() - self._reader_start
        self._reader_start = None

    def step_start(self):
        self._batch_start = time.perf_counter()

    def step_end(self, num_samples=1):
        if self._batch_start is None:
            return
        cost = time.perf_counter() - self._batch_start
        reader_cost, self._pending_reader_cost = \
            self._pending_reader_cost, 0.0
        self._step += 1
        if self._step > self.warmup_steps:
            self._info.reader_cost += reader_cost
            self._info.batch_cost += cost
            self._info.samples += num_samples
            self._info.steps += 1

    def step_info(self, unit="samples"):
        """Steady-state reader/step breakdown as a dict — the
        programmatic surface (goodput accounting and bench consume the
        totals; nothing should re-parse a formatted string).  Averages
        are per counted step; ``*_total`` fields are cumulative seconds
        over the counted (post-warmup) window."""
        i = self._info
        span = i.reader_cost + i.batch_cost
        return {
            "ips": i.ips,
            "avg_batch_cost": i.batch_cost / i.steps if i.steps else 0.0,
            "reader_cost": i.reader_cost / i.steps if i.steps else 0.0,
            "steps": i.steps,
            "unit": f"{unit}/sec",
            "samples": i.samples,
            "batch_cost_total": i.batch_cost,
            "reader_cost_total": i.reader_cost,
            "reader_ratio": i.reader_cost / span if span > 0 else 0.0,
        }

    def take_pending_reader_cost(self):
        """Return and clear reader time stashed by ``after_reader`` but
        not yet committed by ``step_end`` — callers that re-attribute a
        gap (e.g. the goodput accountant claiming epoch-end eval time)
        drain it here so the next step doesn't double-bill it."""
        pending, self._pending_reader_cost = self._pending_reader_cost, 0.0
        return pending

    @property
    def ips(self):
        return self._info.ips
