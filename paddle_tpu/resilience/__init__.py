"""paddle_tpu.resilience — correctness under failure.

The north star serves millions of users from preemptible TPU fleets;
this package is the fault boundary that makes that survivable:

- :mod:`.atomic` — the tmp+rename write primitive every durable file
  in the repo commits through (linted by
  ``tools/check_atomic_writes.py``).
- :mod:`.checkpoint_manager` — :class:`CheckpointManager`: atomic
  commit, per-shard CRC32, ``latest()`` discovery that skips torn or
  corrupt checkpoints, newest-intact fallback restore, ``keep_last_n``
  retention, optional background async save.
- :mod:`.faults` — deterministic seed-driven fault injection (named
  sites, off by default, env-gated via ``PADDLE_TPU_FAULTS``); drives
  the crash-consistency tests and counts every fired fault into the
  metrics registry.
- :mod:`.retry` — jittered exponential backoff (:func:`retry`,
  :func:`backoff_delays`) and :class:`Deadline`, adopted by the
  TCPStore client and the serving engine's per-request TTLs.
- :mod:`.integrity` — the silent-corruption sentinel:
  :func:`tree_fingerprint` per-leaf CRC32 digests compared across dp
  ranks over the TCPStore, sampled step-replay verification, and the
  ``param_divergence`` restore-and-replay repair
  (:class:`IntegrityCallback`, exported lazily to keep the layer
  stack acyclic).
- :mod:`.supervisor` — :class:`TrainingSupervisor`: runs the trainer
  as a watched child process and autonomously relaunches it (jittered
  backoff, ``max_restarts`` budget, elastic-membership rendezvous),
  resuming from the newest intact checkpoint — preemption-to-resume
  with zero operator action.

Consumers: ``framework_io.save`` and ``jit.save`` write atomically;
``distributed.checkpoint`` checksums shards and exposes kill sites;
``hapi.CheckpointCallback`` + ``Model.fit(resume_from=...)`` make a
killed training run continue with a matching loss curve; the serving
engine sheds load at watermarks and evicts requests past deadline.
"""
from __future__ import annotations

from .atomic import CRC32Writer, atomic_write  # noqa: F401
from .checkpoint_manager import (  # noqa: F401
    CheckpointAuditError,
    CheckpointManager,
    verify_checkpoint,
)
from .faults import (  # noqa: F401
    FaultInjector,
    FaultSpec,
    SimulatedCrash,
    current_injector,
    fault_point,
    injected_faults,
    install,
    install_from_env,
    uninstall,
)
from .retry import Deadline, RetryError, backoff_delays, retry  # noqa: F401
from .supervisor import (  # noqa: F401
    ENV_ATTEMPT,
    ENV_RESUME_DIR,
    TrainingSupervisor,
)

__all__ = [
    "atomic_write", "CRC32Writer",
    "CheckpointManager", "CheckpointAuditError", "verify_checkpoint",
    "IntegrityCallback", "tree_fingerprint", "compare_digests",
    "FaultInjector", "FaultSpec", "SimulatedCrash", "fault_point",
    "install", "uninstall", "current_injector", "injected_faults",
    "install_from_env",
    "Deadline", "RetryError", "backoff_delays", "retry",
    "TrainingSupervisor", "ENV_RESUME_DIR", "ENV_ATTEMPT",
]

_INTEGRITY_NAMES = {"IntegrityCallback", "tree_fingerprint",
                    "compare_digests", "first_divergent_leaf",
                    "majority_partition"}


def __getattr__(name):
    # integrity's sentinel callback needs the hapi hook surface (via
    # observability.goodput); importing it lazily keeps this package
    # importable from the bottom of the layer stack
    if name in _INTEGRITY_NAMES:
        from . import integrity

        return getattr(integrity, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


# env-gated fault injection: inert unless PADDLE_TPU_FAULTS is set
install_from_env()
