"""Atomic file writes — the repo's single tmp+rename commit primitive.

Every durable artifact (checkpoints, ``paddle_tpu.save`` blobs, jit
export bundles) goes through :func:`atomic_write`: bytes land in a
``<name>.tmp.<pid>`` sibling and ``os.replace`` publishes them, so a
crash at ANY byte offset leaves either the old complete file or no file
— never a torn one.  ``tools/check_atomic_writes.py`` lints that no
module under ``paddle_tpu/`` opens a file for writing outside this
helper (trace/log writers are allowlisted; losing half a trace is
annoying, losing half a checkpoint is an outage).

The writer optionally maintains a running CRC32 (``crc=True``) so
checkpoint shards get a checksum of the exact bytes written, with no
second read pass.  Each write passes through the named fault site
(default ``io.write``) before commit — the injection point for torn
writes, transient I/O errors, and kill-during-write.
"""
from __future__ import annotations

import contextlib
import os
import zlib

from .faults import fault_point

__all__ = ["atomic_write", "CRC32Writer"]


class CRC32Writer:
    """File-object proxy keeping a running CRC32 of everything written."""

    def __init__(self, f):
        self._f = f
        self.crc32 = 0

    def write(self, data):
        b = data.encode() if isinstance(data, str) else data
        self.crc32 = zlib.crc32(b, self.crc32)
        return self._f.write(data)

    def __getattr__(self, name):
        return getattr(self._f, name)


@contextlib.contextmanager
def atomic_write(path, mode="wb", site="io.write", fsync=False):
    """Yield a writer for ``path`` that commits via tmp + ``os.replace``.

    The yielded object is a :class:`CRC32Writer` (its ``.crc32`` holds
    the checksum of the committed bytes).  On any exception the target
    is untouched; the tmp file is left behind only for simulated
    crashes (real crashes can't clean up either — recovery must cope),
    and removed for ordinary errors so retries start clean.
    """
    if mode not in ("w", "wb"):
        raise ValueError(f"atomic_write only writes ({mode!r}); append "
                         "can't be made atomic by rename")
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    f = open(tmp, mode)
    writer = CRC32Writer(f)
    try:
        yield writer
        f.flush()
        if fsync:
            os.fsync(f.fileno())
        f.close()
        # the injection point: torn-write truncates tmp (then crashes),
        # io_error fires before the rename so the target stays intact
        fault_point(site, path=tmp)
        os.replace(tmp, path)
    except BaseException as e:
        if not f.closed:
            f.close()
        if isinstance(e, Exception):
            with contextlib.suppress(OSError):
                os.remove(tmp)
        raise
