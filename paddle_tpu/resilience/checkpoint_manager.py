"""Crash-safe checkpoint lifecycle over ``distributed.checkpoint``.

``save_sharded`` knows how to lay one pytree down as shard files + a
manifest; this module owns everything around that write that makes a
*sequence* of checkpoints survive being killed at any instant:

- **atomic commit** — each save lands in ``<dir>/step_<n>.tmp`` (shards
  checksummed, manifest written last, itself via tmp+rename), then ONE
  ``os.replace`` publishes the directory as ``<dir>/step_<n>``.  There
  is no moment at which a reader can see a half-written checkpoint
  under a committed name.
- **checksums** — every shard's CRC32 (of the exact bytes written) is
  recorded in the manifest; :func:`verify_checkpoint` recomputes them,
  and restore refuses a checkpoint whose bytes rotted after commit.
- **discovery** — :meth:`CheckpointManager.latest` scans for committed
  steps, skipping ``.tmp`` leftovers and (with ``verify=True``)
  corrupt directories.
- **fallback restore** — :meth:`CheckpointManager.restore` walks
  newest→oldest until a checkpoint passes verification, so one damaged
  checkpoint degrades recovery by one save interval, not to zero.
- **retention** — ``keep_last_n`` garbage-collects old committed steps
  after each successful commit (tmp droppings from crashed saves are
  swept opportunistically too).
- **audit-on-save** — ``save(..., verify=True)`` (or
  ``verify_on_save=True`` on the manager) re-reads the committed
  shards and re-checks every manifest CRC *before* retention GC runs.
  A save whose bytes rotted between write and commit (controller
  bitflip, lying disk cache) raises :class:`CheckpointAuditError` with
  the old checkpoints untouched — a corrupted save can never become
  the only restore candidate.
- **discard** — :meth:`CheckpointManager.discard_after` removes
  committed checkpoints NEWER than a step: the integrity sentinel's
  restore-and-replay repair uses it to drop saves taken after a silent
  corruption (intact CRC-wise, numerically poisoned), so a crash
  mid-repair can't resume from one of them.
- **async save** — ``async_save=True`` snapshots the tree to host
  memory synchronously and writes + commits on a background thread;
  :meth:`wait` joins it and re-raises its failure.  The training
  thread pays device→host copy time, not disk time.  The snapshot is
  a *deep* copy taken before the handoff: host-resident numpy leaves
  are copied (``jax.device_get`` passes them through by reference)
  and device arrays land in fresh host buffers, so a trainer that
  immediately mutates or donates the live tree on the next step never
  races the background write.

Fault sites (see ``resilience.faults``): ``checkpoint.before_shard``,
``checkpoint.shard_write``, ``checkpoint.before_manifest``,
``checkpoint.manifest_write``, ``checkpoint.before_commit``,
``checkpoint.after_commit``.
"""
from __future__ import annotations

import os
import re
import shutil
import threading
import zlib

from ..observability.profiling import phase as profiling_phase
from .faults import fault_point

__all__ = ["CheckpointManager", "CheckpointAuditError",
           "verify_checkpoint"]

_STEP_RE = re.compile(r"^step_(\d+)$")


class CheckpointAuditError(RuntimeError):
    """A just-committed checkpoint failed its post-commit audit
    (``save(verify=True)``).  The previous good checkpoints were NOT
    garbage-collected."""

    def __init__(self, step, errors):
        super().__init__(
            f"checkpoint step {step} failed post-commit audit: "
            + "; ".join(errors) + " — old checkpoints were not GC'd")
        self.step = int(step)
        self.errors = list(errors)


def _step_dirname(step):
    return f"step_{int(step):010d}"


def _file_crc32(path, chunk=1 << 20):
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return crc
            crc = zlib.crc32(b, crc)


def verify_checkpoint(path):
    """Recompute every shard's CRC32 against the manifest.

    Returns ``(ok, errors)``; a checkpoint with no manifest, a missing
    shard file, or any checksum mismatch fails.  Shard entries written
    before checksums existed (no ``crc32`` key) are accepted — age is
    not corruption."""
    from ..distributed.checkpoint import _load_manifest

    errors = []
    try:
        manifest = _load_manifest(path)
    except (OSError, ValueError) as e:
        return False, [f"manifest unreadable: {e}"]
    for leaf in manifest.get("leaves", []):
        for sh in leaf["shards"]:
            fpath = os.path.join(path, leaf["id"], sh["file"])
            want = sh.get("crc32")
            try:
                got = _file_crc32(fpath)
            except OSError as e:
                errors.append(f"{leaf['path']}/{sh['file']}: {e}")
                continue
            if want is not None and got != want:
                errors.append(
                    f"{leaf['path']}/{sh['file']}: crc32 {got:#010x} != "
                    f"manifest {want:#010x}")
    return not errors, errors


def _host_snapshot(tree):
    """Deep device→host copy of a checkpoint tree.

    ``jax.device_get`` copies device arrays into fresh host buffers but
    returns host numpy arrays *by reference* (and on CPU backends may
    hand back a read-only view of the very buffer the trainer will
    donate to the next step).  Every array leaf here ends up in memory
    the background writer exclusively owns."""
    import jax
    import numpy as np

    def leaf(x):
        if isinstance(x, np.ndarray):
            return np.array(x, copy=True)
        if isinstance(x, jax.Array):
            out = np.asarray(jax.device_get(x))
            if not out.flags.owndata or not out.flags.writeable:
                out = np.array(out, copy=True)
            return out
        return x

    return jax.tree_util.tree_map(leaf, tree)


class CheckpointManager:
    """Atomic, checksummed, retained checkpoints under one directory."""

    def __init__(self, directory, keep_last_n=None, async_save=False,
                 sweep_orphans=True, verify_on_save=False, barrier=None):
        self.directory = os.fspath(directory)
        self.keep_last_n = keep_last_n
        self.async_save = bool(async_save)
        self.verify_on_save = bool(verify_on_save)
        # multi-host: a distributed.checkpoint.CommitBarrier makes the
        # step-directory rename rank-0-only and gated on every rank's
        # shard-CRC ack — latest() is then globally consistent
        self._barrier = barrier
        # _thread is owned by the training thread (save/wait only);
        # _error crosses from the background save thread into wait()
        self._lock = threading.Lock()
        self._thread = None
        self._error = None      # guarded-by: self._lock
        os.makedirs(self.directory, exist_ok=True)
        if barrier is not None and barrier.rank != 0:
            # only the committing rank may mutate shared directories
            # outside its own shard files
            sweep_orphans = False
        if sweep_orphans:
            # reclaim step_N.tmp debris from a save killed mid-write in
            # a PREVIOUS process (a crashed trainer's relaunch lands
            # here before any new save runs — without this, every
            # preemption leaks one tmp dir forever).  Only safe when no
            # other process is writing this directory; pass
            # sweep_orphans=False for read-side managers that may
            # coexist with a live trainer.
            self._sweep_tmp()

    # ------------------------------------------------------------ discovery
    def steps(self):
        """Committed step numbers, ascending (no verification)."""
        out = []
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return out
        for name in entries:
            m = _STEP_RE.match(name)
            if m and os.path.isdir(os.path.join(self.directory, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    def step_path(self, step):
        return os.path.join(self.directory, _step_dirname(step))

    def latest(self, verify=True):
        """Newest committed (and, with ``verify``, intact) step number,
        or None.  Corrupt/uncommitted directories are skipped, newest
        first — this is what a restarted trainer calls to find where to
        resume."""
        for step in reversed(self.steps()):
            if not verify:
                return step
            ok, _ = verify_checkpoint(self.step_path(step))
            if ok:
                return step
            self._count("checkpoint_corrupt_skipped_total")
        return None

    # --------------------------------------------------------------- save
    def save(self, tree, step, extra=None, verify=None):
        """Checkpoint ``tree`` as ``step``.  With ``async_save`` the
        device→host snapshot happens now and the write/commit happens on
        a background thread (a previous in-flight save is joined first,
        so saves never reorder).  ``verify=True`` (default: the
        manager's ``verify_on_save``) audits the committed bytes before
        GC — see :class:`CheckpointAuditError`; an async audit failure
        surfaces from :meth:`wait` / the next :meth:`save`."""
        verify = self.verify_on_save if verify is None else bool(verify)
        if not self.async_save:
            self.wait()
            self._write_and_commit(tree, step, extra, verify=verify)
            return self.step_path(step)
        # snapshot BEFORE joining the previous save: the caller's tree
        # is only guaranteed step-consistent right now — the join may
        # block on disk, the device→host copy must not wait for it
        host_tree = _host_snapshot(tree)
        self.wait()
        self._thread = threading.Thread(
            target=self._bg_save, args=(host_tree, step, extra, verify),
            name=f"ckpt-save-{step}", daemon=True)
        self._thread.start()
        return self.step_path(step)

    def _bg_save(self, tree, step, extra, verify=False):
        import time

        t0 = time.perf_counter()
        try:
            self._write_and_commit(tree, step, extra, verify=verify)
        except BaseException as e:          # surfaced by wait()/next save
            with self._lock:
                self._error = e
            return
        # the overlapped (off-training-thread) write time: compare with
        # the sync/async series the CheckpointCallback records to see
        # how much wall-clock async saving actually hides
        from ..observability.metrics import default_registry

        default_registry().histogram(
            "checkpoint_save_seconds",
            "checkpoint save duration by mode (sync/async block the "
            "training thread; background is the overlapped write)",
            labelnames=("mode",),
        ).labels(mode="background").observe(time.perf_counter() - t0)

    def wait(self):
        """Join an in-flight async save; re-raise its failure here (the
        training thread is the one that must learn the save died)."""
        t, self._thread = self._thread, None
        if t is not None:
            t.join()
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    def _write_and_commit(self, tree, step, extra, verify=False):
        # both the sync and the background save path funnel here: mark
        # the window for the sampling profiler's phase attribution
        with profiling_phase("checkpoint"):
            return self._write_and_commit_inner(tree, step, extra,
                                                verify=verify)

    def _write_and_commit_inner(self, tree, step, extra, verify=False):
        from ..distributed.checkpoint import save_sharded

        if self._barrier is not None:
            return self._write_and_commit_multihost(tree, step, extra,
                                                    verify)
        final = self.step_path(step)
        tmp = final + ".tmp"
        if os.path.exists(tmp):             # debris from a crashed save
            shutil.rmtree(tmp)
        save_sharded(tmp, tree, step=int(step), extra=extra)
        fault_point("checkpoint.before_commit", path=tmp)
        if os.path.isdir(final):
            # re-saving a step that already exists on disk is legitimate
            # (restore fell back past a corrupt step N and retrained to
            # it, or a crashed run's async save committed after the
            # trainer restored an older step): the new bytes supersede.
            # os.replace cannot rename over a non-empty dir, so clear it
            # — a crash in between costs only this one step; older
            # committed steps still restore.
            shutil.rmtree(final)
        os.replace(tmp, final)              # THE commit point
        fault_point("checkpoint.after_commit", path=final)
        self._count("checkpoint_commits_total")
        if verify:
            # audit BEFORE retention: a save that fails its re-read
            # must never cause the good checkpoints to be GC'd
            ok, errors = verify_checkpoint(final)
            if not ok:
                self._count("checkpoint_audit_failures_total")
                raise CheckpointAuditError(step, errors)
        self._gc()

    def _write_and_commit_multihost(self, tree, step, extra, verify):
        """The barrier-gated save: every rank writes its addressable
        shards into ONE shared ``step_N.tmp``, acks its shard CRCs,
        and rank 0 performs the directory rename only after the full
        ack set arrived — then (alone) audits and GCs.  A rank dying
        before its ack starves the barrier: rank 0 raises
        :class:`~paddle_tpu.distributed.checkpoint.CommitBarrierError`
        with the tmp directory never renamed, so ``latest()`` on every
        surviving rank still resolves the previous step."""
        from ..distributed.checkpoint import save_sharded

        b = self._barrier
        final = self.step_path(step)
        tmp = final + ".tmp"
        token = _step_dirname(step)

        def _prepare():
            if os.path.exists(tmp):         # debris from a crashed save
                shutil.rmtree(tmp)

        b.begin(token, prepare=_prepare)
        manifest = save_sharded(tmp, tree, step=int(step), extra=extra,
                                rank=b.rank)
        crcs = {f"{l['id']}/{s['file']}": s["crc32"]
                for l in manifest["leaves"] for s in l["shards"]}
        b.ack(token, crcs)

        def _commit():
            fault_point("checkpoint.before_commit", path=tmp)
            if os.path.isdir(final):
                shutil.rmtree(final)
            os.replace(tmp, final)          # THE commit point
        b.commit(token, fn=_commit)
        if b.rank != 0:
            return
        fault_point("checkpoint.after_commit", path=final)
        self._count("checkpoint_commits_total")
        if verify:
            ok, errors = verify_checkpoint(final)
            if not ok:
                self._count("checkpoint_audit_failures_total")
                raise CheckpointAuditError(step, errors)
        self._gc()

    # ------------------------------------------------------------- restore
    def restore(self, like_tree=None, step=None, verify=True,
                before_step=None):
        """Load the newest intact checkpoint (or exactly ``step``).

        Returns ``(step, tree, manifest)``; ``like_tree`` follows
        ``load_sharded`` semantics (sharded rebuild vs host dict).
        Walks back over corrupt checkpoints unless pinned to ``step``
        (an explicitly requested broken checkpoint should fail loudly).
        ``before_step`` bounds the walk to steps strictly below it —
        the health-rollback path uses it to refuse a checkpoint taken
        at (or after) the anomalous step itself, which is intact
        CRC-wise but numerically poisoned.
        Raises FileNotFoundError when nothing restorable exists."""
        from ..distributed.checkpoint import load_sharded

        candidates = [step] if step is not None else \
            [s for s in reversed(self.steps())
             if before_step is None or s < int(before_step)]
        last_err = None
        for s in candidates:
            path = self.step_path(s)
            if verify:
                ok, errors = verify_checkpoint(path)
                if not ok:
                    if step is not None:
                        raise ValueError(
                            f"checkpoint step {s} failed verification: "
                            + "; ".join(errors))
                    self._count("checkpoint_corrupt_skipped_total")
                    last_err = errors
                    continue
            tree, manifest = load_sharded(path, like_tree=like_tree)
            return s, tree, manifest
        detail = f" (newest candidate errors: {last_err})" if last_err \
            else ""
        raise FileNotFoundError(
            f"no intact checkpoint under {self.directory!r}{detail}")

    def discard_after(self, step):
        """Remove committed checkpoints STRICTLY NEWER than ``step``.

        The integrity repair path calls this after restoring a
        verified-good checkpoint: saves taken between the corruption
        and its detection pass CRC verification but hold poisoned
        numbers, and until the replay overwrites them they would be
        the newest restore candidates for any crash.  Returns the
        removed step numbers."""
        removed = []
        for s in self.steps():
            if s > int(step):
                shutil.rmtree(self.step_path(s), ignore_errors=True)
                removed.append(s)
                self._count("checkpoint_discarded_total")
        return removed

    # ----------------------------------------------------------- retention
    def _sweep_tmp(self):
        """Remove ``step_N.tmp`` debris from killed saves."""
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return
        for name in entries:
            if name.endswith(".tmp"):
                full = os.path.join(self.directory, name)
                # a foreign pid may still be writing; only sweep our
                # naming scheme's directories
                if _STEP_RE.match(name[:-4]) and os.path.isdir(full):
                    shutil.rmtree(full, ignore_errors=True)
                    self._count("checkpoint_tmp_swept_total")

    def _gc(self):
        self._sweep_tmp()
        if self.keep_last_n is None:
            return
        steps = self.steps()
        for s in steps[:max(0, len(steps) - int(self.keep_last_n))]:
            shutil.rmtree(self.step_path(s), ignore_errors=True)
            self._count("checkpoint_gc_removed_total")

    @staticmethod
    def _count(name):
        from ..observability.metrics import default_registry

        default_registry().counter(name).inc()
