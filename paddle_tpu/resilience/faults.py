"""Deterministic fault injection — the crash-consistency test driver.

Production TPU fleets treat preemption as routine (the reference's
elastic manager relaunches on ``ELASTIC_EXIT_CODE=101``); the only way
to know recovery works is to kill the process at every interesting
boundary and check what restore finds.  This module provides *named
fault sites* threaded through the I/O and checkpoint paths — each site
calls :func:`fault_point` with its name, and an installed
:class:`FaultInjector` decides (deterministically) whether to fire.

Fault kinds:

``kill``
    Raise :class:`SimulatedCrash` (a ``BaseException`` so ordinary
    ``except Exception`` recovery code can't swallow it — exactly like
    a SIGKILL, nothing downstream of the site runs).
``torn_write``
    Truncate the file named by the site's ``path`` (for a directory
    site, a seed-chosen file under it) to a seed-chosen fraction of
    its bytes, then crash — a torn write only matters when the process
    dies before completing it.
``io_error``
    Raise a transient ``OSError`` (recoverable: retry decorators and
    callers see a plain failure, the process survives).
``stall``
    Sleep ``stall_s`` seconds — an artificial host hiccup for deadline
    and watchdog paths.
``bitflip``
    Flip ONE seed-chosen bit and keep running — the silent-data-
    corruption fault (a cosmic ray, a marginal HBM cell, a desynced
    replica).  At a site passing ``tree=`` (a mutable ``{name: array}``
    dict), a seed-chosen leaf (or ``FaultSpec(leaf=...)``) is replaced
    with a one-bit-flipped copy; at a site passing ``path=``, one bit
    of the file (for a directory, of a seed-chosen file under it) is
    flipped in place.  Nothing is raised: detection is the integrity
    sentinel's job (``resilience.integrity``), not the injector's.
``poison_request``
    The query-of-death fault: at a site passing ``tokens=`` (an
    iterable of token-ID streams — the serving engine passes every
    in-flight request's tokens at ``serving.step``), raise
    :class:`PoisonRequestError` whenever any stream contains the
    spec's ``pattern`` as a contiguous subsequence (seed-chosen when
    unset).  Unlike every other kind it matches on *content*, not
    occurrence: the same poisoned prompt keeps killing every replica
    it is re-dispatched to, which is exactly the cascade the router's
    suspect-tracker / canary / quarantine machinery must contain.
    ``PoisonRequestError`` is deliberately an ``OSError``: from the
    fleet router's point of view a poisoned request crashes its
    replica the way a dead RPC peer does — attribution is the
    *router's* job (suspicion points, canary dispatch), never the
    dying engine's.

Everything is **off by default**: with no injector installed,
``fault_point`` is a dict lookup and a return.  Installation is
programmatic (:func:`install` / :func:`uninstall`, or the
:func:`injected_faults` context manager tests use) or via the
``PADDLE_TPU_FAULTS`` env var (``site:kind:occurrence[,...]``), read
once by :func:`install_from_env`.

Every fired fault increments ``faults_injected_total{site=,kind=}`` in
the default metrics registry, so a fault-injection run's telemetry
shows exactly what was injected where.  A fired fault also records a
``{site, kind, occurrence, seed}`` event on the **active span** (the
thread's ambient :func:`~paddle_tpu.observability.tracing.active_span`,
or an explicit ``fault_point(..., span=...)``) — a chaos-soak trace
shows *where* the fault landed inline, no cross-referencing the
counter; and the tracer's tail-retention policy pins every
fault-carrying trace in the ring.

Control-plane sites: the serving stack's data-plane sites
(``serving.admit``, ``serving.step``) are joined by the autoscaler's
control loop — ``autoscaler.poll`` fires at the top of every
:meth:`~paddle_tpu.serving.Autoscaler.tick` (a ``stall`` there is the
control loop hiccuping: scaling is delayed, never wrong) and
``autoscaler.scale_up`` fires before every spawn attempt (an
``io_error`` is a spawn that died mid-flight, retried with bounded
jittered backoff — the PR 6 supervisor discipline).  The chaos soak
harness (``bench.py --section soak``) exercises both alongside hard
replica kills as its standing kill matrix.
"""
from __future__ import annotations

import contextlib
import os
import time

__all__ = ["SimulatedCrash", "PoisonRequestError", "FAULT_KINDS",
           "FaultSpec", "FaultInjector", "fault_point",
           "install", "uninstall", "current_injector", "injected_faults",
           "install_from_env"]

#: every fault kind a FaultSpec may carry — tools/analysis's
#: fault-sites pass reads this tuple (by AST, not import) and requires
#: each kind to be exercised by at least one test
FAULT_KINDS = ("kill", "torn_write", "io_error", "stall", "bitflip",
               "poison_request")


class SimulatedCrash(BaseException):
    """An injected process death.  Deliberately NOT an ``Exception``:
    recovery code that catches ``Exception`` must not be able to
    "survive" a simulated SIGKILL."""

    def __init__(self, site, occurrence):
        super().__init__(f"simulated crash at fault site {site!r} "
                         f"(occurrence {occurrence})")
        self.site = site
        self.occurrence = occurrence


class PoisonRequestError(OSError):
    """A poison request killed the engine it was running on.

    Deliberately an ``OSError``: the fleet router's failure path treats
    it exactly like a crashed replica RPC, so attribution (suspicion
    points keyed by prompt hash, canary dispatch, quarantine) stays
    where the evidence is — above the replica that just died."""

    def __init__(self, site, pattern, occurrence):
        super().__init__(
            f"poison request at fault site {site!r}: token pattern "
            f"{tuple(pattern)!r} is aboard (occurrence {occurrence})")
        self.site = site
        self.pattern = tuple(pattern)
        self.occurrence = occurrence


class FaultSpec:
    """Fire ``kind`` at the ``occurrence``-th hit (1-based) of ``site``.

    ``torn_frac`` overrides the seed-derived truncation fraction for
    ``torn_write``; ``stall_s`` sets the ``stall`` duration; ``leaf``
    pins a ``bitflip`` to a named tree leaf and ``bit`` to an exact bit
    index (both seed-chosen when unset).  ``pattern`` (a token-ID
    tuple, seed-chosen when unset) is the ``poison_request`` trigger:
    that kind ignores ``occurrence`` and fires at EVERY hit of the
    site whose ``tokens=`` payload contains the pattern — a poisoned
    prompt is poisonous on every replica it reaches."""

    def __init__(self, site, kind="kill", occurrence=1, torn_frac=None,
                 stall_s=0.05, leaf=None, bit=None, pattern=None):
        if kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        self.site = site
        self.kind = kind
        self.occurrence = int(occurrence)
        self.torn_frac = torn_frac
        self.stall_s = stall_s
        self.leaf = leaf
        self.bit = bit
        self.pattern = None if pattern is None else tuple(
            int(t) for t in pattern)

    def __repr__(self):
        return (f"FaultSpec({self.site!r}, {self.kind!r}, "
                f"occurrence={self.occurrence})")


class FaultInjector:
    """Seed-driven injector: hit counts per site + the spec table.

    The seed drives only *fault shape* (torn-write truncation point),
    never *whether* a fault fires — firing is exact (site, occurrence)
    matching so a failing kill point reproduces from its test id alone.
    """

    def __init__(self, specs=(), seed=0):
        import numpy as np

        self.specs = list(specs)
        self.seed = int(seed)    # echoed into span fault events
        self._rng = np.random.default_rng(seed)
        self._hits = {}          # site -> total hits
        self._fired = []         # [(site, kind, occurrence)] audit log

    # ------------------------------------------------------------ counters
    def hits(self, site):
        return self._hits.get(site, 0)

    @property
    def fired(self):
        return list(self._fired)

    # ------------------------------------------------------------- firing
    def _record(self, site, kind, occ, span=None):
        self._fired.append((site, kind, occ))
        # lazy import: faults must be importable before the jax-adjacent
        # observability stack (and from tools that never touch it)
        from ..observability.metrics import default_registry
        from ..observability.tracing import active_span

        default_registry().counter(
            "faults_injected_total",
            help="faults fired by the resilience fault injector",
            labelnames=("site", "kind")).labels(site=site, kind=kind).inc()
        target = span if span is not None else active_span()
        if target is not None:
            # the trace-side audit record: retention classifies any
            # fault-carrying trace as always-keep
            target.attributes.setdefault("faults", []).append(
                {"site": site, "kind": kind, "occurrence": occ,
                 "seed": self.seed})

    def _file_of(self, path):
        """The file a path-targeted fault mutates: the path itself, or
        a seed-chosen file under a directory site (checkpoint commit
        sites pass the committed directory)."""
        if path is None or not os.path.exists(path):
            return None
        if not os.path.isdir(path):
            return path
        files = []
        for dirpath, _, names in os.walk(path):
            files.extend(os.path.join(dirpath, n) for n in sorted(names))
        files = sorted(f for f in files if os.path.getsize(f) > 0)
        if not files:
            return None
        return files[int(self._rng.integers(len(files)))]

    def _bitflip(self, spec, path=None, tree=None):
        import numpy as np

        if tree is not None:
            names = sorted(k for k, v in tree.items()
                           if getattr(v, "size", 0))
            if spec.leaf is not None and spec.leaf not in names:
                raise KeyError(f"bitflip leaf {spec.leaf!r} not in tree "
                               f"({names})")
            if not names:
                return
            name = spec.leaf if spec.leaf is not None else \
                names[int(self._rng.integers(len(names)))]
            arr = np.array(tree[name], copy=True)       # host, writable
            flat = arr.reshape(-1).view(np.uint8)
            bit = (spec.bit if spec.bit is not None
                   else int(self._rng.integers(flat.size * 8)))
            flat[bit // 8] ^= np.uint8(1 << (bit % 8))
            tree[name] = arr
            return
        target = self._file_of(path)
        if target is None:
            return
        size = os.path.getsize(target)
        bit = (spec.bit if spec.bit is not None
               else int(self._rng.integers(size * 8)))
        with open(target, "r+b") as f:
            f.seek(bit // 8)
            b = f.read(1)
            f.seek(bit // 8)
            f.write(bytes([b[0] ^ (1 << (bit % 8))]))

    def _poison_pattern(self, spec):
        """The spec's trigger pattern, seed-chosen (and cached on the
        spec) when the caller didn't pin one."""
        if spec.pattern is None:
            spec.pattern = tuple(
                int(t) for t in self._rng.integers(1, 1 << 15, size=3))
        return spec.pattern

    @staticmethod
    def _contains(stream, pattern):
        """Contiguous-subsequence match of ``pattern`` in ``stream``."""
        n, m = len(stream), len(pattern)
        if m == 0 or n < m:
            return False
        first = pattern[0]
        for i in range(n - m + 1):
            if stream[i] == first and \
                    tuple(stream[i:i + m]) == pattern:
                return True
        return False

    def on_fault_point(self, site, path=None, tree=None, span=None,
                       tokens=None):
        occ = self._hits.get(site, 0) + 1
        self._hits[site] = occ
        # poison_request matches on CONTENT, not occurrence: the same
        # poisoned token pattern fires at every hit of the site it is
        # aboard — re-dispatching the request to a fresh replica
        # re-arms the fault, which is the whole cascade
        if tokens is not None:
            for spec in self.specs:
                if spec.site != site or spec.kind != "poison_request":
                    continue
                pattern = self._poison_pattern(spec)
                if any(self._contains(list(stream), pattern)
                       for stream in tokens):
                    self._record(site, spec.kind, occ, span=span)
                    raise PoisonRequestError(site, pattern, occ)
        for spec in self.specs:
            if spec.site != site or spec.occurrence != occ \
                    or spec.kind == "poison_request":
                continue
            self._record(site, spec.kind, occ, span=span)
            if spec.kind == "kill":
                raise SimulatedCrash(site, occ)
            if spec.kind == "torn_write":
                target = self._file_of(path)
                if target is not None:
                    size = os.path.getsize(target)
                    frac = (spec.torn_frac if spec.torn_frac is not None
                            else float(self._rng.uniform(0.1, 0.9)))
                    with open(target, "r+b") as f:
                        f.truncate(max(0, int(size * frac)))
                raise SimulatedCrash(site, occ)
            if spec.kind == "io_error":
                raise OSError(f"injected transient I/O error at {site!r} "
                              f"(occurrence {occ})")
            if spec.kind == "stall":
                time.sleep(spec.stall_s)
            if spec.kind == "bitflip":
                self._bitflip(spec, path=path, tree=tree)


_injector: FaultInjector | None = None


def install(injector: FaultInjector):
    global _injector
    _injector = injector
    return injector


def uninstall():
    global _injector
    _injector = None


def current_injector():
    return _injector


@contextlib.contextmanager
def injected_faults(*specs, seed=0):
    """``with injected_faults(FaultSpec(...)):`` — install for a block,
    always uninstall (even when the block dies of SimulatedCrash)."""
    inj = install(FaultInjector(specs, seed=seed))
    try:
        yield inj
    finally:
        uninstall()


def fault_point(site, path=None, tree=None, span=None, tokens=None):
    """Declare a named fault site.  No-op unless an injector is
    installed AND a spec matches this site at the current hit count.
    ``tree`` (a mutable ``{name: array}`` dict) exposes live state to
    the ``bitflip`` kind — the caller must write replaced leaves back.
    ``tokens`` (an iterable of token-ID streams) exposes in-flight
    request content to the ``poison_request`` kind, which fires on a
    pattern match at EVERY hit, not a counted occurrence.  ``span``
    pins the fired-fault event to a specific span instead of the
    thread's ambient :func:`active_span`."""
    if _injector is not None:
        _injector.on_fault_point(site, path=path, tree=tree, span=span,
                                 tokens=tokens)


def install_from_env(var="PADDLE_TPU_FAULTS"):
    """Parse ``site:kind:occurrence[,site:kind:occurrence...]`` from the
    environment and install an injector; returns it (None if unset).
    Seed comes from ``PADDLE_TPU_FAULTS_SEED`` (default 0)."""
    raw = os.environ.get(var, "").strip()
    if not raw:
        return None
    specs = []
    for item in raw.split(","):
        parts = item.strip().split(":")
        site = parts[0]
        kind = parts[1] if len(parts) > 1 else "kill"
        occ = int(parts[2]) if len(parts) > 2 else 1
        specs.append(FaultSpec(site, kind, occurrence=occ))
    seed = int(os.environ.get(var + "_SEED", "0"))
    return install(FaultInjector(specs, seed=seed))
