"""Deterministic fault injection — the crash-consistency test driver.

Production TPU fleets treat preemption as routine (the reference's
elastic manager relaunches on ``ELASTIC_EXIT_CODE=101``); the only way
to know recovery works is to kill the process at every interesting
boundary and check what restore finds.  This module provides *named
fault sites* threaded through the I/O and checkpoint paths — each site
calls :func:`fault_point` with its name, and an installed
:class:`FaultInjector` decides (deterministically) whether to fire.

Fault kinds:

``kill``
    Raise :class:`SimulatedCrash` (a ``BaseException`` so ordinary
    ``except Exception`` recovery code can't swallow it — exactly like
    a SIGKILL, nothing downstream of the site runs).
``torn_write``
    Truncate the file named by the site's ``path`` to a seed-chosen
    fraction of its bytes, then crash — a torn write only matters when
    the process dies before completing it.
``io_error``
    Raise a transient ``OSError`` (recoverable: retry decorators and
    callers see a plain failure, the process survives).
``stall``
    Sleep ``stall_s`` seconds — an artificial host hiccup for deadline
    and watchdog paths.

Everything is **off by default**: with no injector installed,
``fault_point`` is a dict lookup and a return.  Installation is
programmatic (:func:`install` / :func:`uninstall`, or the
:func:`injected_faults` context manager tests use) or via the
``PADDLE_TPU_FAULTS`` env var (``site:kind:occurrence[,...]``), read
once by :func:`install_from_env`.

Every fired fault increments ``faults_injected_total{site=,kind=}`` in
the default metrics registry, so a fault-injection run's telemetry
shows exactly what was injected where.
"""
from __future__ import annotations

import contextlib
import os
import time

__all__ = ["SimulatedCrash", "FaultSpec", "FaultInjector", "fault_point",
           "install", "uninstall", "current_injector", "injected_faults",
           "install_from_env"]


class SimulatedCrash(BaseException):
    """An injected process death.  Deliberately NOT an ``Exception``:
    recovery code that catches ``Exception`` must not be able to
    "survive" a simulated SIGKILL."""

    def __init__(self, site, occurrence):
        super().__init__(f"simulated crash at fault site {site!r} "
                         f"(occurrence {occurrence})")
        self.site = site
        self.occurrence = occurrence


class FaultSpec:
    """Fire ``kind`` at the ``occurrence``-th hit (1-based) of ``site``.

    ``torn_frac`` overrides the seed-derived truncation fraction for
    ``torn_write``; ``stall_s`` sets the ``stall`` duration."""

    def __init__(self, site, kind="kill", occurrence=1, torn_frac=None,
                 stall_s=0.05):
        if kind not in ("kill", "torn_write", "io_error", "stall"):
            raise ValueError(f"unknown fault kind {kind!r}")
        self.site = site
        self.kind = kind
        self.occurrence = int(occurrence)
        self.torn_frac = torn_frac
        self.stall_s = stall_s

    def __repr__(self):
        return (f"FaultSpec({self.site!r}, {self.kind!r}, "
                f"occurrence={self.occurrence})")


class FaultInjector:
    """Seed-driven injector: hit counts per site + the spec table.

    The seed drives only *fault shape* (torn-write truncation point),
    never *whether* a fault fires — firing is exact (site, occurrence)
    matching so a failing kill point reproduces from its test id alone.
    """

    def __init__(self, specs=(), seed=0):
        import numpy as np

        self.specs = list(specs)
        self._rng = np.random.default_rng(seed)
        self._hits = {}          # site -> total hits
        self._fired = []         # [(site, kind, occurrence)] audit log

    # ------------------------------------------------------------ counters
    def hits(self, site):
        return self._hits.get(site, 0)

    @property
    def fired(self):
        return list(self._fired)

    # ------------------------------------------------------------- firing
    def _record(self, site, kind, occ):
        self._fired.append((site, kind, occ))
        # lazy import: faults must be importable before the jax-adjacent
        # observability stack (and from tools that never touch it)
        from ..observability.metrics import default_registry

        default_registry().counter(
            "faults_injected_total",
            help="faults fired by the resilience fault injector",
            labelnames=("site", "kind")).labels(site=site, kind=kind).inc()

    def on_fault_point(self, site, path=None):
        occ = self._hits.get(site, 0) + 1
        self._hits[site] = occ
        for spec in self.specs:
            if spec.site != site or spec.occurrence != occ:
                continue
            self._record(site, spec.kind, occ)
            if spec.kind == "kill":
                raise SimulatedCrash(site, occ)
            if spec.kind == "torn_write":
                if path is not None and os.path.exists(path):
                    size = os.path.getsize(path)
                    frac = (spec.torn_frac if spec.torn_frac is not None
                            else float(self._rng.uniform(0.1, 0.9)))
                    with open(path, "r+b") as f:
                        f.truncate(max(0, int(size * frac)))
                raise SimulatedCrash(site, occ)
            if spec.kind == "io_error":
                raise OSError(f"injected transient I/O error at {site!r} "
                              f"(occurrence {occ})")
            if spec.kind == "stall":
                time.sleep(spec.stall_s)


_injector: FaultInjector | None = None


def install(injector: FaultInjector):
    global _injector
    _injector = injector
    return injector


def uninstall():
    global _injector
    _injector = None


def current_injector():
    return _injector


@contextlib.contextmanager
def injected_faults(*specs, seed=0):
    """``with injected_faults(FaultSpec(...)):`` — install for a block,
    always uninstall (even when the block dies of SimulatedCrash)."""
    inj = install(FaultInjector(specs, seed=seed))
    try:
        yield inj
    finally:
        uninstall()


def fault_point(site, path=None):
    """Declare a named fault site.  No-op unless an injector is
    installed AND a spec matches this site at the current hit count."""
    if _injector is not None:
        _injector.on_fault_point(site, path=path)


def install_from_env(var="PADDLE_TPU_FAULTS"):
    """Parse ``site:kind:occurrence[,site:kind:occurrence...]`` from the
    environment and install an injector; returns it (None if unset).
    Seed comes from ``PADDLE_TPU_FAULTS_SEED`` (default 0)."""
    raw = os.environ.get(var, "").strip()
    if not raw:
        return None
    specs = []
    for item in raw.split(","):
        parts = item.strip().split(":")
        site = parts[0]
        kind = parts[1] if len(parts) > 1 else "kill"
        occ = int(parts[2]) if len(parts) > 2 else 1
        specs.append(FaultSpec(site, kind, occurrence=occ))
    seed = int(os.environ.get(var + "_SEED", "0"))
    return install(FaultInjector(specs, seed=seed))
