"""Silent-corruption sentinel — self-verifying training state.

Every loud failure mode is already survivable: crashes resume from
atomic checkpoints, hangs are localized by the flight watchdog, dead
serving replicas fail over.  What nothing upstream catches is a rank
that keeps running but computes the *wrong numbers* — a hardware
bitflip, a nondeterministic kernel, a dp replica that desynced after a
missed collective.  There is no NaN, no stall, no dead socket; the only
symptom is a loss curve that quietly goes wrong while every checkpoint
since the corruption gets poisoned.  This module makes live training
state verify itself, three ways:

- **cross-rank fingerprints** — :func:`tree_fingerprint` computes a
  per-leaf CRC32 digest (leaf-name-keyed, over the exact host bytes of
  each array).  Every ``fingerprint_every`` steps each dp rank
  publishes its digest over the TCPStore rendezvous plane (per-step
  keys under ``integrity/fp/rank_<r>``) and compares against its
  peers: replicated state must be *bitwise identical*, so any mismatch
  is corruption.  Majority vote names the divergent rank(s) and the
  first divergent leaf; ``integrity_divergence_total{kind="cross_rank"}``
  fires with an ``integrity::divergence`` span, and the divergent rank
  flips ``training_healthy`` + ``integrity_divergence_active``.
- **sampled step replay** — every ``replay_every`` steps the callback
  snapshots pre-step state (params, buffers, optimizer state, RNG
  streams, LR), lets the real step run, then re-executes it via
  ``Model.replay_train_batch`` and compares the two outcomes bitwise.
  Any delta means nondeterminism or silent corruption *within one
  step*, reported with the first differing leaf
  (``integrity_divergence_total{kind="replay"}``).
- **repair** — a confirmed cross-rank divergence is an anomaly kind
  (``param_divergence``) the :class:`~paddle_tpu.observability.health.
  HealthMonitor` routes through the PR-6 rollback machinery: the
  divergent rank restores the newest checkpoint at or before the last
  *verified* step, discards the poisoned newer checkpoints, rewinds the
  fit loop and **replays** the same batches (no data is skipped —
  unlike a poisoned-batch rollback, the data was fine; the state was
  not), reconverging bitwise with the healthy replicas.

Audit-on-save (``CheckpointManager.save(verify=True)``) closes the
fourth hole: a save whose bytes rot between commit and the next
restore.  See :mod:`.checkpoint_manager`.

The ``bitflip`` fault kind (:mod:`.faults`) makes every detection path
reproducible on CPU: flip one seed-chosen bit in a named array at the
``hapi.step_params`` site and watch the sentinel find it, name it, and
repair it.

Overhead: fingerprints are one CRC pass over host bytes every N steps;
replay costs one extra step every M steps.  ``bench.py --section
integrity`` measures the combined amortized cost — documented bound
<3% of step time at the bench config (defaults N=25, M=100).
"""
from __future__ import annotations

import json
import logging
import time
import zlib

# the duck-typed hapi hook surface: resilience sits below hapi in the
# layer stack, so the sentinel callback must not import paddle_tpu.hapi
from ..observability.goodput import TrainingCallback

__all__ = ["tree_fingerprint", "shard_fingerprint",
           "first_divergent_leaf", "majority_partition",
           "compare_digests", "IntegrityCallback"]

logger = logging.getLogger("paddle_tpu.resilience")


# ------------------------------------------------------------ fingerprints


def _leaf_crc(arr):
    import numpy as np

    a = np.asarray(arr)                     # device_get for jax arrays
    if not a.flags.c_contiguous:
        a = np.ascontiguousarray(a)
    # dtype + shape ride in the digest: a reshaped or recast leaf with
    # identical bytes is still a divergence
    crc = zlib.crc32(f"{a.dtype.str}:{a.shape}".encode())
    return zlib.crc32(memoryview(a).cast("B"), crc)


def tree_fingerprint(tree, prefix=""):
    """Per-leaf CRC32 digest of a nested dict/list/array tree.

    Returns ``{leaf_path: crc32}`` with ``/``-joined path keys in
    sorted order — the cheap, leaf-name-keyed state digest the
    cross-rank compare and the step-replay verifier both speak.
    Non-array scalar leaves hash their ``repr``; ``None`` leaves are
    skipped."""
    out = {}

    def visit(path, node):
        if isinstance(node, dict):
            for k in sorted(node):
                visit(f"{path}/{k}" if path else str(k), node[k])
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                visit(f"{path}/{i}" if path else str(i), v)
        elif node is None:
            return
        elif hasattr(node, "dtype") or hasattr(node, "__array__"):
            out[path] = _leaf_crc(node)
        else:
            out[path] = zlib.crc32(repr(node).encode())

    visit(prefix, tree)
    return out


def shard_fingerprint(tree, prefix="", devices=None):
    """Per-ADDRESSABLE-shard CRC32 digest of a (possibly GSPMD-sharded)
    tree: ``{leaf_path@window: crc32}`` where ``window`` names the
    shard's global index slice (``0:64,32:64``).

    The multi-chip view of :func:`tree_fingerprint`: under real GSPMD
    a rank holds only its addressable shards, so the digest covers
    exactly the bytes this rank owns — no device→host gather of the
    global array.  Duplicate windows (axes replicated across local
    devices) hash once.  ``devices`` restricts the view to shards on
    those devices (how tests simulate per-rank locality on one host).

    Cross-rank comparison contract: digests are only comparable within
    a dp REPLICA GROUP (``distributed.mesh.replica_peers``) — mp/pp/
    sharding neighbours hold *different* windows and legitimately
    differ; comparing across them is a false positive by construction.
    """
    out = {}
    devset = None if devices is None else set(devices)

    def win_key(index, shape):
        return ",".join(
            f"{sl.start or 0}:{shape[i] if sl.stop is None else sl.stop}"
            for i, sl in enumerate(index))

    def visit(path, node):
        if isinstance(node, dict):
            for k in sorted(node):
                visit(f"{path}/{k}" if path else str(k), node[k])
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                visit(f"{path}/{i}" if path else str(i), v)
        elif node is None:
            return
        elif getattr(node, "addressable_shards", None):
            seen = set()
            for sh in node.addressable_shards:
                if devset is not None and sh.device not in devset:
                    continue
                index = tuple(
                    sl if isinstance(sl, slice) else slice(sl, sl + 1)
                    for sl in (sh.index or
                               (slice(0, 1),) * max(node.ndim, 1)))
                key = win_key(index, tuple(node.shape) or (1,))
                if key in seen:
                    continue
                seen.add(key)
                out[f"{path}@{key}"] = _leaf_crc(sh.data)
        elif hasattr(node, "dtype") or hasattr(node, "__array__"):
            out[path] = _leaf_crc(node)
        else:
            out[path] = zlib.crc32(repr(node).encode())

    visit(prefix, tree)
    return out


def first_divergent_leaf(mine, other):
    """First (sorted) leaf name whose digest differs between two
    fingerprints — a leaf missing from either side counts."""
    for name in sorted(set(mine) | set(other)):
        if mine.get(name) != other.get(name):
            return name
    return None


def majority_partition(digests):
    """Partition ``{rank: fingerprint}`` by bitwise-identical digest.

    Returns ``(majority_ranks, minority_ranks, majority_digest)``.
    The majority is the largest identical group; a tie breaks toward
    the group containing the lowest rank (with two ranks, rank 0
    anchors — attribution is a convention there, detection is not)."""
    groups = {}
    for rank, digest in digests.items():
        key = tuple(sorted(digest.items()))
        groups.setdefault(key, []).append(rank)
    ordered = sorted(groups.items(),
                     key=lambda kv: (-len(kv[1]), min(kv[1])))
    maj_key, maj_ranks = ordered[0]
    minority = sorted(r for key, ranks in groups.items()
                      if key != maj_key for r in ranks)
    return sorted(maj_ranks), minority, dict(maj_key)


def compare_digests(digests):
    """Cross-rank compare: ``None`` when every rank agrees, else a
    report naming the divergent rank(s) and, per divergent rank, the
    first divergent leaf vs the majority."""
    if len(digests) < 2:
        return None
    majority, minority, maj_digest = majority_partition(digests)
    if not minority:
        return None
    return {
        "majority_ranks": majority,
        "divergent_ranks": minority,
        "first_divergent_leaf": {
            r: first_divergent_leaf(digests[r], maj_digest)
            for r in minority},
    }


# ----------------------------------------------------------- the sentinel


def _rank_step_key(prefix, rank, step):
    return f"{prefix}/fp/rank_{int(rank)}/step_{int(step)}"


class IntegrityCallback(TrainingCallback):
    """The silent-corruption sentinel as a ``Model.fit`` callback.

    ``store``/``rank``/``world_size`` wire the cross-rank fingerprint
    compare over the TCPStore rendezvous plane (omit ``store`` for
    single-process use — replay verification still runs).  ``monitor``
    (a :class:`~paddle_tpu.observability.health.HealthMonitor`, ideally
    ``action="rollback"``) receives a confirmed *own-rank* divergence
    as a ``param_divergence`` anomaly, which triggers the
    restore-and-replay repair (requires a ``CheckpointCallback`` in the
    same fit); without a monitor the sentinel detects and reports but
    does not repair.

    ``fingerprint_every=0`` / ``replay_every=0`` disable that
    mechanism.  ``include_opt_state`` folds optimizer slots into the
    fingerprint (params-only by default: corrupt optimizer state
    surfaces in the params within a step anyway).

    Events land in ``self.events`` (newest last), metrics in
    ``integrity_checks_total{kind}`` / ``integrity_divergence_total
    {kind}`` / ``integrity_fingerprint_seconds`` /
    ``integrity_replay_seconds`` / ``integrity_last_verified_step`` /
    ``integrity_divergence_active``, spans as ``integrity::divergence``
    and ``integrity::replay``.  The telemetry server's ``/integrity``
    endpoint serves :meth:`report`, and ``/healthz`` goes 503 while
    ``divergence_active`` is set (cleared when a later compare
    matches again — i.e. once the repair actually reconverged)."""

    def __init__(self, store=None, rank=0, world_size=1,
                 fingerprint_every=25, replay_every=0, monitor=None,
                 include_opt_state=False, key_prefix="integrity",
                 history=4, registry=None, tracer=None, clock=None,
                 peers=None, fingerprint_shards=False,
                 local_devices=None):
        """``peers``/``fingerprint_shards``/``local_devices`` are the
        GSPMD wiring: under a multi-chip mesh the fingerprint must
        cover each rank's *addressable shard view*
        (:func:`shard_fingerprint`, enabled by ``fingerprint_shards``;
        ``local_devices`` restricts to this rank's devices) and the
        cross-rank compare must be restricted to this rank's dp
        replica group (``peers``, from
        :func:`~paddle_tpu.distributed.mesh.replica_peers`) — mp/pp/
        sharding neighbours hold different shards and legitimately
        differ."""
        super().__init__()
        self.store = store
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.peers = None if peers is None else sorted(
            int(p) for p in peers)
        self.fingerprint_shards = bool(fingerprint_shards)
        self.local_devices = local_devices
        self.fingerprint_every = int(fingerprint_every)
        self.replay_every = int(replay_every)
        self.monitor = monitor
        self.include_opt_state = bool(include_opt_state)
        self.key_prefix = key_prefix
        self.history = int(history)
        self._registry = registry
        self._tracer = tracer
        self._clock = clock or time.time
        self._global_step = 0
        self._snapshot = None
        self.events = []
        self.divergence_active = False
        self.last_verified_global_step = None
        self.checks = {"fingerprint": 0, "replay": 0}

    # ---- wiring ---------------------------------------------------------
    def registry(self):
        if self._registry is None:
            from ..observability.metrics import default_registry

            self._registry = default_registry()
        return self._registry

    def tracer(self):
        if self._tracer is None:
            from ..observability.tracing import default_tracer

            self._tracer = default_tracer()
        return self._tracer

    def _active_gauge(self):
        return self.registry().gauge(
            "integrity_divergence_active",
            "1 while a confirmed state divergence on this rank is "
            "unrepaired")

    def _divergence_counter(self, kind):
        return self.registry().counter(
            "integrity_divergence_total",
            "state divergences detected by the integrity sentinel",
            labelnames=("kind",)).labels(kind=kind)

    def _check_counter(self, kind):
        return self.registry().counter(
            "integrity_checks_total",
            "integrity verifications run (fingerprint compares, step "
            "replays)", labelnames=("kind",)).labels(kind=kind)

    def report(self):
        """The ``/integrity`` payload."""
        return {
            "rank": self.rank,
            "world_size": self.world_size,
            "fingerprint_every": self.fingerprint_every,
            "replay_every": self.replay_every,
            "global_step": self._global_step,
            "last_verified_global_step": self.last_verified_global_step,
            "divergence_active": bool(self.divergence_active),
            "checks": dict(self.checks),
            "events": list(self.events[-32:]),
        }

    # ---- hapi hooks -----------------------------------------------------
    def on_train_begin(self, logs=None):
        info = getattr(self.model, "_resume_info", None) or {}
        self._global_step = int(info.get("global_step", 0))
        self._snapshot = None
        self.events = []
        self.checks = {"fingerprint": 0, "replay": 0}
        self.divergence_active = False
        self.last_verified_global_step = None
        self._active_gauge().set(0)
        if self.replay_every:
            # fit stashes each raw batch so the replay can re-feed it
            self.model._stash_batch = True

    def on_train_end(self, logs=None):
        if self.model is not None:
            self.model._stash_batch = False

    def rewind_to(self, global_step):
        """Rollback support: a rewind-and-replay repair moved training
        back to ``global_step`` — step counting must follow, and a
        snapshot taken for the aborted step is meaningless now."""
        self._global_step = int(global_step)
        self._snapshot = None

    def on_train_batch_begin(self, step, logs=None):
        if not self.replay_every:
            return
        upcoming = self._global_step + 1
        if upcoming % self.replay_every:
            return
        model = self.model
        opt = getattr(model, "_optimizer", None)
        if not hasattr(opt, "apply_gradients"):
            return                  # eager fallback path: no pure step
        from ..core.random import get_rng_state

        params, buffers = model.network.raw_state()
        self._snapshot = {
            # jax arrays are immutable — references ARE the snapshot
            "params": dict(params),
            "buffers": dict(buffers),
            "opt_state": model._opt_state,
            "rng": dict(get_rng_state()),
            "lr": float(opt.get_lr()),
        }

    def on_train_batch_end(self, step, logs=None):
        self._global_step += 1
        if self._snapshot is not None:
            self._run_replay(step)
        if self.fingerprint_every and \
                self._global_step % self.fingerprint_every == 0:
            self._run_fingerprint(step)

    # ---- step replay ----------------------------------------------------
    def _run_replay(self, step):
        import numpy as np

        snap, self._snapshot = self._snapshot, None
        batch = getattr(self.model, "_last_batch", None)
        if batch is None:
            return
        t0 = time.perf_counter()
        loss2, params2 = self.model.replay_train_batch(snap, batch)
        current = {k: p.data for k, p
                   in self.model.network.named_parameters()}
        leaf = None
        for name in sorted(current):
            a = np.ascontiguousarray(np.asarray(current[name]))
            b = np.ascontiguousarray(np.asarray(params2[name]))
            if a.tobytes() != b.tobytes():
                leaf = name
                break
        self.registry().histogram(
            "integrity_replay_seconds",
            "wall time of one sampled step replay").observe(
                time.perf_counter() - t0)
        self._check_counter("replay").inc()
        self.checks["replay"] += 1
        if leaf is None:
            return
        detail = {"kind": "replay", "global_step": self._global_step,
                  "step": int(step), "first_divergent_leaf": leaf,
                  "replayed_loss": float(loss2)}
        self.events.append(detail)
        self._divergence_counter("replay").inc()
        span = self.tracer().start_trace("integrity::replay",
                                         attributes=dict(detail))
        span.end()
        logger.error(
            "integrity: step replay mismatch at global step %d — first "
            "divergent leaf %r (the step is nondeterministic or "
            "silently corrupting)", self._global_step, leaf)
        if self.monitor is not None:
            # step_replay_mismatch is deliberately NOT a rollback kind:
            # replay can't say which of the two executions was right
            self.monitor.external_anomaly("step_replay_mismatch",
                                          detail, step)

    # ---- cross-rank fingerprints ---------------------------------------
    def _fingerprint_tree(self):
        params, _ = self.model.network.raw_state()
        tree = {"params": dict(params)}
        if self.include_opt_state and self.model._opt_state is not None:
            tree["opt"] = self.model._opt_state
        return tree

    def _run_fingerprint(self, step):
        t0 = time.perf_counter()
        if self.fingerprint_shards:
            digest = shard_fingerprint(self._fingerprint_tree(),
                                       devices=self.local_devices)
        else:
            digest = tree_fingerprint(self._fingerprint_tree())
        self.registry().histogram(
            "integrity_fingerprint_seconds",
            "wall time of one parameter-tree fingerprint").observe(
                time.perf_counter() - t0)
        digests = {self.rank: digest}
        if self.store is not None:
            try:
                self._publish(digest)
                digests.update(self._peer_digests())
            except (OSError, RuntimeError) as e:
                logger.warning("integrity: store unavailable for "
                               "fingerprint exchange: %s", e)
        self._check_counter("fingerprint").inc()
        self.checks["fingerprint"] += 1
        report = compare_digests(digests)
        if report is None:
            self.last_verified_global_step = self._global_step
            self.registry().gauge(
                "integrity_last_verified_step",
                "newest global step whose cross-rank fingerprint "
                "compare matched").set(self._global_step)
            if self.divergence_active:
                self.divergence_active = False
                self._active_gauge().set(0)
                logger.warning(
                    "integrity: rank %d reconverged with the fleet at "
                    "global step %d — divergence repaired",
                    self.rank, self._global_step)
            return
        self._handle_divergence(report, step)

    def _publish(self, digest):
        key = _rank_step_key(self.key_prefix, self.rank,
                             self._global_step)
        self.store.set(key, json.dumps(
            {"rank": self.rank, "global_step": self._global_step,
             "time": self._clock(), "digest": digest}))
        stale = self._global_step - self.history * self.fingerprint_every
        if stale > 0 and hasattr(self.store, "delete_key"):
            try:
                self.store.delete_key(_rank_step_key(
                    self.key_prefix, self.rank, stale))
            except (OSError, RuntimeError):
                pass

    def _peer_digests(self):
        """Peer fingerprints for THIS global step — only ranks that
        have already published (non-blocking: a slow peer is compared
        on a later step, not waited on).  With ``peers`` set, only the
        dp replica group is consulted — everyone else's shard view
        differs by construction.

        The ``blocking=False`` below is load-bearing, not an
        optimization: a blocking get here would make every fingerprint
        interval a de-facto barrier — one dead rank stalls the whole
        fleet's training loop.  The ``collective-discipline`` static
        pass treats a blocking one-sided store wait as exactly that
        hazard; this publish/compare exchange stays in its handshake
        class only because nobody ever waits."""
        out = {}
        ranks = (self.peers if self.peers is not None
                 else range(self.world_size))
        for r in ranks:
            if r == self.rank:
                continue
            key = _rank_step_key(self.key_prefix, r, self._global_step)
            try:
                blob = self.store.get(key, blocking=False)
            except KeyError:
                continue
            try:
                payload = json.loads(blob)
            except ValueError:
                continue
            out[r] = {k: int(v)
                      for k, v in payload.get("digest", {}).items()}
        return out

    def _handle_divergence(self, report, step):
        self_divergent = self.rank in report["divergent_ranks"]
        detail = {
            "kind": "cross_rank",
            "global_step": self._global_step,
            "step": int(step),
            "divergent_ranks": report["divergent_ranks"],
            "majority_ranks": report["majority_ranks"],
            "first_divergent_leaf": report["first_divergent_leaf"],
            "self_divergent": self_divergent,
            "last_verified_global_step": self.last_verified_global_step,
        }
        self.events.append(detail)
        self._divergence_counter("cross_rank").inc()
        span = self.tracer().start_trace("integrity::divergence",
                                         attributes={
                                             k: repr(v) if
                                             isinstance(v, (list, dict))
                                             else v
                                             for k, v in detail.items()})
        span.end()
        leaves = report["first_divergent_leaf"]
        logger.error(
            "integrity: cross-rank state divergence at global step %d "
            "— divergent rank(s) %s, first divergent leaf %s",
            self._global_step, report["divergent_ranks"], leaves)
        if not self_divergent:
            return                  # the divergent rank repairs itself
        self.divergence_active = True
        self._active_gauge().set(1)
        self.registry().gauge(
            "training_healthy",
            "1 = no active training anomaly, 0 = unhealthy").set(0)
        if self.monitor is not None:
            rollback_detail = dict(detail)
            rollback_detail["rewind"] = True
            if self.last_verified_global_step is not None:
                # restore a checkpoint at or before the last step the
                # fleet agreed on — anything newer may be poisoned
                rollback_detail["restore_before"] = \
                    self.last_verified_global_step + 1
            self.monitor.external_anomaly("param_divergence",
                                          rollback_detail, step)
