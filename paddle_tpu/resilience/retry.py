"""Jittered exponential backoff + deadlines.

The two primitives every blocking edge of the system shares:

- :class:`Deadline` — an absolute time budget carried through nested
  waits (connect → request → poll), so layered timeouts can't stack
  into multiples of the user's budget.
- :func:`retry` — a decorator re-running a callable on transient
  failure with capped exponential backoff and full jitter (the AWS
  architecture-blog scheme: ``sleep = uniform(0, min(cap, base·2^k))``
  decorrelates a thundering herd of reconnecting hosts).
- :func:`backoff_delays` — the underlying delay generator, used
  directly by polling loops (``TCPStore.get``) that aren't shaped like
  a retryable function call.

Retries are visible in telemetry: every backed-off attempt counts into
``retry_attempts_total{name=...}`` in the default metrics registry.
"""
from __future__ import annotations

import functools
import random
import time

__all__ = ["Deadline", "backoff_delays", "retry", "RetryError"]


class RetryError(RuntimeError):
    """All attempts exhausted; ``last`` is the final exception."""

    def __init__(self, name, attempts, last):
        super().__init__(f"{name}: {attempts} attempts failed; "
                         f"last error: {last!r}")
        self.attempts = attempts
        self.last = last


class Deadline:
    """An absolute time budget (monotonic clock).

    ``Deadline(5.0)`` expires 5s from construction; ``Deadline(None)``
    never expires.  ``remaining()`` clamps at 0; ``sleep(dt)`` never
    sleeps past the deadline."""

    def __init__(self, timeout_s):
        self._end = None if timeout_s is None else \
            time.monotonic() + float(timeout_s)

    @classmethod
    def after(cls, timeout_s):
        return cls(timeout_s)

    def remaining(self):
        if self._end is None:
            return float("inf")
        return max(0.0, self._end - time.monotonic())

    def expired(self):
        return self.remaining() <= 0.0

    def sleep(self, dt):
        """Sleep min(dt, remaining); returns the time actually slept."""
        dt = min(float(dt), self.remaining())
        if dt > 0:
            time.sleep(dt)
        return dt

    def __repr__(self):
        if self._end is None:
            return "Deadline(∞)"
        return f"Deadline(remaining={self.remaining():.3f}s)"


def backoff_delays(base=0.001, factor=2.0, cap=0.25, jitter=True, rng=None):
    """Yield successive backoff delays: ``min(cap, base·factor^k)``,
    full-jittered (uniform in (0, d]) unless ``jitter=False``.
    Infinite — the consumer owns the stop condition (attempt count or
    Deadline)."""
    rng = rng or random
    d = float(base)
    while True:
        yield rng.uniform(0.0, d) if jitter else d
        d = min(float(cap), d * factor)


def retry(exceptions=(OSError, TimeoutError), max_attempts=5, base=0.01,
          factor=2.0, cap=1.0, jitter=True, deadline=None, name=None,
          rng=None):
    """Decorator (or ``retry(...)(fn)`` wrapper) with capped, jittered
    exponential backoff.

    Stops on whichever comes first: ``max_attempts`` exhausted
    (raises :class:`RetryError` chaining the last failure) or the
    optional ``deadline`` (a :class:`Deadline` or float seconds per
    *call*) expiring — then the last exception re-raises as-is, since
    a deadline miss is the caller's timeout, not a retry failure.
    """
    excs = tuple(exceptions) if isinstance(exceptions, (tuple, list)) \
        else (exceptions,)

    def deco(fn):
        label = name or getattr(fn, "__qualname__", repr(fn))

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            dl = deadline if isinstance(deadline, Deadline) else \
                Deadline(deadline)
            delays = backoff_delays(base=base, factor=factor, cap=cap,
                                    jitter=jitter, rng=rng)
            last = None
            for attempt in range(1, max_attempts + 1):
                try:
                    return fn(*args, **kwargs)
                except excs as e:
                    last = e
                    from ..observability.metrics import default_registry

                    default_registry().counter(
                        "retry_attempts_total",
                        help="failed attempts retried with backoff",
                        labelnames=("name",)).labels(name=label).inc()
                    if attempt >= max_attempts:
                        raise RetryError(label, attempt, e) from e
                    if dl.sleep(next(delays)) <= 0 and dl.expired():
                        raise
            raise RetryError(label, max_attempts, last)  # pragma: no cover

        return wrapper

    return deco
