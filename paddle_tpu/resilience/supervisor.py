"""Training supervisor — preemption-to-resume with zero operator action.

The ROADMAP's elastic-training north star: a trainer on a preemptible
TPU pod gets killed routinely, and nothing about recovery may involve a
human.  :class:`TrainingSupervisor` is the per-node daemon that closes
that loop over machinery the stack already has:

- it runs the trainer as a **child process** and watches it;
- a clean exit (0) ends the job; ``ELASTIC_EXIT_CODE`` (a worker
  *requesting* relaunch, the reference fleet-elastic contract) or any
  crash triggers a **relaunch with jittered backoff**, up to
  ``max_restarts``;
- with an :class:`~paddle_tpu.distributed.fleet.elastic.ElasticManager`
  attached it **rendezvouses** (waits for the expected membership,
  retrying over transient store outages) before every launch and keeps
  probing membership while the child runs — a lost peer terminates the
  local child and re-enters the relaunch path;
- every launch exports the resume contract to the child:
  ``PADDLE_ELASTIC_RESUME_DIR`` (the checkpoint directory the trainer
  passes to ``Model.fit(resume_from=...)``) and
  ``PADDLE_RESTART_ATTEMPT``.  ``fit(resume_from=...)`` treats an empty
  directory as a fresh start, so the **first launch and the Nth
  relaunch are one code path** — the supervisor never special-cases
  attempt 0.

Between attempts the supervisor opens the checkpoint directory (no
child is alive then, so the constructor's orphan-``.tmp`` sweep is
safe) and logs the step it expects the relaunch to resume from — the
operator-readable audit trail of an operation no operator performed.

With a ``hang_watchdog`` attached (a
:class:`~paddle_tpu.observability.flight.HangWatchdog` in observer
mode, or any object with ``check()``/``write_bundle()``/``reset()``),
the supervisor also escalates on **cross-rank collective hangs**: a
wedged child never exits, so exit-code watching alone would wait
forever.  ``on_hang="bundle+restart"`` (the default) dumps a
supervisor-side debug bundle, terminates the hung child and re-enters
the relaunch path (reason ``hang``); ``on_hang="restart"`` skips the
bundle.  The watchdog is ``reset()`` after the kill so the relaunched
fleet re-baselines instead of re-firing on the dead run's stale
heartbeats.

Telemetry: ``supervisor_restarts_total{reason=elastic_exit|crash|
lost_node|spawn_failed|hang}``, the ``supervisor_child_up`` gauge, and
``supervisor::launch`` / ``supervisor::relaunch`` trace spans.

Fault sites (see :mod:`.faults`): ``supervisor.spawn`` fires before
every child spawn (an ``io_error`` there is a relaunch that itself
dies — the supervisor retries it out of the same restart budget);
``supervisor.rendezvous`` fires before every membership wait (an
``io_error`` is a store outage mid-rendezvous — retried with backoff
under the rendezvous deadline, never read as "the fleet died").
"""
from __future__ import annotations

import logging
import os
import subprocess
import sys
import time

from .faults import fault_point
from .retry import Deadline, backoff_delays

__all__ = ["TrainingSupervisor", "ENV_RESUME_DIR", "ENV_ATTEMPT"]

logger = logging.getLogger("paddle_tpu.resilience")

#: env var naming the checkpoint directory the child resumes from
ENV_RESUME_DIR = "PADDLE_ELASTIC_RESUME_DIR"
#: env var carrying the 0-based launch attempt (same name the launcher
#: uses, so scripts written for either supervisor read one contract)
ENV_ATTEMPT = "PADDLE_RESTART_ATTEMPT"


class TrainingSupervisor:
    """Run, watch, and autonomously relaunch one trainer process.

    ``cmd`` is the trainer argv (e.g. ``[sys.executable, "train.py"]``).
    ``checkpoint_dir`` is exported to the child as
    :data:`ENV_RESUME_DIR`; the trainer is expected to pass it to
    ``Model.fit(resume_from=...)`` (via a ``CheckpointCallback`` on the
    same directory), which makes every relaunch resume from the newest
    intact checkpoint with no supervisor-side state transfer.

    ``elastic``/``hosts`` attach fleet membership: the supervisor
    registers the manager, rendezvouses before each launch and watches
    peers while the child runs.  ``env`` (default: this process's
    environment) is the child's base environment; the resume contract
    is overlaid on top.
    """

    def __init__(self, cmd, checkpoint_dir=None, max_restarts=3,
                 backoff_base=0.2, backoff_cap=10.0, jitter=True,
                 elastic=None, hosts=(), poll_interval=0.05,
                 membership_interval=0.5, rendezvous_timeout=60.0,
                 term_grace_s=10.0, env=None, log_path=None, rng=None,
                 registry=None, tracer=None, hang_watchdog=None,
                 on_hang="bundle+restart"):
        self.cmd = list(cmd)
        self.checkpoint_dir = checkpoint_dir
        self.max_restarts = int(max_restarts)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.jitter = bool(jitter)
        self.elastic = elastic
        self.hosts = list(hosts)
        self.poll_interval = float(poll_interval)
        self.membership_interval = float(membership_interval)
        self.rendezvous_timeout = float(rendezvous_timeout)
        self.term_grace_s = float(term_grace_s)
        self.env = env
        self.log_path = log_path
        self._rng = rng
        self._registry = registry
        self._tracer = tracer
        self.hang_watchdog = hang_watchdog
        if on_hang not in ("bundle+restart", "restart"):
            raise ValueError(f"unknown on_hang policy {on_hang!r}")
        self.on_hang = on_hang
        self.attempt = 0            # current launch attempt (0 = first)
        self.restarts = []          # [(reason, attempt)] audit log

    # ---- wiring ---------------------------------------------------------
    def registry(self):
        if self._registry is None:
            from ..observability.metrics import default_registry

            self._registry = default_registry()
        return self._registry

    def tracer(self):
        if self._tracer is None:
            from ..observability.tracing import default_tracer

            self._tracer = default_tracer()
        return self._tracer

    def _restart_counter(self):
        return self.registry().counter(
            "supervisor_restarts_total",
            "trainer relaunches by the training supervisor",
            labelnames=("reason",))

    def _child_up(self, up):
        self.registry().gauge(
            "supervisor_child_up",
            "1 while the supervised trainer process is running",
        ).set(1 if up else 0)

    # ---- child lifecycle ------------------------------------------------
    def _child_env(self, attempt):
        env = dict(self.env if self.env is not None else os.environ)
        env[ENV_ATTEMPT] = str(attempt)
        if self.checkpoint_dir is not None:
            env[ENV_RESUME_DIR] = os.fspath(self.checkpoint_dir)
        return env

    def _spawn(self, attempt):
        fault_point("supervisor.spawn")
        logf = None
        if self.log_path:
            # lint-ok: atomic-writes append-style run transcript that
            # must be open BEFORE the child exists; a torn line is
            # cosmetic
            logf = open(self.log_path, "a" if attempt else "w")
            if attempt:
                logf.write(f"\n----- restart attempt {attempt} -----\n")
                logf.flush()
        try:
            child = subprocess.Popen(
                self.cmd, env=self._child_env(attempt),
                stdout=logf if logf is not None else None,
                stderr=subprocess.STDOUT if logf is not None else None)
        finally:
            if logf is not None:
                logf.close()    # the child holds its own fd now
        span = self.tracer().start_trace(
            "supervisor::launch",
            attributes={"attempt": attempt, "pid": child.pid,
                        **self._resume_evidence()})
        span.end()
        self._child_up(True)
        return child

    def _terminate(self, child):
        if child.poll() is None:
            child.terminate()
            try:
                child.wait(timeout=self.term_grace_s)
            except subprocess.TimeoutExpired:
                child.kill()
                child.wait()

    def _resume_step(self):
        """The committed step a relaunch will resume from (None when no
        checkpoint directory or no intact checkpoint exists yet).  Also
        the point where orphaned ``.tmp`` save debris from the killed
        child is swept — no other writer is alive here."""
        if self.checkpoint_dir is None:
            return None
        from .checkpoint_manager import CheckpointManager

        try:
            return CheckpointManager(self.checkpoint_dir).latest()
        except OSError:
            return None

    def _resume_evidence(self):
        """Resume step plus the newest manifest's recovery history:
        skipped data windows (poisoned-batch rollbacks) and integrity
        repairs (silent-corruption rewind-and-replay) ride in the
        checkpoint ``extra``, so the supervisor's relaunch telemetry
        records what the previous life of this trainer already
        survived — not just where it resumes."""
        step = self._resume_step()
        out = {"resume_step": step}
        if step is None:
            return out
        try:
            from ..distributed.checkpoint import _load_manifest
            from .checkpoint_manager import CheckpointManager

            extra = _load_manifest(
                CheckpointManager(
                    self.checkpoint_dir,
                    sweep_orphans=False).step_path(step)).get("extra", {})
        except (OSError, ValueError, KeyError):
            return out
        windows = extra.get("skipped_windows") or []
        repairs = extra.get("repairs") or []
        if windows:
            out["skipped_windows"] = len(windows)
            out["last_rollback_reason"] = windows[-1].get("reason")
        if repairs:
            out["integrity_repairs"] = len(repairs)
            out["last_repair_reason"] = repairs[-1].get("reason")
        return out

    # ---- membership -----------------------------------------------------
    def _rendezvous(self):
        """Wait until fleet membership matches, retrying transient store
        failures with backoff (a blipping TCPStore during rendezvous
        must not read as a dead fleet)."""
        if self.elastic is None or not self.hosts:
            return
        dl = Deadline(self.rendezvous_timeout)
        delays = backoff_delays(base=self.backoff_base, cap=1.0,
                                jitter=self.jitter, rng=self._rng)
        while True:
            try:
                fault_point("supervisor.rendezvous")
                if self.elastic.wait_for_np(
                        self.hosts, timeout=max(1.0, dl.remaining())):
                    return
            except (OSError, RuntimeError) as e:
                logger.warning("supervisor: rendezvous store error "
                               "(retrying): %s", e)
            if dl.expired():
                raise TimeoutError(
                    f"rendezvous: membership never reached "
                    f"np={self.elastic.np} within "
                    f"{self.rendezvous_timeout}s")
            dl.sleep(next(delays))

    def _membership_lost(self):
        """Dead peer list, or [] — including on transient store errors
        (a blip is not a death; the next probe round decides)."""
        try:
            return [h for h in self.hosts if not self.elastic.probe(h)]
        except (OSError, RuntimeError):
            return []

    # ---- the loop -------------------------------------------------------
    def _hang_detected(self):
        """Probe the attached hang watchdog (False without one, and on
        probe errors — a broken watchdog must not kill a healthy
        child)."""
        if self.hang_watchdog is None:
            return False
        try:
            return bool(self.hang_watchdog.check())
        except Exception:
            return False

    def _escalate_hang(self, child):
        """The ``on_hang`` escalation: dump (policy permitting), kill
        the wedged child, reset the watchdog for the relaunch."""
        logger.error("supervisor: cross-rank hang detected — "
                     "escalating with policy %r", self.on_hang)
        if "bundle" in self.on_hang:
            try:
                self.hang_watchdog.write_bundle(reason="supervisor_hang")
            except Exception:
                logger.exception("supervisor: hang bundle write failed")
        self._terminate(child)
        self._child_up(False)
        try:
            self.hang_watchdog.reset()
        except Exception:
            pass    # silent-ok: advisory reset — the relaunch
                    # re-baselines against stale heartbeats regardless

    def _watch(self, child):
        """Block until the child exits, membership breaks, or the hang
        watchdog fires.  Returns ``("ok"|"elastic_exit"|"crash"|
        "lost_node"|"hang", exit_code)``."""
        elastic_code = self._elastic_exit_code()
        next_probe = time.monotonic() + self.membership_interval
        # lint-ok: bounded-retries the watch loop is bounded by the
        # child's lifetime (poll() returning), not by a deadline
        while True:
            code = child.poll()
            if code is not None:
                self._child_up(False)
                if code == 0:
                    return ("ok", 0)
                if code == elastic_code:
                    return ("elastic_exit", code)
                return ("crash", code)
            if time.monotonic() >= next_probe:
                if self.elastic is not None and self.hosts:
                    dead = self._membership_lost()
                    if dead:
                        logger.warning("supervisor: lost node(s) %s — "
                                       "terminating local trainer for "
                                       "relaunch", dead)
                        self._terminate(child)
                        self._child_up(False)
                        return ("lost_node", elastic_code)
                if self._hang_detected():
                    self._escalate_hang(child)
                    return ("hang", elastic_code)
                next_probe = time.monotonic() + self.membership_interval
            time.sleep(self.poll_interval)

    @staticmethod
    def _elastic_exit_code():
        from ..distributed.fleet.elastic import ELASTIC_EXIT_CODE

        return ELASTIC_EXIT_CODE

    def run(self):
        """Supervise until the trainer completes or the restart budget
        is exhausted.  Returns the final exit code (0 = success)."""
        delays = backoff_delays(base=self.backoff_base,
                                cap=self.backoff_cap, jitter=self.jitter,
                                rng=self._rng)
        registered = False
        if self.elastic is not None:
            self.elastic.register()
            registered = True
        try:
            self.attempt = 0
            while True:
                self._rendezvous()
                try:
                    child = self._spawn(self.attempt)
                except OSError as e:
                    logger.warning("supervisor: spawn failed "
                                   "(attempt %d): %s", self.attempt, e)
                    reason, code = "spawn_failed", 1
                else:
                    reason, code = self._watch(child)
                if reason == "ok":
                    return 0
                if self.attempt >= self.max_restarts:
                    logger.error(
                        "supervisor: %s (exit %s) with restart budget "
                        "exhausted after attempt %d — giving up",
                        reason, code, self.attempt)
                    return code or 1
                self.attempt += 1
                self.restarts.append((reason, self.attempt))
                self._restart_counter().labels(reason=reason).inc()
                backoff = next(delays)
                span = self.tracer().start_trace(
                    "supervisor::relaunch",
                    attributes={"reason": reason, "attempt": self.attempt,
                                "exit_code": code, "backoff_s": backoff,
                                **self._resume_evidence()})
                span.end()
                logger.warning(
                    "supervisor: trainer %s (exit %s) — relaunching "
                    "(attempt %d/%d) after %.2fs, resuming from step %s",
                    reason, code, self.attempt, self.max_restarts,
                    backoff, self._resume_step())
                time.sleep(backoff)
        finally:
            self._child_up(False)
            if registered:
                try:
                    self.elastic.deregister()
                except (OSError, RuntimeError):
                    pass


def main(argv=None):  # pragma: no cover - thin CLI shim over the class
    """``python -m paddle_tpu.resilience.supervisor --checkpoint-dir d
    -- trainer.py args...`` — supervise a trainer from the shell."""
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.resilience.supervisor",
        description="Autonomously relaunch a training script, resuming "
                    "from its newest intact checkpoint")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument("--log-path", default=None)
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="trainer command (prefix with --)")
    args = p.parse_args(argv)
    cmd = [c for c in args.cmd if c != "--"]
    if not cmd:
        p.error("no trainer command given")
    if cmd[0].endswith(".py"):
        cmd = [sys.executable, *cmd]
    sup = TrainingSupervisor(cmd, checkpoint_dir=args.checkpoint_dir,
                             max_restarts=args.max_restarts,
                             log_path=args.log_path)
    return sup.run()


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
