"""paddle_tpu.serving — continuous-batching LLM serving on TPU.

The production tail of the inference stack (the reference grew
paddle/fluid/inference the same way): a paged KV cache
(:mod:`kv_cache`), a continuous-batching scheduler (:mod:`engine`) over
the paged-attention decode kernel (kernels/paged_attention.py), and the
serving facade over the framework-wide metrics registry
(:mod:`metrics` → paddle_tpu.observability).  ``inference.Config
.enable_generation()`` + ``create_predictor`` expose it through the
predictor API; ``bench.py --section serving`` measures tokens/sec and
TTFT under a Poisson arrival trace.

Overload behavior is part of the contract (README "Resilience"):
infeasible requests are REJECTED hard at submit; with watermarks
armed, feasible-but-unlucky ones get the soft RETRY_AFTER; requests
with a TTL are EVICTED (pages freed, partial output kept) the moment
a step starts past their deadline; the ``serving_engine_healthy``
gauge tells ops which regime the engine is in.

Drain-estimate contract: every RETRY_AFTER request carries
``Request.retry_after_s`` — a finite, strictly positive number of
seconds derived from the live backlog (queued + running decode tokens
still owed) divided by the engine's EWMA decode rate
(``Engine.estimated_drain_s()``).  The same figure is published as the
``serving_estimated_drain_seconds`` gauge and on the telemetry server's
``/healthz`` (README "Flight recorder"), so front-ends and fleet
schedulers back off by measured drain time, not a guessed constant.
Every request is additionally traced queued→prefill→decode[i]→terminal
through ``Engine.tracer`` (chrome-trace / JSON exportable).
"""
from .engine import Engine, Request, RequestState, SamplingParams  # noqa: F401
from .kv_cache import PagedKVCache  # noqa: F401
from .metrics import Counter, Gauge, Histogram, ServingMetrics  # noqa: F401
