"""paddle_tpu.serving — continuous-batching LLM serving on TPU with a
unified-step (chunked prefill) scheduler.

The production tail of the inference stack (the reference grew
paddle/fluid/inference the same way): a paged KV cache
(:mod:`kv_cache`), a continuous-batching scheduler (:mod:`engine`) over
the fused ragged paged-attention kernel
(kernels/paged_attention.py), and the serving facade over the
framework-wide metrics registry (:mod:`metrics` →
paddle_tpu.observability).  ``inference.Config.enable_generation()`` +
``create_predictor`` expose it through the predictor API; ``bench.py
--section serving`` measures tokens/sec, TTFT under a Poisson arrival
trace, and the long-prompt-interference probe.

Unified-step scheduling (this replaced the prefill/decode phase split):
there is ONE jitted program, ``serving::unified_step``, and every
in-flight request advances through it each step as a ragged row
carrying (query_len, context_len).  A prompt is split into
``chunk_len``-token chunks that run as ordinary rows next to decode
rows, writing their K/V into the paged pool incrementally, so a long
prompt can never stall the decoding batch (head-of-line blocking) —
the worst decode stall is one chunk step.  ``chunk_len`` is the knob:
larger chunks finish a given prompt's prefill in fewer steps, smaller
chunks bound the per-step latency everyone else pays.  The first token
is sampled by the step in which the LAST chunk completes — that is the
TTFT event (``serving_ttft_seconds``), and each chunk increments
``serving_prefill_chunks_total``.

Admission semantics: any prompt with prompt + max_new_tokens ≤
cfg.max_seq_len (and a page count the pool could ever hold) is
admissible — there is no prompt-length ceiling below that; the old
``prefill_len`` gate is gone (the name survives as a legacy alias for
``chunk_len``).  Pages are allocated chunk-by-chunk: admission reserves
only the first chunk, later chunks extend the page table step by step,
and memory pressure preempts the youngest row — mid-prefill rows
included, whose already-written chunk pages are freed (likewise on
deadline eviction).

Overload behavior is part of the contract (README "Resilience"):
infeasible requests are REJECTED hard at submit; with watermarks
armed, feasible-but-unlucky ones get the soft RETRY_AFTER; requests
with a TTL are EVICTED (pages freed, partial output kept) the moment
a step starts past their deadline; the ``serving_engine_healthy``
gauge tells ops which regime the engine is in.

Drain-estimate contract: every RETRY_AFTER request carries
``Request.retry_after_s`` — a finite, strictly positive number of
seconds derived from the live backlog (queued + running decode tokens
still owed) divided by the engine's EWMA decode rate
(``Engine.estimated_drain_s()``).  Before the EWMA has its first real
sample the estimate never reports below the configurable
``drain_floor_s`` cold-start floor (default ``Engine.DRAIN_FLOOR_S``),
so a freshly (re)started replica is never advertised as instantly
drainable.  The same figure is published as the
``serving_estimated_drain_seconds`` gauge and on the telemetry server's
``/healthz`` (README "Flight recorder"), so front-ends and fleet
schedulers back off by measured drain time, not a guessed constant.
Every request is additionally traced
queued→chunk[i]→decode[i]→terminal through ``Engine.tracer``
(chrome-trace / JSON exportable).

Prefix-cache contract (:mod:`kv_cache` radix tree + refcounts — README
"Serving fleet"): with ``Engine(prefix_cache=True)`` (the default),
admission walks a radix tree keyed on page-aligned token-ID prefixes;
the longest cached prefix is mapped into the new request's page table
**read-only** (per-page refcount bump) and chunked prefill starts at
the first uncached token — a fully-cached prompt copy-on-writes only
its final page and re-runs exactly one token for logits.  A prompt's
FULL pages enter the tree when its prefill completes.  Semantics the
cache guarantees:

- **token-identical** — cached K/V is a pure function of the token
  prefix, so a cache-hit request's greedy output equals a cold prefill
  of the same prompt (parity-tested, mid-chunk hits and failover
  included).
- **mid-decode pages are never shared** — only full *prompt* pages are
  cached.  The partial final prompt page (and every decode page) keeps
  receiving writes from its owning sequence, so it never enters the
  tree; sharing it would let one request's decode corrupt another's
  context.
- **eviction vs shedding** — ``free()`` decrements, never force-frees:
  a page returns to the pool only at refcount zero.  Cached pages no
  sequence references are *evictable*: ``occupancy()`` counts them as
  free and allocation LRU-evicts them on demand, so a warm cache never
  trips the RETRY_AFTER watermarks — shedding fires on real memory
  pressure only, and deadline eviction of a request mid-prefill
  decrements its shared pages rather than corrupting its siblings.
- ``defrag()`` relocates a shared page once and rewrites every
  referencing page table plus its radix node.

Fleet-router contract (:mod:`router` — README "Serving fleet"): a
:class:`FleetRouter` over N replica engines is the fleet-level
robustness unit.  Semantics it guarantees:

- **drain-based, cache-aware balancing** — each admission goes to the
  admittable replica with the best ``estimated_drain_s −
  expected_prefix_hit_tokens × cache_hit_token_s`` score (queue depth
  + running count break ties): backlog self-levels across the fleet,
  and a request whose system prompt is already warm somewhere routes
  there unless that replica's backlog outweighs the prefill saved.
  Expected hits come from bounded radix summaries (hash-only, no token
  ids) each replica publishes — in-process pulls by default,
  :mod:`prefix_gossip` over TCPStore for cross-host fleets.  Gossip is
  advisory: the target re-walks its own tree at admission, so stale
  summaries cost FLOPs, never correctness.
- **bounded backpressure** — a replica's RETRY_AFTER closes its
  admission window for ``max(retry_after_s, jittered exponential
  delay)`` capped at ``backoff_cap_s`` (``resilience.retry``'s
  full-jitter generator); the window resets on the next successful
  dispatch.  The router never hammers a shedding replica and never
  abandons it either.
- **circuit breaker** — ``breaker_threshold`` failures (OSError from
  step/admit/probe, an admission stall over ``stall_timeout_s`` wall
  time, or ``probe_miss_threshold`` missed health probes) open the
  replica's breaker: out of rotation until restarted.
- **idempotent re-enqueue (zero loss)** — on failover or drain
  deadline, every in-flight request moves back to the router queue
  head *exactly once per event*, re-dispatched as an ordinary
  admission of ``prompt + harvested tokens``; KV state is rebuilt,
  never trusted, only completed-step tokens count as emitted, so
  greedy output is token-identical to an un-failed run and nothing is
  emitted twice.
- **rolling restarts** — ``drain(rid)`` stops admissions, lets decode
  finish within ``drain_deadline_s`` (stragglers re-dispatched), then
  rebuilds the engine from its factory and re-enters rotation.
- **fleet health fold** — ``/healthz`` (with the router attached to
  the telemetry server) is 503 only when NO replica can admit: all
  breakers open or draining.  One shedding replica is soft
  backpressure, not an outage, and the cascade breaker being open
  with admittable replicas left is likewise soft (the payload carries
  ``cascade_breaker_open``, ``quarantined`` and ``suspects``).

Blast-radius containment contract (:mod:`engine` + :mod:`router` —
README "Serving fleet"): failures are attributed to the narrowest
thing that caused them — a row, a request, a replica — and contained
there.  Semantics it guarantees:

- **per-row isolation (engine)** — a Python exception raised while
  planning or committing one specific row (packing its chunk, mapping
  its pages, sampling/committing its token) is pinned on that request:
  terminal ``RequestState.FAILED``, pages freed, trace closed with the
  error — the other rows in the batch and the engine itself sail on.
  Only failures not attributable to a row (the jitted step itself, the
  top-of-step fault site, OSError RPC edges) escalate to the router's
  replica-failure path.
- **suspicion by content (router)** — every request aboard a replica
  at the moment of an *uncontrolled* failure earns one suspicion
  point, keyed by prompt hash, per DISTINCT failure event: failover
  re-dispatches and re-submitted retries accumulate instead of
  resetting.  Finishing a run exonerates the prompt.
- **canary trial** — a request with ``canary_threshold`` (default 2)
  points is only ever dispatched ALONE, on an idle replica reserved
  for it (``canary_for``); no innocent is ever co-batched with a
  request on trial.  Killing the canary convicts it: terminal
  ``FleetRequestState.QUARANTINED`` with evidence attached (suspicion,
  failure-event ids, canary replica, error) — never re-dispatched.  A
  canary death is *controlled*: the replica restarts from its factory
  and is counted in ``router_canary_deaths_total``, not the failure
  window — which is what bounds a K-threshold poison storm at ≤ K+1
  uncontrolled replica kills.
- **cascade breaker (fleet)** — ``cascade_threshold`` uncontrolled
  failures inside ``cascade_window_s`` open the fleet breaker
  (``router_cascade_breaker_open`` = 1, a ``router::cascade`` span
  brackets the storm): every suspect with ≥ 1 point must pass a canary
  trial before normal dispatch resumes for it, and the attached
  autoscaler vetoes scale-up while the breaker is open (a poison storm
  is failure churn, not load — spawning would feed it fresh victims;
  zero-healthy recovery still scales).  The breaker closes when the
  window empties and no suspects remain queued or on trial.
- **innocents are never taxed** — a co-batched innocent rides the
  ordinary exactly-once failover: re-dispatch replays ``prompt +
  harvested tokens`` and host-side greedy sampling is batch-
  composition-independent, so its output stays token-identical to a
  poison-free run no matter how many neighbours get quarantined.

Autoscaler contract (:mod:`autoscaler` — README "Elastic fleet"): an
:class:`Autoscaler` attached to a router sizes the fleet from live
signals.  Semantics it guarantees:

- **signals** — each tick polls, on an injectable clock: every healthy
  replica's ``estimated_drain_s`` and queue depth, the router's
  pending depth, the shed/RETRY_AFTER delta since the last poll, and
  the goodput ratio (finished ÷ dispatched, telemetry).  They fold
  into one *pressure* figure: mean drain seconds per **ready** replica
  plus a pending-depth term.
- **warming is not capacity** — a replica whose decode EWMA has no
  real sample (``health()['decode_rate_tok_s'] is None``) still
  advertises ``drain_floor_s`` and is excluded from the ready count.
  ``Engine.warmup()`` preserves this: it compiles the unified step via
  one tiny request, then resets the EWMA, so a freshly scaled-up
  replica enters rotation warm-compiled but still on the cold-start
  floor until its first real decode step.
- **hysteresis + per-direction cooldowns** — up only when pressure is
  *strictly* above ``up_pressure_s`` (or pending strictly above
  ``up_pending_depth``, or any shed since the last poll); down only
  when pressure is *strictly* below ``down_pressure_s`` with zero
  pending/queued/shed and nothing draining.  Load exactly on a band
  boundary produces zero events, and each direction freezes for its
  own cooldown after acting — no flapping.
- **scale-up = supervised spawn** — revive the cheapest DEAD
  restartable replica, else append through the engine factory
  (``router.add_replica``); either way ``warmup()`` runs before
  rotation entry, and spawn attempts retry with jittered backoff out
  of a bounded budget (the supervisor discipline; the
  ``autoscaler.scale_up`` fault site injects the OSError this path
  must survive, ``autoscaler.poll`` the control-loop stall).
- **scale-down = cache-warmth-aware drain** — victim is the *coldest*
  healthy replica by gossiped radix summary (sum of cached prefix
  token depths = the prefill FLOPs its cache is worth; ties: fewest
  in-flight, then youngest), drained gracefully with
  ``router.drain(rid, restart=False)`` — stragglers re-dispatch
  exactly once, zero loss holds through every scale event.
- **observability** — ``autoscaler_scale_events_total{direction,
  reason}`` / ``autoscaler_target_replicas`` / ``autoscaler::scale``
  spans, and an ``autoscaler`` block folded into ``/fleet``.
- **SLO coupling** (both optional) — with a
  :class:`~paddle_tpu.observability.timeseries.TimeSeriesStore`
  attached (``timeseries=``), the shed and goodput signals become
  ``signal_window_s``-windowed, counter-reset-safe store deltas
  instead of tick-to-tick counter differences; with an
  :class:`~paddle_tpu.observability.slo.SLOEngine` attached
  (``slo=``), a firing fast-burn **page** escalates scale-up past the
  hysteresis band (reason ``slo_fast_burn`` — budget emptying at page
  speed IS demand, even before pressure catches up; cooldown,
  ``max_replicas`` and the cascade veto still bound it), and
  scale-down additionally requires a *healthy* budget: no alert
  active and every objective retaining at least
  ``slo_down_min_budget`` of its error budget.

Distributed-tracing contract (paddle_tpu.observability.tracing +
:mod:`router` — README "Distributed tracing"): every request carries
ONE globally unique ``trace_id`` from router admission to terminal
state, across processes and across failures.  Semantics it guarantees:

- **globally unique ids** — trace/span ids are prefixed with a
  per-process nonce (pid + random), so segments recorded by the
  router, by each replica engine, and by a restarted process never
  collide and can be merged by ``trace_id`` alone.
- **cross-process propagation** — the router serialises a
  ``TraceContext`` (trace_id + parent span_id) into every dispatch;
  ``Engine.add_request(..., trace_context=...)`` continues the trace
  as a child segment.  A failover re-dispatch reuses the ORIGINAL
  request's context, so a hard-killed request reads as one trace with
  both ``router::dispatch`` hops and the ``router::failover`` span on
  it — never two half-traces.
- **tail-based retention** — completed traces are kept by what
  happened on them (error, fault-injection event, flagged span,
  rejection/retry/eviction/failover, deadline, slow-tail), with a
  seeded coin-flip for the boring rest; the ring evicts boring-first,
  so a flood of healthy traffic cannot push out the one trace that
  shed or failed over.  Fired fault injections
  (:mod:`paddle_tpu.resilience.faults`) record (site, kind,
  occurrence, seed) on the ambient span, making a retained trace
  self-describing.
- **fleet collection** — each replica publishes its retained ring
  over the TCPStore plane (``TraceRingPublisher`` /
  ``collect_fleet_traces``); ``router.collect_traces()`` and the
  telemetry server's ``/traces?fleet=1`` merge segments by trace_id
  into one fleet-wide view, chrome-trace exportable.  Histogram
  exemplars (``serving_ttft_seconds`` et al.) link each latency
  bucket to a retained exemplar trace in the OpenMetrics exposition.

Soak exit criteria (:mod:`soak`, ``bench.py --section soak`` and the
compressed tier-1 variant): replaying a seeded diurnal/bursty trace
(:mod:`traffic`) through the autoscaled fleet while the chaos timeline
fires hard kills, admission stalls, poll stalls, spawn I/O errors,
KV-page bitflips, and poison storms must end with ``lost_requests ==
0`` (quarantined/row-failed requests are *contained and accounted*,
not lost), bounded TTFT p99, at least one scale-up AND one scale-down
recorded in ``/fleet``, every poison request terminal ``QUARANTINED``
and visible on ``/fleet`` and the retained trace ring, and every chaos
event visible as a ``soak::*`` record in ``/flight``.
"""
from .engine import Engine, Request, RequestState, SamplingParams  # noqa: F401
from .kv_cache import PagedKVCache, prefix_hashes  # noqa: F401
from .prefix_gossip import (  # noqa: F401
    PrefixSummaryPublisher,
    collect_prefix_summaries,
)
from .metrics import (  # noqa: F401
    AutoscalerMetrics,
    Counter,
    Gauge,
    Histogram,
    RouterMetrics,
    ServingMetrics,
)
from .router import (  # noqa: F401
    FleetRequest,
    FleetRequestState,
    FleetRouter,
    Replica,
    ReplicaState,
)
from .autoscaler import Autoscaler  # noqa: F401
from .traffic import Arrival, TrafficGenerator  # noqa: F401
from .replica import ReplicaServer  # noqa: F401
from .soak import ChaosEvent, run_soak  # noqa: F401
