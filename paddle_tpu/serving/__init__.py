"""paddle_tpu.serving — continuous-batching LLM serving on TPU with a
unified-step (chunked prefill) scheduler.

The production tail of the inference stack (the reference grew
paddle/fluid/inference the same way): a paged KV cache
(:mod:`kv_cache`), a continuous-batching scheduler (:mod:`engine`) over
the fused ragged paged-attention kernel
(kernels/paged_attention.py), and the serving facade over the
framework-wide metrics registry (:mod:`metrics` →
paddle_tpu.observability).  ``inference.Config.enable_generation()`` +
``create_predictor`` expose it through the predictor API; ``bench.py
--section serving`` measures tokens/sec, TTFT under a Poisson arrival
trace, and the long-prompt-interference probe.

Unified-step scheduling (this replaced the prefill/decode phase split):
there is ONE jitted program, ``serving::unified_step``, and every
in-flight request advances through it each step as a ragged row
carrying (query_len, context_len).  A prompt is split into
``chunk_len``-token chunks that run as ordinary rows next to decode
rows, writing their K/V into the paged pool incrementally, so a long
prompt can never stall the decoding batch (head-of-line blocking) —
the worst decode stall is one chunk step.  ``chunk_len`` is the knob:
larger chunks finish a given prompt's prefill in fewer steps, smaller
chunks bound the per-step latency everyone else pays.  The first token
is sampled by the step in which the LAST chunk completes — that is the
TTFT event (``serving_ttft_seconds``), and each chunk increments
``serving_prefill_chunks_total``.

Admission semantics: any prompt with prompt + max_new_tokens ≤
cfg.max_seq_len (and a page count the pool could ever hold) is
admissible — there is no prompt-length ceiling below that; the old
``prefill_len`` gate is gone (the name survives as a legacy alias for
``chunk_len``).  Pages are allocated chunk-by-chunk: admission reserves
only the first chunk, later chunks extend the page table step by step,
and memory pressure preempts the youngest row — mid-prefill rows
included, whose already-written chunk pages are freed (likewise on
deadline eviction).

Overload behavior is part of the contract (README "Resilience"):
infeasible requests are REJECTED hard at submit; with watermarks
armed, feasible-but-unlucky ones get the soft RETRY_AFTER; requests
with a TTL are EVICTED (pages freed, partial output kept) the moment
a step starts past their deadline; the ``serving_engine_healthy``
gauge tells ops which regime the engine is in.

Drain-estimate contract: every RETRY_AFTER request carries
``Request.retry_after_s`` — a finite, strictly positive number of
seconds derived from the live backlog (queued + running decode tokens
still owed) divided by the engine's EWMA decode rate
(``Engine.estimated_drain_s()``).  The same figure is published as the
``serving_estimated_drain_seconds`` gauge and on the telemetry server's
``/healthz`` (README "Flight recorder"), so front-ends and fleet
schedulers back off by measured drain time, not a guessed constant.
Every request is additionally traced
queued→chunk[i]→decode[i]→terminal through ``Engine.tracer``
(chrome-trace / JSON exportable).
"""
from .engine import Engine, Request, RequestState, SamplingParams  # noqa: F401
from .kv_cache import PagedKVCache  # noqa: F401
from .metrics import Counter, Gauge, Histogram, ServingMetrics  # noqa: F401
