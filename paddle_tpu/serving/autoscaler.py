"""Elastic fleet autoscaler — the control loop that sizes the fleet.

PR 9 made the fleet fault-tolerant and PR 14 made it cache-warm, but
its size was fixed at construction: a diurnal or bursty trace either
over-provisions chips all night or sheds load all afternoon.  The
:class:`Autoscaler` closes that gap by watching the signals every
replica already exports and driving the :class:`~.router.FleetRouter`
elastic:

- **signals** (polled each :meth:`tick` on an injectable clock): every
  healthy replica's ``estimated_drain_s`` and queue depth (from
  ``engine.health()``), the router's pending depth, the RETRY_AFTER /
  shed rate, and the fleet goodput ratio (finished ÷ dispatched).
  With a :class:`~paddle_tpu.observability.timeseries.TimeSeriesStore`
  attached (``timeseries=``), shed and goodput come from *windowed*
  store deltas (``signal_window_s`` wide, counter-reset-safe) instead
  of the ad-hoc between-poll counter base the loop otherwise keeps —
  the window is the same no matter how irregular the tick cadence.
  They fold into one *pressure* figure — mean drain seconds per
  **ready** replica plus a pending-depth term — so the decision scales
  with fleet size.
- **SLO input** (``slo=``, an
  :class:`~paddle_tpu.observability.slo.SLOEngine`): a firing
  fast-burn *page* alert escalates scale-up (reason
  ``slo_fast_burn``) even when instantaneous pressure sits inside the
  hysteresis band — the budget emptying at page speed IS demand the
  pressure figure has not caught up to.  Scale-down is gated the other
  way: only while no alert is active and every objective keeps at
  least ``slo_down_min_budget`` of its error budget — a healthy
  budget *permits* shrinking, a burning one forbids it.
  ``slo_scale_up_on`` (a name tuple) restricts which objectives' pages
  escalate; default: any page.
- **warming replicas don't count** — a replica whose decode-rate EWMA
  has no real sample yet (freshly spawned/revived; ``warmup()`` resets
  the EWMA, see :meth:`~.engine.Engine.warmup`) still advertises its
  ``drain_floor_s`` and is excluded from the ready count: the
  autoscaler never treats capacity it cannot prove as absorbed load,
  and never reads a cold replica's floor as backlog pressure it should
  scale away from.
- **hysteresis + per-direction cooldowns** — scale up only when
  pressure is *strictly above* ``up_pressure_s`` (or pending depth
  strictly above ``up_pending_depth``, or any shed events since the
  last poll), scale down only when pressure is *strictly below*
  ``down_pressure_s`` with zero pending/queued/shed.  Load oscillating
  exactly at a band boundary produces zero events.  After a scale-up,
  further ups freeze for ``scale_up_cooldown_s``; scale-down freezes
  for ``scale_down_cooldown_s`` after a scale event in *either*
  direction (an up is never immediately undone — the classic flap —
  while an up right after a down stays fast, because under-capacity
  is the expensive failure mode).
- **cascade-breaker coordination** — when the router's cascade breaker
  is open (≥ K uncontrolled replica failures in its sliding window — a
  poison storm), every scale-up trigger except zero-healthy recovery
  is vetoed: the pending backlog is failure churn, not demand, and a
  spawn would only hand the poison a fresh victim.  A genuine load
  burst arriving mid-storm still scales once the breaker closes.
- **scale-up = spawn through the router's factory path** — a DEAD
  restartable replica is revived first (the cheapest capacity); else
  a fresh replica is appended via :meth:`~.router.FleetRouter.add_replica`.
  Either way the engine runs ``warmup()`` *before* rotation entry, and
  the spawn is retried with jittered exponential backoff out of a
  bounded budget (the PR 6 supervisor spawn discipline) — the
  ``autoscaler.scale_up`` fault site injects the io_error that path
  must survive.
- **scale-down = cache-warmth-aware drain** — the victim is the
  *coldest* replica by gossiped prefix-radix summary (PR 14): each
  candidate's expected hit-token value is the sum of cached-prefix
  token depths in its bounded summary, so the replica whose cache is
  worth the least prefill FLOPs drains first (ties: fewest in-flight,
  then the youngest replica).  The drain itself is the router's
  graceful :meth:`~.router.FleetRouter.drain` with ``restart=False``
  — in-flight decode finishes (stragglers re-dispatch exactly once),
  then the replica leaves rotation as revivable capacity.

Observability: ``autoscaler_scale_events_total{direction,reason}`` /
``autoscaler_target_replicas`` / ``autoscaler_ready_replicas`` /
``autoscaler_pressure_seconds`` in the metrics registry,
``autoscaler::scale`` tracer spans per event, and — with the
autoscaler attached to its router — an ``autoscaler`` block in the
``/fleet`` payload (target, ready/warming counts, last signals,
cooldown state, recent events).

Fault sites (see :mod:`paddle_tpu.resilience.faults`):
``autoscaler.poll`` fires at the top of every tick (a ``stall`` there
is the control loop hiccuping — scaling is delayed, never wrong);
``autoscaler.scale_up`` fires before every spawn attempt (an
``io_error`` is a spawn that died — retried with backoff out of the
bounded budget, then counted as ``autoscaler_spawn_failures_total``).

Threading: :meth:`tick` may be driven by any loop (the soak harness
drives it inline; :meth:`start` runs it on a daemon thread) while the
telemetry server's scrape thread reads :meth:`status` — all mutable
autoscaler state is guarded by one lock.  The autoscaler lock is
always taken *before* any router call (which takes the router's own
lock); :meth:`status` touches only autoscaler state, and the router's
``fleet_status`` folds it in outside the router lock, so the two
locks never interleave in opposite orders.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from ..observability.tracing import Tracer, activate, default_tracer
from ..resilience.faults import fault_point
from ..resilience.retry import backoff_delays
from .metrics import AutoscalerMetrics
from .router import ReplicaState

__all__ = ["Autoscaler"]


class Autoscaler:
    """Elastic control loop over one :class:`~.router.FleetRouter`.

    ``factory`` is the zero-arg engine factory scale-up appends fresh
    replicas through (default: the first factory-built replica's own
    factory).  ``min_replicas``/``max_replicas`` bound the in-rotation
    count.  The hysteresis band is ``(down_pressure_s, up_pressure_s)``
    on the fleet pressure signal (strict comparisons on both edges);
    ``up_pending_depth`` is the router-queue depth that also triggers
    scale-up, and any shed/RETRY_AFTER event since the last poll does
    too.  ``scale_up_cooldown_s``/``scale_down_cooldown_s`` freeze
    each direction independently after an event.  ``spawn_max_retries``
    bounds the spawn-retry budget (jittered backoff between attempts).
    ``warmup=True`` runs ``engine.warmup()`` on every spawned/revived
    engine before rotation entry.  ``clock`` is injectable (tests run
    the whole loop on a manual clock); ``pending_token_s`` converts one
    pending request into pressure seconds.

    ``timeseries`` (a :class:`~paddle_tpu.observability.timeseries.
    TimeSeriesStore` scraping the same registry) switches the
    shed/goodput signals to ``signal_window_s``-windowed store deltas;
    ``slo`` (an :class:`~paddle_tpu.observability.slo.SLOEngine`)
    escalates scale-up under a firing fast-burn page (filtered by
    ``slo_scale_up_on`` when given) and gates scale-down on a healthy
    budget (every objective ≥ ``slo_down_min_budget`` remaining, no
    alert active).  Both default to None — the loop then behaves
    exactly as before."""

    def __init__(self, router, factory=None, *, min_replicas=1,
                 max_replicas=4, poll_interval_s=0.0,
                 up_pressure_s=2.0, down_pressure_s=0.25,
                 up_pending_depth=6, pending_token_s=0.05,
                 scale_up_cooldown_s=2.0, scale_down_cooldown_s=5.0,
                 spawn_max_retries=2, spawn_backoff_base_s=0.05,
                 spawn_backoff_cap_s=1.0, warmup=True, clock=None,
                 tracer=None, registry=None, rng=None, slo=None,
                 timeseries=None, signal_window_s=2.0,
                 slo_down_min_budget=0.25, slo_scale_up_on=None):
        if max_replicas < min_replicas:
            raise ValueError(f"max_replicas {max_replicas} < "
                             f"min_replicas {min_replicas}")
        if down_pressure_s >= up_pressure_s:
            raise ValueError(
                f"hysteresis band is empty: down_pressure_s "
                f"{down_pressure_s} >= up_pressure_s {up_pressure_s}")
        self.router = router
        self._factory = factory
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.poll_interval_s = float(poll_interval_s)
        self.up_pressure_s = float(up_pressure_s)
        self.down_pressure_s = float(down_pressure_s)
        self.up_pending_depth = (None if up_pending_depth is None
                                 else int(up_pending_depth))
        self.pending_token_s = float(pending_token_s)
        self.scale_up_cooldown_s = float(scale_up_cooldown_s)
        self.scale_down_cooldown_s = float(scale_down_cooldown_s)
        self.spawn_max_retries = int(spawn_max_retries)
        self.spawn_backoff_base_s = float(spawn_backoff_base_s)
        self.spawn_backoff_cap_s = float(spawn_backoff_cap_s)
        self.warmup = bool(warmup)
        self._clock = clock or time.perf_counter
        if tracer is None:
            tracer = (default_tracer() if clock is None
                      else Tracer(clock=self._clock))
        self.tracer = tracer
        self.metrics = AutoscalerMetrics(registry=registry)
        self._rng = rng
        # optional SLO coupling — read-only config after construction
        self.slo = slo
        self.timeseries = timeseries
        self.signal_window_s = float(signal_window_s)
        self.slo_down_min_budget = float(slo_down_min_budget)
        self.slo_scale_up_on = (None if slo_scale_up_on is None
                                else tuple(slo_scale_up_on))
        # tick() (driver/daemon thread) mutates, status() (telemetry
        # scrape thread) reads — one lock guards all mutable state.
        # Always taken BEFORE any router call; never held by status().
        self._lock = threading.Lock()
        self._last_poll = None      # guarded-by: self._lock
        self._last_up = None        # guarded-by: self._lock
        self._last_down = None      # guarded-by: self._lock
        self._last_signals = None   # guarded-by: self._lock
        self._events = deque(maxlen=64)   # guarded-by: self._lock
        self._counter_base = None   # guarded-by: self._lock
        self._up_events = 0         # guarded-by: self._lock
        self._down_events = 0       # guarded-by: self._lock
        self._spawn_failures = 0    # guarded-by: self._lock
        self._target = None         # guarded-by: self._lock
        self._thread = None
        self._stop = threading.Event()
        router.attach_autoscaler(self)

    # ------------------------------------------------------------- signals
    def _router_counters(self):
        """The monotonic router counters the shed/goodput deltas are
        computed over."""
        snap = self.router.metrics.snapshot()
        return {
            "backpressure": sum((snap.get("backpressure_retries")
                                 or {}).values()),
            "dispatches": sum((snap.get("dispatches") or {}).values()),
            "finished": snap.get("finished") or 0,
        }

    def _signals_locked(self, now):
        """One poll of the fleet: per-replica drain/queue (dead and
        draining replicas excluded), warming count, pending depth,
        shed delta, goodput ratio — folded into the pressure figure
        the bands compare against."""
        drains, queues, warming = {}, {}, []
        healthy = draining = 0
        finished = 0
        for rep in self.router.replicas:
            if rep.state == ReplicaState.DRAINING:
                draining += 1
            if rep.state != ReplicaState.HEALTHY:
                continue
            healthy += 1
            try:
                h = rep.engine.health()
            except (OSError, AttributeError):
                continue    # the router's own probe path retires it
            rid = rep.replica_id
            drains[rid] = float(h.get("estimated_drain_s") or 0.0)
            queues[rid] = int(h.get("queue_depth") or 0)
            if h.get("decode_rate_tok_s") is None:
                warming.append(rid)
        ready = max(0, healthy - len(warming))
        pending = self.router.pending_depth()
        # the router's cascade breaker: >= K uncontrolled replica
        # failures in the sliding window means the backlog is a poison
        # storm churning the fleet, not organic load — scale-up on it
        # would spawn fresh victims
        cascade = bool(getattr(self.router, "cascade_open",
                               lambda: False)())
        if self.timeseries is not None:
            # windowed, counter-reset-safe deltas from the store — the
            # window is signal_window_s wide no matter how irregular
            # the tick cadence (the between-poll counter base below is
            # exactly as wide as the gap between two ticks happened to
            # be, which is the ad-hoc part this replaces)
            w = self.signal_window_s
            shed_delta = self.timeseries.delta(
                "router_backpressure_retries_total", window_s=w) or 0.0
            dispatch_delta = self.timeseries.delta(
                "router_dispatches_total", window_s=w) or 0.0
            finished_delta = self.timeseries.delta(
                "router_requests_finished_total", window_s=w) or 0.0
        else:
            counters = self._router_counters()
            base = self._counter_base or counters
            self._counter_base = counters
            shed_delta = counters["backpressure"] - base["backpressure"]
            dispatch_delta = counters["dispatches"] - base["dispatches"]
            finished_delta = counters["finished"] - base["finished"]
        goodput = (min(1.0, finished_delta / dispatch_delta)
                   if dispatch_delta > 0 else None)
        slo_alerts, slo_page, slo_budget = [], False, None
        if self.slo is not None:
            slo_alerts = self.slo.alerts_active()
            slo_budget = self.slo.min_budget_ratio()
            watched = self.slo_scale_up_on
            slo_page = any(
                sev == "page" and (watched is None or name in watched)
                for name, sev in slo_alerts)
        # warming replicas are NOT capacity: their drain floor is a
        # cold-start advertisement, not backlog — pressure is backlog
        # seconds per replica that can actually absorb it
        ready_drain = [drains[r] for r in drains if r not in warming]
        denom = max(ready, 1)
        pressure = (sum(ready_drain) / denom
                    + pending * self.pending_token_s / denom)
        return {
            "healthy": healthy, "ready": ready,
            "warming": list(warming), "draining": draining,
            "pending_depth": pending,
            "queue_depth": sum(queues.values()),
            "drain_s": drains,
            "shed_delta": shed_delta,
            "goodput_ratio": goodput,
            "pressure_s": pressure,
            "cascade_open": cascade,
            "slo_page": slo_page,
            "slo_alerts": slo_alerts,
            "slo_min_budget": slo_budget,
            "time": now,
        }

    # ------------------------------------------------------------ decision
    def _decide_locked(self, sig, now):
        """(direction, reason) or None under the hysteresis bands and
        per-direction cooldowns.  Strict comparisons on both band
        edges: load sitting exactly on a boundary never scales."""
        healthy = sig["healthy"]
        up_ok = (healthy < self.max_replicas
                 and (self._last_up is None
                      or now - self._last_up >= self.scale_up_cooldown_s))
        # the down window counts from the last event in EITHER
        # direction: a scale-up is never immediately undone (the
        # classic flap), while an up right after a down stays fast —
        # under-capacity is the expensive failure mode
        last_any = max((t for t in (self._last_up, self._last_down)
                        if t is not None), default=None)
        down_ok = (healthy > self.min_replicas
                   and sig["draining"] == 0
                   and (last_any is None
                        or now - last_any >= self.scale_down_cooldown_s))
        if healthy == 0 and self.max_replicas > 0:
            # nobody can absorb anything — bypass the up cooldown, this
            # is recovery, not flap (every replica dead or draining).
            # The cascade breaker does NOT veto this one: with zero
            # healthy replicas even the canary trials are starved.
            return ("up", "no_capacity")
        if sig.get("cascade_open"):
            # poison storm in progress: the pending depth and shed rate
            # are failure churn, not demand — adding replicas only
            # feeds the cascade fresh victims.  A real load burst that
            # arrives meanwhile still scales once the breaker closes.
            return None
        if up_ok:
            if sig.get("slo_page"):
                # the error budget is emptying at page speed: that IS
                # demand, whether or not the pressure figure has caught
                # up — escalate past the hysteresis band (cooldown and
                # max_replicas still bound it, the cascade veto above
                # still wins during a storm)
                return ("up", "slo_fast_burn")
            if sig["pressure_s"] > self.up_pressure_s:
                return ("up", "pressure")
            if self.up_pending_depth is not None and \
                    sig["pending_depth"] > self.up_pending_depth:
                return ("up", "pending")
            if sig["shed_delta"] > 0:
                return ("up", "shed")
        # with an SLO engine attached, shrinking requires a *healthy*
        # budget: no alert firing and every objective above the
        # retained-budget floor — capacity is only returned when the
        # objectives can afford the risk
        slo_ok = (not sig.get("slo_alerts")
                  and (sig.get("slo_min_budget") is None
                       or sig["slo_min_budget"]
                       >= self.slo_down_min_budget))
        if down_ok and slo_ok \
                and sig["pressure_s"] < self.down_pressure_s and \
                sig["pending_depth"] == 0 and sig["queue_depth"] == 0 \
                and sig["shed_delta"] == 0:
            return ("down", "idle")
        return None

    # ------------------------------------------------------------ scale up
    def _spawn_locked(self):
        """One replica of new capacity, through the router's factory
        path: revive the cheapest DEAD restartable replica, else append
        a fresh one.  Spawn attempts are retried with jittered backoff
        out of a bounded budget — the supervisor's spawn discipline —
        and the ``autoscaler.scale_up`` fault site fires before each
        attempt."""
        delays = backoff_delays(base=self.spawn_backoff_base_s,
                                cap=self.spawn_backoff_cap_s,
                                rng=self._rng)
        last = None
        for _attempt in range(self.spawn_max_retries + 1):
            try:
                fault_point("autoscaler.scale_up")
                dead = next((rep for rep in self.router.replicas
                             if rep.state == ReplicaState.DEAD
                             and rep.factory is not None), None)
                if dead is not None:
                    return self.router.restart_replica(dead.replica_id)
                factory = self._factory
                if factory is None:
                    factory = next(
                        (rep.factory for rep in self.router.replicas
                         if rep.factory is not None), None)
                if factory is None:
                    raise OSError("autoscaler has no engine factory "
                                  "to spawn with")
                return self.router.add_replica(factory)
            except OSError as e:
                last = e
                time.sleep(next(delays))
        self._spawn_failures += 1
        self.metrics.spawn_failures.inc()
        self._events.append({"time": self._clock(), "direction": "up",
                             "reason": "spawn_failed",
                             "error": repr(last)})
        return None

    # ---------------------------------------------------------- scale down
    def _pick_victim_locked(self):
        """Cache-warmth-aware victim selection: the healthy replica
        whose gossiped radix summary is worth the fewest expected hit
        tokens drains first (its cache costs the least prefill FLOPs
        to lose).  Ties: fewest in-flight requests, then the youngest
        replica (highest id — the most recently added capacity)."""
        self.router.refresh_prefix_summaries()
        summaries = self.router.prefix_summaries()
        in_flight = self.router.in_flight_counts()
        cands = []
        for rep in self.router.replicas:
            if rep.state != ReplicaState.HEALTHY:
                continue
            s = summaries.get(rep.replica_id) or {}
            warm_tokens = (sum((s.get("entries") or {}).values())
                           if s.get("enabled", True) else 0)
            cands.append((warm_tokens,
                          in_flight.get(rep.replica_id, 0),
                          -rep.replica_id, rep))
        if not cands:
            return None, 0
        cands.sort(key=lambda c: c[:3])
        return cands[0][3], cands[0][0]

    # ---------------------------------------------------------------- tick
    def tick(self):
        """One control-loop iteration: poll signals, decide under the
        bands/cooldowns, act.  Returns the ``(direction, reason)`` of a
        scale event, or None.  Safe to call more often than
        ``poll_interval_s`` — early calls are no-ops."""
        fault_point("autoscaler.poll")
        now = self._clock()
        with self._lock:
            if self._last_poll is not None and self.poll_interval_s > 0 \
                    and now - self._last_poll < self.poll_interval_s:
                return None
            self._last_poll = now
            sig = self._signals_locked(now)
            self._last_signals = sig
            decision = self._decide_locked(sig, now)
            self.metrics.pressure.set(sig["pressure_s"])
            self.metrics.ready_replicas.set(sig["ready"])
            if decision is None:
                if self._target is None:
                    self._target = sig["healthy"]
                    self.metrics.target_replicas.set(self._target)
                return None
            direction, reason = decision
            event = {"time": now, "direction": direction,
                     "reason": reason,
                     "pressure_s": round(sig["pressure_s"], 4),
                     "pending_depth": sig["pending_depth"]}
            # the scale span opens BEFORE the action so a fault firing
            # mid-spawn (autoscaler.scale_up) lands on it as the
            # ambient active span
            span = self.tracer.start_trace(
                "autoscaler::scale", start_s=now, attributes=event)
            if direction == "up":
                with activate(span):
                    rep = self._spawn_locked()
                if rep is None:          # spawn budget exhausted
                    span.set_attribute("outcome", "spawn_failed")
                    span.end(self._clock())
                    return None
                self._last_up = now
                self._up_events += 1
                event["replica"] = rep.replica_id
                self._target = sig["healthy"] + 1
            else:
                victim, warm_tokens = self._pick_victim_locked()
                if victim is None:
                    span.set_attribute("outcome", "no_victim")
                    span.end(self._clock())
                    return None
                self.router.drain(victim.replica_id, restart=False)
                self._last_down = now
                self._down_events += 1
                event["replica"] = victim.replica_id
                event["victim_warm_tokens"] = warm_tokens
                self._target = sig["healthy"] - 1
            self._events.append(event)
            self.metrics.scale_events.labels(
                direction=direction, reason=reason).inc()
            self.metrics.target_replicas.set(self._target)
            span.set_attributes(event)
            span.end(self._clock())
            return decision

    # --------------------------------------------------------------- status
    def status(self):
        """The ``/fleet`` autoscaler block: bands, target, last
        signals, cooldown state, recent events.  Reads only autoscaler
        state (never the router), so the telemetry scrape can fold it
        into ``fleet_status`` without interleaving the two locks."""
        now = self._clock()

        def _cooldown(last, cooldown_s):
            if last is None:
                return 0.0
            return max(0.0, cooldown_s - (now - last))

        with self._lock:
            return {
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "target_replicas": self._target,
                "bands": {"up_pressure_s": self.up_pressure_s,
                          "down_pressure_s": self.down_pressure_s,
                          "up_pending_depth": self.up_pending_depth},
                "cooldown_remaining_s": {
                    "up": _cooldown(self._last_up,
                                    self.scale_up_cooldown_s),
                    "down": _cooldown(self._last_down,
                                      self.scale_down_cooldown_s)},
                "scale_events": {"up": self._up_events,
                                 "down": self._down_events},
                "spawn_failures": self._spawn_failures,
                "last_signals": ({k: v for k, v in
                                  self._last_signals.items()
                                  if not k.startswith("_")}
                                 if self._last_signals else None),
                "events": list(self._events)[-16:],
            }

    # --------------------------------------------------------------- thread
    def start(self, interval_s=None):
        """Run the control loop on a daemon thread every ``interval_s``
        (default: ``poll_interval_s`` or 1s).  Strictly opt-in — the
        soak harness and tests drive :meth:`tick` inline instead."""
        if self._thread is not None:
            return self
        beat = float(interval_s if interval_s is not None
                     else (self.poll_interval_s or 1.0))
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, args=(beat,),
                                        name="fleet-autoscaler",
                                        daemon=True)
        self._thread.start()
        return self

    def _run(self, interval_s):
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                pass    # silent-ok: a flaky poll must not kill the
                #         loop; the next beat re-reads live state
            self._stop.wait(interval_s)

    def stop(self):
        t, self._thread = self._thread, None
        if t is not None:
            self._stop.set()
            t.join(timeout=5.0)
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
