"""Continuous-batching generation engine — unified-step scheduler.

The serving-side counterpart of the training HybridEngine: requests
enter a FIFO admission queue and ONE statically-shaped jitted program
(``serving::unified_step``, compiles exactly once) advances every
in-flight request each step — whether the request is mid-prefill or
decoding.  There is no prefill phase: a prompt is split into
bounded-size *chunks* (``chunk_len`` tokens) that run as ordinary rows
of the ragged batch next to decode rows, so one long prompt can never
stall the decoding requests sharing the batch (the head-of-line
blocking the old prefill/decode phase split suffered from — "Ragged
Paged Attention", arXiv:2604.15464).

Per ``step()``:
  1. evict — drop every request (running or queued) past its deadline.
  2. admit — pop the queue head while a batch slot AND pages for its
     *first chunk* exist (pages are allocated chunk-by-chunk, not for
     the whole prompt upfront).
  3. unified step — plan the ragged batch under ``token_budget`` packed
     query tokens: every decode row gets its one token, then
     mid-prefill rows split the remaining budget fairly (a newly
     admitted short prompt is not starved behind a long one).  Chunk
     K/V is written into the paged pool incrementally; the row whose
     chunk completes its prompt samples the first token (TTFT), decode
     rows sample their next token.
  4. gauges — page-pool occupancy into the metrics registry.

Admission control: requests that can NEVER fit (prompt + max_new_tokens
over the model's max_seq_len, or more pages than the whole pool) are
rejected at submit with Request.state == REJECTED — the engine's
graceful-overload contract.  Any prompt up to that bound is admissible;
chunking removed the old ``prefill_len`` prompt-length ceiling.
Requests that merely can't fit *now* stay queued.  If a sequence
outgrows the pool mid-flight (admission is optimistic), the youngest
running sequence — mid-prefill or decoding — is preempted back to the
queue head and recomputed later — memory pressure degrades throughput,
never correctness.

Overload robustness (the production-traffic contract):

- **load shedding** — with watermarks configured, crossing the HIGH
  page-occupancy or queue-depth mark flips the engine to *degraded*:
  new submissions return ``RequestState.RETRY_AFTER`` (a soft "come
  back later", distinct from the hard ``REJECTED`` of an infeasible
  request) until occupancy/queue fall below the LOW marks (hysteresis,
  so the admit/shed decision doesn't flap per token).  The
  ``serving_engine_healthy`` gauge mirrors the state for ops.
- **deadlines** — a request with a TTL (``SamplingParams.ttl_s`` or
  the engine's ``default_ttl_s``) is EVICTED the moment a step starts
  past its deadline — mid-decode or still queued — freeing its pages
  for requests that can still meet theirs.  A request nobody is
  waiting for anymore is pure waste to keep decoding.
- **retry-after hint** — a shed request carries ``retry_after_s``:
  the engine's ``estimated_drain_s`` (outstanding decode tokens ÷ the
  EWMA decode rate), so a cooperating front-end backs off for exactly
  as long as the backlog needs instead of hammering a bare
  RETRY_AFTER.  The same figure is published on ``/healthz`` and the
  ``serving_estimated_drain_seconds`` gauge.

Flight recorder: every request is traced — a root span per request
(one chrome-trace track), with ``queued`` / ``chunk[i]`` /
``decode[i]`` child spans carrying batch-slot and page-pool-occupancy
attributes, through terminal states finished / evicted / shed.  The
engine shares the process-wide tracer by default; with an injected
``clock`` it gets a private Tracer on that clock so tests drive span
timestamps deterministically.

Prefix reuse (``prefix_cache=True``, the default): admission walks the
page pool's radix tree for the longest cached page-aligned prefix of
the prompt, maps those pages in read-only (a refcount bump instead of
prefill FLOPs) and starts chunked prefill at the first uncached token —
mid-chunk starts are fine, the planner just sees a shorter remaining
prompt.  A prompt whose prefill completes inserts its full pages back
into the tree.  K/V is a pure function of the token prefix, so a
cache-hit request's greedy output is token-identical to a cold prefill
of the same prompt (parity-tested).  Zero-ref cached pages are counted
as free for watermark/occupancy purposes and LRU-evicted on demand, so
a warm cache never sheds traffic it could serve.

Sampling is host-side (greedy / temperature / top-k / top-p) with a
per-request numpy Generator seeded at submit, so outputs are
deterministic for a fixed seed regardless of batch composition.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque

import numpy as np

import jax
import jax.numpy as jnp

from ..models.gpt import GPTConfig, gpt_init, gpt_ragged_step
from ..observability.compile_watchdog import watch
from ..observability.profiling import phase as profiling_phase
from ..observability.tracing import Tracer, default_tracer
from ..profiler.profiler import RecordEvent
from ..resilience.faults import fault_point
from .kv_cache import PagedKVCache
from .metrics import ServingMetrics

__all__ = ["SamplingParams", "Request", "RequestState", "Engine"]


class RequestState:
    QUEUED = "queued"
    RUNNING = "running"
    FINISHED = "finished"
    REJECTED = "rejected"      # hard: can never be served (infeasible)
    RETRY_AFTER = "retry_after"  # soft: shed under load, resubmit later
    EVICTED = "evicted"        # deadline/TTL passed before completion
    EVACUATED = "evacuated"    # pulled off a failed/draining replica; the
    #                            fleet router re-enqueues it elsewhere
    FAILED = "failed"          # a row-attributable exception: THIS request
    #                            broke, its pages are freed, the engine
    #                            (and every co-batched request) lives on


@dataclasses.dataclass
class SamplingParams:
    """temperature == 0 is greedy (argmax); top_k/top_p only apply when
    sampling.  stop_token_ids end generation (the stop token is kept in
    the output, reason "stop"); max_new_tokens caps it (reason "length").
    ttl_s bounds submit→finish wall time: past it the request is evicted
    (reason "deadline") even mid-decode."""
    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    stop_token_ids: tuple = ()
    ttl_s: float = None


@dataclasses.dataclass
class Request:
    id: int
    prompt: list
    sampling: SamplingParams
    state: str = RequestState.QUEUED
    tokens: list = dataclasses.field(default_factory=list)  # prompt + output
    finish_reason: str = None
    t_submit: float = 0.0
    t_admitted: float = None
    t_first_token: float = None
    t_finished: float = None
    deadline: float = None     # absolute engine-clock time, None = no TTL
    retry_after_s: float = None  # drain-estimate hint on RETRY_AFTER
    prompt_pos: int = 0        # prompt tokens already written to pages
    _chunks_done: int = 0      # prefill chunks completed (span index)
    _rng: object = None
    _span: object = None       # root trace span (one per request)
    _phase: object = None      # current lifecycle child span

    @property
    def output(self):
        return self.tokens[len(self.prompt):]

    def _reset_for_recompute(self):
        """Preemption rewinds to the prompt — including mid-prefill
        chunk progress; the reseeded rng replays the exact same draws,
        so a preempted request's final output is identical to its
        uninterrupted one."""
        self.tokens = list(self.prompt)
        self.prompt_pos = 0
        self._chunks_done = 0
        self.state = RequestState.QUEUED
        self._rng = np.random.default_rng(self.sampling.seed)


class Engine:
    """Continuous-batching generation over a paged KV cache with a
    unified (chunked-prefill) step scheduler.

    cfg/params: the GPT model (params default to gpt_init — useful for
    benches and tests).  page_size/num_pages size the KV pool;
    max_batch_size fixes the in-flight row count (static shape).
    ``chunk_len`` bounds the prompt tokens any single row contributes
    per step — the knob that trades TTFT of the chunked prompt against
    the stall it imposes on everyone else (``prefill_len`` is accepted
    as a legacy alias; it no longer caps admissible prompt length).
    ``token_budget`` is the packed query-token width of the one
    compiled step (default chunk_len + max_batch_size - 1: one full
    chunk plus a decode token for every other row).

    Robustness knobs: ``default_ttl_s`` is the per-request deadline when
    SamplingParams doesn't set one.  ``shed_occupancy_high/low`` (pool
    fraction, 0..1) and ``shed_queue_high/low`` (queue depth) arm
    watermark load shedding; lows default to 3/4 of their high.
    ``drain_floor_s`` is the cold-start floor on the drain estimate:
    until the decode-rate EWMA has its first real sample the engine
    cannot know how fast it drains, so ``estimated_drain_s()`` (and
    the ``retry_after_s`` hint built on it) never reports below this
    floor — a freshly (re)started replica advertises "give me a
    moment" instead of a useless 0 that would invite the whole fleet's
    backlog at once.  Once a decode step has measured the real rate
    the floor no longer applies.
    ``clock`` replaces time.perf_counter (tests drive a manual clock so
    deadline behavior is deterministic, not sleep-based).  ``tracer``
    overrides the flight recorder; by default the engine records into
    the process-wide tracer, or — when a custom ``clock`` is injected —
    into a private Tracer on that clock (so manual-clock tests get
    deterministic span timestamps without touching global state).
    """

    #: assumed decode throughput (tok/s) until the first decode step has
    #: measured the real EWMA rate — only ever used for the drain
    #: estimate of a request shed before any decoding happened
    ASSUMED_DECODE_RATE = 100.0

    #: default cold-start floor (seconds) on the drain estimate while
    #: the decode-rate EWMA has no sample yet
    DRAIN_FLOOR_S = 0.5

    def __init__(self, cfg: GPTConfig, params=None, *, page_size=16,
                 num_pages=256, max_batch_size=4, chunk_len=None,
                 token_budget=None, prefill_len=None,
                 default_ttl_s=None, shed_occupancy_high=None,
                 shed_occupancy_low=None, shed_queue_high=None,
                 shed_queue_low=None, drain_floor_s=None,
                 prefix_cache=True, clock=None, tracer=None, mesh=None):
        self.cfg = cfg
        self._clock = clock or time.perf_counter
        if tracer is None:
            tracer = (default_tracer() if clock is None
                      else Tracer(clock=self._clock))
        self.tracer = tracer
        self._decode_rate_ewma = None     # tok/s, None until first decode
        self._ewma_alpha = 0.25
        self.default_ttl_s = default_ttl_s
        self.drain_floor_s = (self.DRAIN_FLOOR_S if drain_floor_s is None
                              else float(drain_floor_s))
        self.shed_occupancy_high = shed_occupancy_high
        self.shed_occupancy_low = (
            shed_occupancy_low if shed_occupancy_low is not None
            else (None if shed_occupancy_high is None
                  else 0.75 * shed_occupancy_high))
        self.shed_queue_high = shed_queue_high
        self.shed_queue_low = (
            shed_queue_low if shed_queue_low is not None
            else (None if shed_queue_high is None
                  else max(0, int(0.75 * shed_queue_high))))
        self._shedding = False
        self.params = params if params is not None else gpt_init(cfg)
        self.page_size = page_size
        self.max_batch_size = max_batch_size
        # prefill_len kept as a legacy alias for the chunk size; prompts
        # of ANY admissible length are chunked through it
        self.chunk_len = max(1, min(chunk_len or prefill_len or 64,
                                    cfg.max_seq_len))
        self.token_budget = max(
            token_budget or (self.chunk_len + max_batch_size - 1),
            max_batch_size)
        self.cache = PagedKVCache(
            num_layers=cfg.num_layers, num_heads=cfg.num_heads,
            head_dim=cfg.head_dim, num_pages=num_pages, page_size=page_size,
            max_seq_len=cfg.max_seq_len, dtype=cfg.jdtype())
        # prefix/radix reuse: admission walks the radix tree so a shared
        # system prompt is a refcount bump instead of prefill FLOPs;
        # completed prompts are inserted back.  Off = always-cold
        # admission (the bench's cold-fleet baseline).
        self.prefix_cache = bool(prefix_cache)
        self._prefix_seen = {"hits": 0, "hit_tokens": 0, "evictions": 0}
        self.metrics = ServingMetrics()
        self._queue = deque()
        self._slots = [None] * max_batch_size
        self._just_finished = []
        self._admit_seq = 0                 # admission order, for preemption
        self._next_id = 0
        # donation chains the page buffers through steps; XLA:CPU can't
        # donate and warns, so only donate on accelerators
        donate = (1, 2) if jax.default_backend() != "cpu" else ()
        cfg_, max_q = cfg, self.chunk_len

        def _step(params, k_pages, v_pages, tokens, rows, slots, qlens,
                  ctxs, tables):
            return gpt_ragged_step(cfg_, params, tokens, rows, slots,
                                   qlens, ctxs, k_pages, v_pages, tables,
                                   max_q=max_q)

        # GSPMD serving (prepare(mesh=...) analogue): params follow the
        # mesh.py GPT rule table and the KV page pool [L, P, ps, H, hd]
        # shards its HEAD axis along "mp" — each model-parallel shard
        # owns its head group's pages, so page writes are local and the
        # only cross-shard traffic is the per-layer psum GSPMD inserts
        # at the residual write plus ONE logits gather per step
        # (out_shardings pins logits replicated; pages stay sharded
        # end-to-end, never gathered).
        self.mesh = mesh
        self._page_sharding = None
        jit_kw = {"donate_argnums": donate}
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..distributed import mesh as mesh_mod

            self.params = mesh_mod.shard_params(self.params, mesh)
            page_spec = mesh_mod.resolve_spec(
                P(None, None, None, "mp"), self.cache.k_pages.shape,
                mesh)
            psh = NamedSharding(mesh, page_spec)
            self.cache.k_pages = jax.device_put(self.cache.k_pages, psh)
            self.cache.v_pages = jax.device_put(self.cache.v_pages, psh)
            self._page_sharding = psh
            rep = NamedSharding(mesh, P())
            p_sh = mesh_mod.sharding_tree(self.params, mesh)
            jit_kw.update(
                in_shardings=(p_sh, psh, psh) + (rep,) * 6,
                out_shardings=(rep, psh, psh))
        # watchdog-wrapped: the ONE statically-shaped program — prompt
        # chunks and decode rows share it — must compile exactly once;
        # any recompile here is a serving bug the watchdog flags with
        # the offending shape diff
        self._step_fn = watch(jax.jit(_step, **jit_kw),
                              name="serving::unified_step")

    # ------------------------------------------------------------- submit
    def add_request(self, prompt, sampling: SamplingParams = None, *,
                    trace_context=None):
        """Queue a prompt (list of token ids).  Returns the Request;
        state is REJECTED immediately when it can never be served, and
        a shed request carries ``retry_after_s`` (the live drain
        estimate) next to its RETRY_AFTER state.  ``trace_context`` (a
        :class:`~..observability.tracing.TraceContext` or its dict form)
        continues a caller's trace — the router hands its dispatch
        span's context over, so the request's whole engine lifecycle
        records under the fleet trace instead of a fresh local one."""
        # fault site: a stall here is an admission wedge (the RPC thread
        # of a real deployment hanging in submit); an io_error is the
        # transport refusing the request.  The fleet router detects both.
        fault_point("serving.admit")
        sampling = sampling or SamplingParams()
        req = Request(id=self._next_id, prompt=list(prompt),
                      sampling=sampling, t_submit=self._clock())
        self._next_id += 1
        req.tokens = list(req.prompt)
        req._rng = np.random.default_rng(sampling.seed)
        ttl = sampling.ttl_s if sampling.ttl_s is not None \
            else self.default_ttl_s
        if ttl is not None:
            req.deadline = req.t_submit + float(ttl)
        self.metrics.requests_submitted.inc()
        req._span = self.tracer.start_trace(
            f"request#{req.id}", start_s=req.t_submit,
            attributes={"request_id": req.id,
                        "prompt_len": len(req.prompt),
                        "max_new_tokens": sampling.max_new_tokens},
            context=trace_context)

        # chunked prefill admits any prompt the model itself can hold —
        # there is deliberately NO prompt-length gate below max_seq_len
        total = len(req.prompt) + sampling.max_new_tokens
        reason = None
        if not req.prompt:
            reason = "empty prompt"
        elif total > self.cfg.max_seq_len:
            reason = (f"prompt + max_new_tokens = {total} exceeds "
                      f"max_seq_len {self.cfg.max_seq_len}")
        elif self.cache.pages_for(total) > self.cache.num_pages:
            reason = (f"{total} tokens need "
                      f"{self.cache.pages_for(total)} pages; the pool has "
                      f"{self.cache.num_pages} — page pool exhausted")
        if reason is not None:
            req.state = RequestState.REJECTED
            req.finish_reason = reason
            self.metrics.requests_rejected.inc()
            self._end_trace(req)
            return req
        if self._update_shedding():
            # soft rejection: the request IS feasible, the engine is
            # just saturated — back off ~retry_after_s and resubmit
            req.state = RequestState.RETRY_AFTER
            req.retry_after_s = self._retry_after()
            req.finish_reason = (
                f"load shed: occupancy {self.cache.occupancy():.2f}, "
                f"queue depth {len(self._queue)} — retry in "
                f"{req.retry_after_s:.3f}s")
            self.metrics.requests_shed.inc()
            self.metrics.estimated_drain_s.set(req.retry_after_s)
            self._end_trace(req)
            return req
        self._queue.append(req)
        req._phase = self.tracer.start_span("queued", req._span,
                                            start_s=req.t_submit)
        self.metrics.queue_depth.set(len(self._queue))
        self._update_shedding()
        return req

    # ----------------------------------------------------- flight recorder
    def _end_phase(self, req, end_s=None, **attrs):
        if req._phase is not None:
            req._phase.set_attributes(attrs)
            req._phase.end(end_s)
            req._phase = None

    def _end_trace(self, req, end_s=None):
        """Terminal span bookkeeping: close the open phase (if any) and
        the request root, stamping the final state / reason / output
        size and the pool occupancy at that instant."""
        if req._span is None:
            return
        self._end_phase(req, end_s)
        req._span.set_attributes({
            "state": req.state, "finish_reason": req.finish_reason,
            "tokens_out": len(req.output),
            "page_occupancy": round(self.cache.occupancy(), 4)})
        if req.retry_after_s is not None:
            req._span.set_attribute("retry_after_s", req.retry_after_s)
        req._span.end(end_s)

    # ------------------------------------------------------ drain estimate
    def pending_decode_tokens(self):
        """Decode tokens still owed to queued + running requests (the
        backlog the drain estimate is over)."""
        owed = sum(r.sampling.max_new_tokens - len(r.output)
                   for r in self._queue)
        owed += sum(max(0, r.sampling.max_new_tokens - len(r.output))
                    for r in self._running())
        return owed

    def decode_rate(self):
        """EWMA decode throughput in tok/s (None before the first
        decode step)."""
        return self._decode_rate_ewma

    def estimated_drain_s(self):
        """Seconds to decode the current backlog at the measured rate —
        the machine-readable retry-after hint (ROADMAP: "estimated
        drain time from queue depth × decode rate").  Before the first
        decode measurement the rate falls back to ASSUMED_DECODE_RATE
        and the estimate never reports below ``drain_floor_s``: a
        cold/freshly-restarted engine has no evidence it drains fast,
        and advertising 0 would invite a router to dump the whole
        fleet's backlog on it at once."""
        tokens = self.pending_decode_tokens()
        if self._decode_rate_ewma is None:
            assumed = tokens / self.ASSUMED_DECODE_RATE
            return max(assumed, self.drain_floor_s)
        if tokens <= 0:
            return 0.0
        return tokens / max(self._decode_rate_ewma, 1e-9)

    def _retry_after(self):
        """Finite, strictly positive back-off for a shed request: at
        least one decode-step's worth even when the backlog estimate
        rounds to zero."""
        rate = self._decode_rate_ewma or self.ASSUMED_DECODE_RATE
        return max(self.estimated_drain_s(), 1.0 / max(rate, 1e-9))

    # ----------------------------------------------------- load shedding
    def _update_shedding(self):
        """High/low-watermark hysteresis over page-pool occupancy and
        queue depth; mirrors into the health gauge.  Returns the current
        shedding state."""
        occ, q = self.cache.occupancy(), len(self._queue)
        high = ((self.shed_occupancy_high is not None
                 and occ >= self.shed_occupancy_high)
                or (self.shed_queue_high is not None
                    and q >= self.shed_queue_high))
        low = ((self.shed_occupancy_low is None
                or occ <= self.shed_occupancy_low)
               and (self.shed_queue_low is None
                    or q <= self.shed_queue_low))
        if not self._shedding and high:
            self._shedding = True
        elif self._shedding and low and not high:
            self._shedding = False
        self.metrics.engine_healthy.set(0 if self._shedding else 1)
        return self._shedding

    # -------------------------------------------------- deadline eviction
    def _evict(self, req, now):
        """Terminal deadline eviction: pages freed, partial output kept."""
        if req in self._slots:
            self.cache.free(req.id)
            self._slots[self._slots.index(req)] = None
        req.state = RequestState.EVICTED
        req.finish_reason = "deadline"
        req.t_finished = now
        self.metrics.deadline_evictions.inc()
        self._end_trace(req, end_s=now)
        self._just_finished.append(req)

    def _fail(self, req, exc):
        """Per-row failure isolation: an exception raised while packing
        or committing ONE row is that request's fault, not the
        engine's — the row is retired terminal FAILED with its pages
        freed and its trace closed on the error, and every co-batched
        request keeps running.  Only exceptions that cannot be pinned
        to a row (the jitted step itself, the top-of-step fault site)
        escalate to the caller — the fleet router's replica-failure
        path."""
        if req in self._slots:
            self.cache.free(req.id)
            self._slots[self._slots.index(req)] = None
        req.state = RequestState.FAILED
        req.finish_reason = f"row failure: {exc!r}"
        req.t_finished = self._clock()
        self.metrics.requests_failed.inc()
        if req._span is not None:
            req._span.set_attribute("error", repr(exc))
        self._end_trace(req, end_s=req.t_finished)
        self._just_finished.append(req)

    def _evict_expired(self):
        """Evict every request (running OR still queued) whose deadline
        has passed — run at step start so freed pages are available to
        this step's admissions."""
        now = self._clock()
        for req in self._running():
            if req.deadline is not None and now > req.deadline:
                self._evict(req, now)
        expired = [r for r in self._queue
                   if r.deadline is not None and now > r.deadline]
        for req in expired:
            self._queue.remove(req)
            self._evict(req, now)

    # -------------------------------------------------------------- admit
    def _free_slot(self):
        for i, r in enumerate(self._slots):
            if r is None:
                return i
        return None

    def _try_admit(self):
        with profiling_phase("admission"):
            self._try_admit_inner()

    def _try_admit_inner(self):
        while self._queue:
            slot = self._free_slot()
            if slot is None:
                return
            req = self._queue[0]
            # chunk-granularity admission: pages for the FIRST chunk
            # only — later chunks extend the table step by step.  With
            # the prefix cache on, the radix walk happens here: the
            # longest cached prefix of the prompt is mapped in
            # read-only (refcount bump) and chunked prefill starts at
            # the first uncached token
            if self.prefix_cache:
                matched = self.cache.allocate_prefixed(
                    req.id, req.prompt, self.chunk_len)
                if matched is None:
                    return                   # FIFO: no queue-jumping
            else:
                matched = 0
                first = min(self.chunk_len, len(req.prompt))
                if not self.cache.allocate(req.id, first):
                    return                   # FIFO: no queue-jumping
            req.prompt_pos = matched
            self._queue.popleft()
            now = self._clock()
            req.state = RequestState.RUNNING
            req.t_admitted = now
            req._admit_seq = self._admit_seq
            self._admit_seq += 1
            self._slots[slot] = req
            self.metrics.requests_admitted.inc()
            self.metrics.queue_wait.observe(now - req.t_submit)
            self._end_phase(req, end_s=now)      # queued → admitted
            if req._span is not None:
                req._span.set_attributes({
                    "batch_slot": slot,
                    "prefix_hit_tokens": matched,
                    "occupancy_at_admit":
                        round(self.cache.occupancy(), 4)})

    # -------------------------------------------------------- unified step
    def _running(self):
        return [r for r in self._slots if r is not None]

    def _preempt(self, req):
        """Free req's pages and push it back to the queue head for
        recompute (memory pressure, never an error)."""
        self.cache.free(req.id)
        self._slots[self._slots.index(req)] = None
        req._reset_for_recompute()
        self._queue.appendleft(req)
        self.metrics.requests_preempted.inc()
        # lifecycle rewinds with the tokens: close the open phase and
        # re-enter "queued" so the trace shows the preemption gap
        self._end_phase(req, preempted=True)
        if req._span is not None:
            req._span.attributes["preemptions"] = \
                req._span.attributes.get("preemptions", 0) + 1
            req._phase = self.tracer.start_span("queued", req._span)

    def _plan_rows(self):
        """{batch slot: query tokens this step} under token_budget.
        Decode rows always get their one token; mid-prefill rows then
        split the remaining budget fairly (ceil-share, admission order)
        so a short prompt admitted behind a long one still makes
        progress toward its TTFT instead of starving."""
        plan = {}
        budget = self.token_budget
        chunkers = []
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            if req.prompt_pos >= len(req.prompt):
                plan[i] = 1
                budget -= 1
            else:
                chunkers.append(i)
        chunkers.sort(key=lambda i: self._slots[i]._admit_seq)
        for n, i in enumerate(chunkers):
            if budget <= 0:
                break
            req = self._slots[i]
            fair = -(-budget // (len(chunkers) - n))          # ceil share
            q = min(self.chunk_len, len(req.prompt) - req.prompt_pos,
                    fair)
            if q > 0:
                plan[i] = q
                budget -= q
        return plan

    def _ensure_capacity(self):
        """Pages for every planned row's post-step context — the chunk a
        mid-prefill row is about to write, or the token decode is about
        to append; preempt youngest-first (mid-prefill rows included)
        when the pool runs dry.  Returns the final, feasible plan."""
        while True:
            plan = self._plan_rows()
            stable = True
            for i in sorted(plan, key=lambda i: self._slots[i]._admit_seq
                            if self._slots[i] is not None else 0):
                req = self._slots[i]
                if req is None:
                    continue                 # preempted earlier this pass
                if req.prompt_pos < len(req.prompt):
                    target = req.prompt_pos + plan[i]
                else:
                    target = len(req.tokens)
                while req in self._slots and \
                        not self.cache.extend(req.id, target):
                    victim = max(self._running(),
                                 key=lambda r: r._admit_seq)
                    self._preempt(victim)
                    stable = False
                    if victim is req:
                        break
            if stable:
                return plan

    def _unified_step_once(self, plan):
        """Run the one jitted program over the planned ragged batch and
        fold the results back into each request's lifecycle."""
        if not plan:
            return
        B, T = self.max_batch_size, self.token_budget
        tokens = np.zeros((T,), np.int32)
        rows = np.full((T,), B, np.int32)        # B marks padding slots
        slots = np.zeros((T,), np.int32)
        qlens = np.zeros((B,), np.int32)
        ctxs = np.zeros((B,), np.int32)
        tables = np.zeros((B, self.cache.max_pages_per_seq), np.int32)
        sched = []                               # (slot, req, q, new ctx)
        off = 0
        for i in range(B):                       # packing is row-major
            req = self._slots[i]
            q = plan.get(i, 0)
            if req is None or q <= 0:
                continue
            try:
                if req.prompt_pos < len(req.prompt):
                    chunk = req.prompt[req.prompt_pos:req.prompt_pos + q]
                    ctx = req.prompt_pos + q
                else:
                    chunk = req.tokens[-1:]
                    ctx = len(req.tokens)
                table = self.cache.page_table(req.id)
            except Exception as e:
                # row-attributable plan failure: THIS row dies, the
                # batch (arrays untouched for it) runs without it
                self._fail(req, e)
                continue
            tokens[off:off + q] = chunk
            rows[off:off + q] = i
            slots[off:off + q] = np.arange(q)
            qlens[i], ctxs[i] = q, ctx
            tables[i] = table
            sched.append((i, req, q, ctx))
            off += q
        if not sched:
            return
        # phase attribution for the sampling profiler: a step with any
        # mid-prefill row is a prefill chunk, else pure decode
        step_phase = "prefill_chunk" if any(
            req.prompt_pos < len(req.prompt)
            for _, req, _, _ in sched) else "decode"
        t0 = self._clock()
        with profiling_phase(step_phase), \
                RecordEvent("serving::unified_step"):
            logits, k, v = self._step_fn(
                self.params, self.cache.k_pages, self.cache.v_pages,
                jnp.asarray(tokens), jnp.asarray(rows),
                jnp.asarray(slots), jnp.asarray(qlens),
                jnp.asarray(ctxs), jnp.asarray(tables))
            logits = np.asarray(logits)
        self.cache.k_pages, self.cache.v_pages = k, v
        t1 = self._clock()
        dt = t1 - t0
        occ = round(self.cache.occupancy(), 4)
        n_rows = len(sched)
        sampled = 0
        for i, req, q, ctx in sched:
            # per-row commit isolation: anything this row's sampling /
            # bookkeeping raises is ITS failure — the row retires
            # FAILED, every other row in the batch commits normally
            try:
                mid_prefill = req.prompt_pos < len(req.prompt)
                if mid_prefill:
                    req.prompt_pos = ctx
                    self.metrics.prefill_tokens.inc(q)
                    self.metrics.prefill_chunks.inc()
                    if req._span is not None:
                        self.tracer.start_span(
                            f"chunk[{req._chunks_done}]", req._span,
                            start_s=t0,
                            attributes={"tokens": q, "prefilled": ctx,
                                        "batch_slot": i,
                                        "batch_size": n_rows,
                                        "page_occupancy": occ}).end(t1)
                    req._chunks_done += 1
                    if ctx < len(req.prompt):
                        continue             # more chunks to go
                    # prompt complete: its FULL pages are now reusable
                    # K/V — register them in the radix tree so the next
                    # request sharing this prefix skips the prefill
                    # FLOPs (the partial final page keeps taking decode
                    # writes and is never shared)
                    if self.prefix_cache:
                        self.cache.insert_prefix(req.id, req.prompt)
                    # the chunk that completed the prompt falls through
                    # and samples the request's first token — TTFT
                tok = self._sample_token(logits[i], req)
                req.tokens.append(tok)
                sampled += 1
                self.metrics.tokens_generated.inc()
                if req.t_first_token is None:
                    # time-to-first-SAMPLED-token: stamped when the last
                    # prompt chunk completes, not when prefill starts
                    req.t_first_token = t1
                    # exemplar: this observation's trace — the /metrics
                    # p99 bucket then names a trace the ring retains
                    self.metrics.ttft.observe(
                        t1 - req.t_submit,
                        exemplar=getattr(req._span, "trace_id", None))
                if not mid_prefill:
                    self.metrics.decode_token.observe(dt / n_rows)
                    if req._span is not None:
                        # retroactive span over the batched step this
                        # request rode in — one decode[i] per token
                        self.tracer.start_span(
                            f"decode[{len(req.output) - 1}]", req._span,
                            start_s=t0,
                            attributes={"batch_slot": i,
                                        "batch_size": n_rows,
                                        "page_occupancy": occ}).end(t1)
                self._maybe_finish(req)
            except Exception as e:
                self._fail(req, e)
        if dt > 0 and sampled:
            # EWMA decode throughput feeds the drain/retry-after hint
            inst = sampled / dt
            a = self._ewma_alpha
            self._decode_rate_ewma = (
                inst if self._decode_rate_ewma is None
                else a * inst + (1 - a) * self._decode_rate_ewma)

    # ------------------------------------------------------------ sampling
    def _sample_token(self, logits_row, req):
        sp = req.sampling
        logits = np.asarray(logits_row, np.float64)
        if sp.temperature <= 0.0:
            return int(np.argmax(logits))
        logits = logits / sp.temperature
        if sp.top_k and sp.top_k < logits.size:
            kth = np.partition(logits, -sp.top_k)[-sp.top_k]
            logits = np.where(logits < kth, -np.inf, logits)
        probs = np.exp(logits - np.max(logits))
        probs = probs / probs.sum()
        if sp.top_p < 1.0:
            order = np.argsort(-probs)
            cum = np.cumsum(probs[order])
            # smallest prefix reaching top_p (always keep the first)
            cut = int(np.searchsorted(cum, sp.top_p)) + 1
            mask = np.zeros_like(probs)
            mask[order[:cut]] = 1.0
            probs = probs * mask
            probs = probs / probs.sum()
        return int(req._rng.choice(probs.size, p=probs))

    # ------------------------------------------------------------- finish
    def _maybe_finish(self, req):
        sp = req.sampling
        reason = None
        if req.tokens[-1] in sp.stop_token_ids:
            reason = "stop"
        elif len(req.output) >= sp.max_new_tokens:
            reason = "length"
        elif len(req.tokens) >= self.cfg.max_seq_len:
            reason = "length"
        if reason is None:
            return
        req.state = RequestState.FINISHED
        req.finish_reason = reason
        req.t_finished = self._clock()
        self.cache.free(req.id)
        if req in self._slots:
            self._slots[self._slots.index(req)] = None
        self.metrics.requests_finished.inc()
        self._end_trace(req, end_s=req.t_finished)
        self._just_finished.append(req)

    # --------------------------------------------------------------- drive
    def has_work(self):
        return bool(self._queue) or any(r is not None for r in self._slots)

    def step(self):
        """One scheduler iteration: evict past-deadline requests, admit,
        run the unified ragged step (prompt chunks + decode rows in one
        batch), update gauges.  Returns requests that finished (or were
        evicted) this step."""
        # fault site: an io_error here is the whole step failing the way
        # a crashed replica's RPC would — before any request state
        # mutates, so a router can re-dispatch losslessly.  tree=
        # exposes the live KV page pool to the bitflip kind (silent
        # corruption of serving state) and tokens= exposes every
        # in-flight request's stream to poison_request (the
        # query-of-death: a seed-chosen pattern that kills whichever
        # replica it is aboard — deliberately NOT row-attributable)
        kv = {"k_pages": self.cache.k_pages, "v_pages": self.cache.v_pages}
        fault_point("serving.step", tree=kv,
                    tokens=[r.tokens for r in self._running()]
                    + [r.tokens for r in self._queue])
        self.cache.k_pages, self.cache.v_pages = kv["k_pages"], \
            kv["v_pages"]
        self._evict_expired()
        self._try_admit()
        self._unified_step_once(self._ensure_capacity())
        self._update_shedding()
        self.metrics.page_occupancy.set(self.cache.occupancy())
        self.metrics.queue_depth.set(len(self._queue))
        self.metrics.estimated_drain_s.set(self.estimated_drain_s())
        self._sync_prefix_metrics()
        done, self._just_finished = self._just_finished, []
        return done

    def _sync_prefix_metrics(self):
        """Fold the cache's monotonic prefix counters into the
        serving_prefix_* registry series (delta sync: the cache doesn't
        know about metrics, the registry wants monotonic counters)."""
        stats = self.cache.prefix_stats()
        m = self.metrics
        for key, counter in (("hits", m.prefix_cache_hits),
                             ("hit_tokens", m.prefix_hit_tokens),
                             ("evictions", m.prefix_cache_evictions)):
            delta = stats[key] - self._prefix_seen[key]
            if delta:
                counter.inc(delta)
                self._prefix_seen[key] = stats[key]
        m.prefix_cache_pages.set(stats["cached_pages"])

    def prefix_summary(self, max_entries=32):
        """Bounded radix-tree summary for cache-aware routing — the
        per-replica payload the fleet gossips (root hashes + hit
        stats).  See ``PagedKVCache.prefix_summary``."""
        out = self.cache.prefix_summary(max_entries=max_entries)
        out["enabled"] = self.prefix_cache
        return out

    def evacuate(self):
        """Pull EVERY in-flight request off this engine — running
        (mid-prefill or decoding) and queued — free their pages, and
        return them with their sampled tokens intact, in admission
        order (running first, then the queue).

        The fleet router's failover/drain primitive: the caller
        re-enqueues each request elsewhere as an ordinary admission
        (prompt + already-sampled tokens), so this engine's paged KV
        state is never trusted again.  Each request leaves in state
        ``EVACUATED`` with its trace closed; partial output is
        preserved — nothing is re-sampled here, nothing is lost."""
        now = self._clock()
        running = sorted(self._running(), key=lambda r: r._admit_seq)
        for req in running:
            self.cache.free(req.id)
            self._slots[self._slots.index(req)] = None
        queued = list(self._queue)
        self._queue.clear()
        out = running + queued
        for req in out:
            req.state = RequestState.EVACUATED
            req.finish_reason = "evacuated"
            self._end_trace(req, end_s=now)
        self.metrics.queue_depth.set(0)
        self.metrics.page_occupancy.set(self.cache.occupancy())
        return out

    def health(self):
        """Live scheduler health — the ``/healthz`` payload: shedding
        flag, queue depth, in-flight batch, pool occupancy, and the
        drain estimate a cooperating front-end should back off by."""
        return {"healthy": not self._shedding,
                "queue_depth": len(self._queue),
                "running": len(self._running()),
                "page_occupancy": self.cache.occupancy(),
                "estimated_drain_s": self.estimated_drain_s(),
                "decode_rate_tok_s": self._decode_rate_ewma,
                "prefix_cache": {"enabled": self.prefix_cache,
                                 **self.cache.prefix_stats()}}

    def generate(self, prompts, sampling=None):
        """Batch convenience: submit all prompts, drive the scheduler to
        completion, return each request's generated tokens (submit
        order; rejected requests yield [])."""
        if sampling is None or isinstance(sampling, SamplingParams):
            sampling = [sampling] * len(prompts)
        reqs = [self.add_request(p, s) for p, s in zip(prompts, sampling)]
        while self.has_work():
            self.step()
        return [r.output for r in reqs]

    def warmup(self, prompt_len=4, max_new_tokens=2):
        """Pre-rotation warmup: run one tiny request end-to-end so the
        unified step compiles now, not on the first real request —
        then RESET the decode-rate EWMA.  The warmup steps time jit
        compilation, not steady-state decode, so their rate samples
        are garbage; discarding them keeps ``drain_floor_s``
        advertised (``estimated_drain_s`` stays on the cold-start
        floor, ``health()['decode_rate_tok_s']`` stays None) until the
        first *real* decode step measures the true rate.  The
        autoscaler reads that None as "warming, not capacity yet"."""
        n = max(1, min(int(prompt_len), self.cfg.max_seq_len // 2))
        prompt = list(range(1, n + 1))
        self.generate([prompt],
                      SamplingParams(max_new_tokens=int(max_new_tokens)))
        self._decode_rate_ewma = None
        return self
