"""Block-paged KV cache — the physical memory manager behind the serving
engine.

vLLM's PagedAttention memory model on TPU (arXiv:2604.15464): K/V live in
fixed-size pages drawn from one shared pool, a per-sequence page table
maps logical token positions to physical pages, and sequences of wildly
different lengths share the pool with at most page_size-1 slots of waste
each.  The pool is a single stacked array [L, P, page_size, H, hd]
(layer-major so the model's lax.scan over layers consumes it as per-layer
xs/ys), bf16 by default.

Allocation is chunk-granular: the engine's chunked-prefill scheduler
``allocate``s only a prompt's first chunk at admission and ``extend``s
the table as later chunks (and decode tokens) land, so a long prompt
holds exactly the pages its written tokens need — never a whole-prompt
reservation sitting idle while other requests starve.

Host-side bookkeeping (free list, page tables) is plain Python — it sits
on the scheduler path, not the device path; the device only ever sees the
dense page arrays plus int32 tables the engine builds per step.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

__all__ = ["PagedKVCache"]


class PagedKVCache:
    """Page pool + per-sequence page tables with alloc/free/defrag.

    The arrays (`k_pages`/`v_pages`) are functional: jitted model steps
    take them as inputs and return updated copies; the engine assigns the
    results back.  Bookkeeping methods never touch the arrays except
    ``defrag`` (a gather) and ``reset`` (a fill).
    """

    def __init__(self, *, num_layers, num_heads, head_dim, num_pages,
                 page_size, max_seq_len, dtype=jnp.bfloat16):
        if num_pages <= 0 or page_size <= 0:
            raise ValueError("num_pages and page_size must be positive")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_pages_per_seq = math.ceil(max_seq_len / page_size)
        shape = (num_layers, num_pages, page_size, num_heads, head_dim)
        self.k_pages = jnp.zeros(shape, dtype)
        self.v_pages = jnp.zeros(shape, dtype)
        # LIFO free list: recently-freed (still-warm) pages are reused first
        self._free = list(range(num_pages - 1, -1, -1))
        self._tables = {}          # seq_id -> [physical page ids]

    # ------------------------------------------------------------ queries
    @property
    def num_free_pages(self):
        return len(self._free)

    @property
    def num_used_pages(self):
        return self.num_pages - len(self._free)

    def occupancy(self):
        """Fraction of the pool in use, 0..1."""
        return self.num_used_pages / self.num_pages

    def pages_for(self, num_tokens):
        return math.ceil(num_tokens / self.page_size)

    def can_allocate(self, num_tokens):
        return self.pages_for(num_tokens) <= len(self._free)

    def seq_ids(self):
        return list(self._tables)

    # ------------------------------------------------------- alloc / free
    def allocate(self, seq_id, num_tokens):
        """Reserve pages for a new sequence of num_tokens.  Returns True
        on success; False (allocating nothing) when the pool can't cover
        the request — the engine's admission gate."""
        if seq_id in self._tables:
            raise ValueError(f"seq {seq_id!r} already allocated")
        need = self.pages_for(num_tokens)
        if need > self.max_pages_per_seq:
            raise ValueError(
                f"seq {seq_id!r}: {num_tokens} tokens need {need} pages > "
                f"max_pages_per_seq {self.max_pages_per_seq}")
        if need > len(self._free):
            return False
        self._tables[seq_id] = [self._free.pop() for _ in range(need)]
        return True

    def extend(self, seq_id, num_tokens):
        """Grow seq_id's table to cover num_tokens total.  True on
        success; False (table unchanged) when the pool is exhausted —
        the engine then preempts."""
        table = self._tables[seq_id]
        need = self.pages_for(num_tokens) - len(table)
        if need <= 0:
            return True
        if len(table) + need > self.max_pages_per_seq:
            raise ValueError(
                f"seq {seq_id!r}: extend to {num_tokens} tokens exceeds "
                f"max_pages_per_seq {self.max_pages_per_seq}")
        if need > len(self._free):
            return False
        table.extend(self._free.pop() for _ in range(need))
        return True

    def free(self, seq_id):
        """Return seq_id's pages to the pool (stale contents are fine:
        pages are fully overwritten before they are ever read again)."""
        for p in self._tables.pop(seq_id):
            self._free.append(p)

    def reset(self):
        """Free everything and zero the pool."""
        self._tables.clear()
        self._free = list(range(self.num_pages - 1, -1, -1))
        self.k_pages = jnp.zeros_like(self.k_pages)
        self.v_pages = jnp.zeros_like(self.v_pages)

    # ---------------------------------------------------------- page table
    def page_table(self, seq_id, width=None):
        """seq_id's table padded with 0 to ``width`` (default
        max_pages_per_seq).  Pad entries are never read: attention masks
        by seq_len and writes are index-routed out of bounds first."""
        width = width or self.max_pages_per_seq
        table = self._tables[seq_id]
        return table + [0] * (width - len(table))

    # -------------------------------------------------------------- defrag
    def defrag(self):
        """Compact live pages into the low-index prefix of the pool.

        Long-running engines interleave alloc/free until the free list is
        scattered; compaction restores locality (sequential page ids DMA
        as one contiguous stream on TPU) and makes the pool's live set
        checkpointable as a prefix slice.  One gather per pool array;
        page tables are remapped in place.  Returns pages moved."""
        order = []                   # new physical slot -> old page id
        remap = {}                   # old page id -> new page id
        for seq_id in self._tables:
            for old in self._tables[seq_id]:
                remap[old] = len(order)
                order.append(old)
        n_used = len(order)
        moved = sum(1 for old, new in remap.items() if old != new)
        if moved == 0:
            return 0
        order += [p for p in range(self.num_pages) if p not in remap]
        idx = jnp.asarray(order, jnp.int32)
        self.k_pages = jnp.take(self.k_pages, idx, axis=1)
        self.v_pages = jnp.take(self.v_pages, idx, axis=1)
        self._tables = {sid: [remap[p] for p in t]
                        for sid, t in self._tables.items()}
        self._free = list(range(self.num_pages - 1, n_used - 1, -1))
        return moved
