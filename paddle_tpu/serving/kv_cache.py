"""Block-paged KV cache — the physical memory manager behind the serving
engine, with refcounted pages and a radix prefix cache.

vLLM's PagedAttention memory model on TPU (arXiv:2604.15464): K/V live in
fixed-size pages drawn from one shared pool, a per-sequence page table
maps logical token positions to physical pages, and sequences of wildly
different lengths share the pool with at most page_size-1 slots of waste
each.  The pool is a single stacked array [L, P, page_size, H, hd]
(layer-major so the model's lax.scan over layers consumes it as per-layer
xs/ys), bf16 by default.

Allocation is chunk-granular: the engine's chunked-prefill scheduler
``allocate``s only a prompt's first chunk at admission and ``extend``s
the table as later chunks (and decode tokens) land, so a long prompt
holds exactly the pages its written tokens need — never a whole-prompt
reservation sitting idle while other requests starve.

Prefix reuse (the millions-of-users economics): chat traffic shares a
system prompt, and re-prefilling it per request burns FLOPs on K/V the
pool already holds.  Every page therefore carries a **refcount**, and a
**radix tree keyed on page-aligned token-ID prefixes** (one edge = one
FULL page of prompt tokens) indexes pages whose contents are a pure
function of their token prefix.  ``allocate_prefixed`` walks the tree
for the longest cached prefix of a new prompt, maps those pages into
the new sequence's table read-only (a refcount bump instead of prefill
FLOPs), and allocates fresh pages only from the first uncached token.
When the *whole* prompt is cached the final page is **copied on write**
(the one page the new sequence must write its last prompt token into)
so shared pages are never mutated.  Only full prompt pages ever enter
the tree: a partial final page keeps receiving decode writes and
mid-decode pages are owned by exactly one sequence, never shared.

Freeing decrements; a page returns to the free list only at refcount
zero.  Cached pages nobody references (tree-only, refcount 1) are
*evictable*: ``num_free_pages``/``occupancy()`` count them as free, so
a warm cache never trips the engine's occupancy watermark (no
RETRY_AFTER storm from cache residue), and allocation under pressure
transparently evicts least-recently-used zero-ref leaves before
failing.

Host-side bookkeeping (free list, page tables, radix tree) is plain
Python — it sits on the scheduler path, not the device path; the device
only ever sees the dense page arrays plus int32 tables the engine
builds per step.  Shared pages are read through the existing page-table
indirection — the ragged kernel needs no change.  The tree, refcount
map and prefix stats are read by telemetry scrape threads while the
scheduler mutates them, so they are lock-guarded (and annotated for the
lock-discipline pass).
"""
from __future__ import annotations

import hashlib
import heapq
import math
import threading

import jax.numpy as jnp

__all__ = ["PagedKVCache", "prefix_hashes"]

#: chain hash of the empty prefix (the radix root)
_ROOT_HASH = "radix-root"


def _chunk_hash(parent_hash, key):
    """Chain hash of one page-aligned token chunk appended to a prefix.

    Stable across processes (hashlib, not ``hash()``) — it is the wire
    identity of a cached prefix in the fleet gossip protocol: a router
    hashing a prompt's page chunks client-side can test membership
    against a replica's published radix summary without shipping token
    ids."""
    h = hashlib.blake2b(digest_size=8)
    h.update(parent_hash.encode("ascii"))
    h.update(",".join(str(int(t)) for t in key).encode("ascii"))
    return h.hexdigest()


def prefix_hashes(token_ids, page_size, max_pages=64):
    """Chain hashes of the page-aligned prefixes of ``token_ids``.

    ``prefix_hashes(t, ps)[i]`` identifies the prefix ``t[:(i+1)*ps]``
    and equals the ``chain_hash`` of the radix node any
    :class:`PagedKVCache` holds for that exact prefix — the client side
    of cache-aware routing: the deepest hash present in a replica's
    prefix summary is that replica's expected hit length."""
    out, h = [], _ROOT_HASH
    for i in range(min(len(token_ids) // page_size, max_pages)):
        key = token_ids[i * page_size:(i + 1) * page_size]
        h = _chunk_hash(h, key)
        out.append(h)
    return out


class _PrefixNode:
    """One radix-tree edge: one FULL page of prompt tokens.

    ``key`` is the page's token tuple, ``page`` the physical page id
    whose K/V encodes exactly the root→here token prefix,
    ``chain_hash`` the gossip identity of that prefix, ``last_used`` a
    logical LRU tick (clock-free: deterministic under injected engine
    clocks)."""

    __slots__ = ("key", "page", "parent", "children", "chain_hash",
                 "last_used")

    def __init__(self, key, page, parent, chain_hash, last_used):
        self.key = key
        self.page = page
        self.parent = parent
        self.children = {}
        self.chain_hash = chain_hash
        self.last_used = last_used


class PagedKVCache:
    """Page pool + per-sequence page tables with alloc/free/defrag,
    per-page refcounts and a radix prefix cache.

    The arrays (`k_pages`/`v_pages`) are functional: jitted model steps
    take them as inputs and return updated copies; the engine assigns the
    results back.  Bookkeeping methods never touch the arrays except
    ``defrag`` (a gather), the copy-on-write path of
    ``allocate_prefixed`` (one page copy) and ``reset`` (a fill).
    """

    def __init__(self, *, num_layers, num_heads, head_dim, num_pages,
                 page_size, max_seq_len, dtype=jnp.bfloat16):
        if num_pages <= 0 or page_size <= 0:
            raise ValueError("num_pages and page_size must be positive")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_pages_per_seq = math.ceil(max_seq_len / page_size)
        shape = (num_layers, num_pages, page_size, num_heads, head_dim)
        self.k_pages = jnp.zeros(shape, dtype)
        self.v_pages = jnp.zeros(shape, dtype)
        # LIFO free list: recently-freed (still-warm) pages are reused first
        self._free = list(range(num_pages - 1, -1, -1))
        self._tables = {}          # seq_id -> [physical page ids]
        # scheduler thread vs telemetry scrapes (prefix_summary via
        # /fleet) race on the shared prefix structures — one re-entrant
        # lock serializes them (public methods lock, _locked helpers
        # assert the caller holds it)
        self._lock = threading.RLock()
        self._ref = {}             # page -> refcount  # guarded-by: self._lock
        self._radix = _PrefixNode((), None, None, _ROOT_HASH, 0)  # guarded-by: self._lock
        self._tree_pages = {}      # page -> its radix node  # guarded-by: self._lock
        # evictable pages (tree-held, refcount 1) are counted
        # incrementally — num_free_pages/occupancy sit on every
        # admission check and must not walk the tree
        self._evictable = 0        # guarded-by: self._lock
        # lazy min-heap of (last_used, seq, node) eviction candidates;
        # stale entries (touched/bumped/detached nodes) are skipped at
        # pop time, so eviction is O(log heap) not O(tree)
        self._evict_heap = []      # guarded-by: self._lock
        self._heap_seq = 0         # guarded-by: self._lock
        # monotonic counters for the serving_prefix_* metrics (the
        # engine syncs deltas each step)
        self._prefix_stats = {"hits": 0, "hit_tokens": 0,
                              "evictions": 0,
                              "inserted_pages": 0}  # guarded-by: self._lock
        self._tick = 0             # logical LRU clock

    # ------------------------------------------------------------ queries
    @property
    def num_free_pages(self):
        """Allocatable pages: the free list PLUS cached prefix pages no
        sequence references (refcount 1, tree-only) — those are evicted
        on demand, so a warm cache never looks like memory pressure."""
        with self._lock:
            return len(self._free) + self._evictable_locked()

    @property
    def num_used_pages(self):
        return self.num_pages - self.num_free_pages

    def occupancy(self):
        """Fraction of the pool in *hard* use (pages some sequence
        references), 0..1.  Evictable cached pages do not count — the
        watermark shedding reading this must not RETRY_AFTER traffic a
        one-page eviction would admit."""
        return self.num_used_pages / self.num_pages

    def pages_for(self, num_tokens):
        return math.ceil(num_tokens / self.page_size)

    def can_allocate(self, num_tokens):
        return self.pages_for(num_tokens) <= self.num_free_pages

    def seq_ids(self):
        return list(self._tables)

    # ------------------------------------------------------- alloc / free
    def allocate(self, seq_id, num_tokens):
        """Reserve pages for a new sequence of num_tokens.  Returns True
        on success; False (allocating nothing) when the pool can't cover
        the request — the engine's admission gate."""
        if seq_id in self._tables:
            raise ValueError(f"seq {seq_id!r} already allocated")
        need = self.pages_for(num_tokens)
        if need > self.max_pages_per_seq:
            raise ValueError(
                f"seq {seq_id!r}: {num_tokens} tokens need {need} pages > "
                f"max_pages_per_seq {self.max_pages_per_seq}")
        with self._lock:
            pages = self._take_pages_locked(need)
            if pages is None:
                return False
            self._tables[seq_id] = pages
        return True

    def extend(self, seq_id, num_tokens):
        """Grow seq_id's table to cover num_tokens total.  True on
        success; False (table unchanged) when the pool is exhausted —
        the engine then preempts.  Under pressure, zero-ref cached
        prefix pages are LRU-evicted before giving up."""
        table = self._tables[seq_id]
        need = self.pages_for(num_tokens) - len(table)
        if need <= 0:
            return True
        if len(table) + need > self.max_pages_per_seq:
            raise ValueError(
                f"seq {seq_id!r}: extend to {num_tokens} tokens exceeds "
                f"max_pages_per_seq {self.max_pages_per_seq}")
        with self._lock:
            pages = self._take_pages_locked(need)
            if pages is None:
                return False
            table.extend(pages)
        return True

    def free(self, seq_id):
        """Drop seq_id's references: each page's refcount is
        DECREMENTED, and only pages nobody else holds (no other table,
        no radix node) return to the pool.  Stale contents of truly
        freed pages are fine: pages are fully overwritten before they
        are ever read again."""
        with self._lock:
            for p in self._tables.pop(seq_id):
                self._release_page_locked(p)

    def reset(self):
        """Free everything — tables, prefix cache, refcounts — and zero
        the pool.  Prefix hit/eviction counters stay monotonic (they
        feed Prometheus counters)."""
        with self._lock:
            self._tables.clear()
            self._free = list(range(self.num_pages - 1, -1, -1))
            self._ref = {}
            self._radix = _PrefixNode((), None, None, _ROOT_HASH, 0)
            self._tree_pages = {}
            self._evictable = 0
            self._evict_heap = []
            self.k_pages = jnp.zeros_like(self.k_pages)
            self.v_pages = jnp.zeros_like(self.v_pages)

    # --------------------------------------------------- locked internals
    def _release_page_locked(self, page):
        self._ref[page] -= 1
        count = self._ref[page]
        if count == 0:
            del self._ref[page]
            self._free.append(page)
        elif count == 1:
            node = self._tree_pages.get(page)
            if node is not None:      # tree-only now: became evictable
                self._evictable += 1
                if not node.children:
                    self._note_evictable_locked(node)

    def _bump_ref_locked(self, page):
        count = self._ref.get(page, 0)
        self._ref[page] = count + 1
        if count == 1 and page in self._tree_pages:
            self._evictable -= 1      # referenced again: no longer evictable

    def _note_evictable_locked(self, node):
        """Push ``node`` as an eviction candidate at its current
        ``last_used``.  Lazy: a later touch/bump/detach makes the entry
        stale, detected (and skipped) at pop time.  Compacts the heap
        when stale entries dominate so it stays O(tree)-sized."""
        self._heap_seq += 1
        heapq.heappush(self._evict_heap,
                       (node.last_used, self._heap_seq, node))
        if len(self._evict_heap) > 4 * (len(self._tree_pages) + 16):
            live = {}
            for entry in self._evict_heap:
                last_used, _, cand = entry
                if (cand.last_used == last_used and not cand.children
                        and self._tree_pages.get(cand.page) is cand
                        and self._ref.get(cand.page) == 1):
                    live[id(cand)] = entry
            self._evict_heap = sorted(live.values())

    def _take_pages_locked(self, need):
        """Pop ``need`` pages (refcount 1 each), LRU-evicting zero-ref
        cached prefixes as required.  None (nothing taken) when the
        pool genuinely can't cover it."""
        while len(self._free) < need:
            if not self._evict_one_locked():
                return None
        pages = [self._free.pop() for _ in range(need)]
        for p in pages:
            self._ref[p] = 1
        return pages

    def _evictable_locked(self):
        """Cached pages reclaimable by eviction: tree-held with no
        sequence reference.  A sequence referencing a node references
        every ancestor too, so refcount-1 tree pages always form
        evictable (leaf-first) subtrees.  Maintained incrementally on
        refcount 1<->2 transitions and insert/evict — this sits behind
        num_free_pages/occupancy on every admission check."""
        return self._evictable

    def _iter_nodes_locked(self):
        stack = list(self._radix.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            yield node

    def _evict_one_locked(self):
        """Evict the least-recently-used zero-ref LEAF node (leaf-only:
        an inner node's page is the prefix its cached descendants
        attend through).  Pops the lazy candidate heap, skipping stale
        entries.  Returns True when a page was reclaimed."""
        while self._evict_heap:
            last_used, _, victim = heapq.heappop(self._evict_heap)
            if (victim.last_used != last_used or victim.children
                    or self._tree_pages.get(victim.page) is not victim
                    or self._ref.get(victim.page) != 1):
                continue              # stale entry
            parent = victim.parent
            parent.children.pop(victim.key)
            del self._tree_pages[victim.page]
            self._evictable -= 1
            self._release_page_locked(victim.page)
            self._prefix_stats["evictions"] += 1
            # the parent may have just become an evictable leaf itself
            if (parent is not self._radix and not parent.children
                    and self._ref.get(parent.page) == 1):
                self._note_evictable_locked(parent)
            return True
        return False

    def _match_locked(self, token_ids):
        """Longest cached page-aligned prefix of token_ids: the radix
        walk.  Returns the node-chain pages (LRU-touched)."""
        self._tick += 1
        node, pages = self._radix, []
        for i in range(len(token_ids) // self.page_size):
            key = tuple(int(t) for t in
                        token_ids[i * self.page_size:
                                  (i + 1) * self.page_size])
            child = node.children.get(key)
            if child is None:
                break
            child.last_used = self._tick
            if not child.children and self._ref.get(child.page) == 1:
                # touch stales the old heap entry; re-arm at the new tick
                self._note_evictable_locked(child)
            pages.append(child.page)
            node = child
        return pages

    # ------------------------------------------------------- prefix reuse
    def allocate_prefixed(self, seq_id, token_ids, chunk_tokens):
        """Admission with prefix reuse.

        Walks the radix tree for the longest cached page-aligned prefix
        of ``token_ids``, maps those pages into ``seq_id``'s new table
        read-only (refcount bump), and allocates fresh pages covering
        the first ``chunk_tokens`` uncached tokens — prefill starts at
        the first uncached token.  When the whole prompt is cached the
        match is capped at ``len(token_ids) - 1`` (the model must still
        run ≥1 token for logits) and the final page is **copied on
        write**: the copy receives the last prompt token's K/V, the
        shared original is never written.

        The matched chain is pinned (refcount-bumped) before fresh
        pages are taken, so allocation-pressure eviction can never
        reclaim the very pages being attached.  When a deep match would
        starve its own admission — the matched pages ARE most of the
        evictable pool — the match is shrunk a page at a time (each
        dropped page becomes evictable again), trading hit length for
        admissibility down to a cold admission.

        Returns the number of prompt tokens served from cache (0 = cold
        admission), or None — nothing allocated, no refcount moved —
        when the pool can't cover the request even after evicting every
        zero-ref cached page."""
        if seq_id in self._tables:
            raise ValueError(f"seq {seq_id!r} already allocated")
        n = len(token_ids)
        with self._lock:
            full_match = self._match_locked(token_ids)
            keep = len(full_match)
            while True:
                shared = full_match[:keep]
                cow_src = None
                if shared and len(shared) * self.page_size >= n:
                    # fully cached: COW the final page, re-run its last
                    # token
                    cow_src = shared[-1]
                    shared = shared[:-1]
                    matched = n - 1
                else:
                    matched = len(shared) * self.page_size
                cover = min(matched + max(1, int(chunk_tokens)), n)
                need = self.pages_for(cover)
                if need > self.max_pages_per_seq:
                    raise ValueError(
                        f"seq {seq_id!r}: {cover} tokens need {need} "
                        f"pages > max_pages_per_seq "
                        f"{self.max_pages_per_seq}")
                # PIN the matched chain (and the COW source) BEFORE
                # taking fresh pages: _take_pages_locked may LRU-evict
                # zero-ref tree leaves, and an unpinned match is exactly
                # such a leaf chain — without the bump, eviction could
                # free a matched page and hand it straight back as
                # "fresh" for this same sequence (one physical page at
                # two logical positions: prefill writes would corrupt
                # the cached prefix).
                pinned = list(shared)
                if cow_src is not None:
                    pinned.append(cow_src)
                for p in pinned:
                    self._bump_ref_locked(p)
                fresh = self._take_pages_locked(need - len(shared))
                if fresh is not None:
                    break
                for p in pinned:      # unwind this attempt: no
                    self._release_page_locked(p)  # refcount moved
                if keep == 0:
                    return None       # nothing allocated
                # a pinned match is unevictable, so a deep match can
                # starve its own admission — shrink it one page at a
                # time (the dropped tail becomes evictable again),
                # trading cache reuse for allocatable pages, down to a
                # cold admission before giving up
                keep -= 1
            if cow_src is not None:
                # one-page copy-on-write; cow page is fresh[0] (owned)
                dst = fresh[0]
                self.k_pages = self.k_pages.at[:, dst].set(
                    self.k_pages[:, cow_src])
                self.v_pages = self.v_pages.at[:, dst].set(
                    self.v_pages[:, cow_src])
                # copy landed; the source keeps only its tree/table refs
                self._release_page_locked(cow_src)
            self._tables[seq_id] = shared + fresh
            if matched:
                self._prefix_stats["hits"] += 1
                self._prefix_stats["hit_tokens"] += matched
            return matched

    def insert_prefix(self, seq_id, token_ids):
        """Register ``seq_id``'s FULL prompt pages in the radix tree
        (each newly cached page gets a tree refcount).  Called by the
        engine when a prompt's prefill completes — from then on an
        identical prefix is a refcount bump instead of prefill FLOPs.
        The partial final page (if any) never enters the tree: decode
        keeps writing into it, and mid-decode pages are never shared.
        Returns the number of pages newly inserted."""
        table = self._tables.get(seq_id)
        if table is None:
            return 0
        added = 0
        with self._lock:
            self._tick += 1
            node = self._radix
            for i in range(len(token_ids) // self.page_size):
                key = tuple(int(t) for t in
                            token_ids[i * self.page_size:
                                      (i + 1) * self.page_size])
                child = node.children.get(key)
                if child is None:
                    page = table[i]
                    child = _PrefixNode(
                        key, page, node,
                        _chunk_hash(node.chain_hash, key), self._tick)
                    node.children[key] = child
                    # bump precedes tree entry: the inserting sequence's
                    # table already holds the page, so post-bump ref >= 2
                    # and the new node is never immediately evictable
                    self._ref[page] = self._ref.get(page, 0) + 1
                    self._tree_pages[page] = child
                    self._prefix_stats["inserted_pages"] += 1
                    added += 1
                else:
                    child.last_used = self._tick
                    if (not child.children
                            and self._ref.get(child.page) == 1):
                        # another sequence's since-freed page: the touch
                        # stales its heap entry, re-arm at the new tick
                        self._note_evictable_locked(child)
                node = child
        return added

    def prefix_stats(self):
        """Monotonic prefix-cache counters plus the live cached-page
        gauge — the engine's serving_prefix_* metrics source."""
        with self._lock:
            out = dict(self._prefix_stats)
            out["cached_pages"] = len(self._tree_pages)
        return out

    def prefix_summary(self, max_entries=32):
        """Bounded radix summary for fleet gossip: the ``chain_hash`` →
        cached-prefix-token-depth map of the ``max_entries`` most
        recently used nodes, plus the stats counters.  A router hashes
        an incoming prompt with :func:`prefix_hashes` and the deepest
        hash present here is this pool's expected hit length — token
        ids never leave the process, and the payload is bounded no
        matter how large the tree grows."""
        with self._lock:
            nodes = []
            stack = [(self._radix, 0)]
            while stack:
                node, depth = stack.pop()
                for child in node.children.values():
                    nodes.append((child, depth + 1))
                    stack.append((child, depth + 1))
            nodes.sort(key=lambda t: t[0].last_used, reverse=True)
            entries = {child.chain_hash: depth * self.page_size
                       for child, depth in nodes[:int(max_entries)]}
            stats = dict(self._prefix_stats)
            stats["cached_pages"] = len(self._tree_pages)
            stats["nodes"] = len(nodes)
        return {"page_size": self.page_size, "entries": entries,
                "stats": stats}

    def check_integrity(self):
        """Debug invariant sweep (tests): every page is exactly one of
        free/referenced, refcounts equal table + tree occurrences, the
        free list holds no duplicates, the incremental evictable
        counter matches a full rescan, and every evictable leaf has a
        live entry in the eviction heap.  Raises AssertionError."""
        with self._lock:
            counts = {}
            for table in self._tables.values():
                for p in table:
                    counts[p] = counts.get(p, 0) + 1
            for node in self._iter_nodes_locked():
                counts[node.page] = counts.get(node.page, 0) + 1
            assert counts == self._ref, \
                f"refcount drift: counted {counts} vs {self._ref}"
            assert len(self._free) == len(set(self._free)), \
                "free list holds duplicates (double free)"
            assert not (set(self._free) & set(counts)), \
                "page both free and referenced"
            assert len(self._free) + len(counts) == self.num_pages, \
                "pages leaked: free + referenced != pool"
            for page, node in self._tree_pages.items():
                assert node.page == page, \
                    f"tree-page map drift: {page} -> node.page {node.page}"
            evictable = sum(1 for p in self._tree_pages
                            if self._ref.get(p) == 1)
            assert evictable == self._evictable, \
                (f"evictable counter drift: counted {evictable} vs "
                 f"{self._evictable}")
            for node in self._iter_nodes_locked():
                if node.children or self._ref.get(node.page) != 1:
                    continue
                assert any(nd is node and lu == node.last_used
                           for lu, _, nd in self._evict_heap), \
                    f"evictable leaf (page {node.page}) missing from heap"

    # ---------------------------------------------------------- page table
    def page_table(self, seq_id, width=None):
        """seq_id's table padded with 0 to ``width`` (default
        max_pages_per_seq).  Pad entries are never read: attention masks
        by seq_len and writes are index-routed out of bounds first."""
        width = width or self.max_pages_per_seq
        table = self._tables[seq_id]
        return table + [0] * (width - len(table))

    # -------------------------------------------------------------- defrag
    def defrag(self):
        """Compact live pages into the low-index prefix of the pool.

        Long-running engines interleave alloc/free until the free list is
        scattered; compaction restores locality (sequential page ids DMA
        as one contiguous stream on TPU) and makes the pool's live set
        checkpointable as a prefix slice.  One gather per pool array.

        Refcount-aware: a page shared by several page tables (a cached
        prefix) — or held only by the radix tree — relocates exactly
        ONCE, and every referencing table plus its tree node is updated
        to the new id, so sequences sharing a prefix keep decoding
        token-identically across a defrag.  Returns pages moved."""
        with self._lock:
            order = []               # new physical slot -> old page id
            remap = {}               # old page id -> new page id
            for seq_id in self._tables:
                for old in self._tables[seq_id]:
                    if old not in remap:
                        remap[old] = len(order)
                        order.append(old)
            # cached-but-unreferenced prefix pages are live too: their
            # contents are the cache
            for node in self._iter_nodes_locked():
                if node.page not in remap:
                    remap[node.page] = len(order)
                    order.append(node.page)
            n_used = len(order)
            moved = sum(1 for old, new in remap.items() if old != new)
            if moved == 0:
                return 0
            order += [p for p in range(self.num_pages) if p not in remap]
            idx = jnp.asarray(order, jnp.int32)
            self.k_pages = jnp.take(self.k_pages, idx, axis=1)
            self.v_pages = jnp.take(self.v_pages, idx, axis=1)
            self._tables = {sid: [remap[p] for p in t]
                            for sid, t in self._tables.items()}
            for node in self._iter_nodes_locked():
                node.page = remap[node.page]
            self._ref = {remap[p]: c for p, c in self._ref.items()}
            self._tree_pages = {remap[p]: nd
                                for p, nd in self._tree_pages.items()}
            self._free = list(range(self.num_pages - 1, n_used - 1, -1))
            return moved
