"""Serving metrics — a thin client of paddle_tpu.observability.

The Counter/Gauge/Histogram primitives were promoted to
:mod:`paddle_tpu.observability.metrics` (thread-safe, labelled,
process-wide registry); this module keeps the serving-shaped facade:

  queue_wait   — submit -> admission (scheduler pressure)
  ttft         — submit -> first SAMPLED token, i.e. the step in which
                 the prompt's last chunk completed (chunked prefill:
                 queueing + every chunk step — the user-felt latency of
                 a streaming response's first byte)
  decode_token — per-token decode step time (steady-state speed)
  prefill_chunks — prompt chunks run through the unified step
  page_occupancy — page-pool utilisation gauge, 0..1 (hard use only:
                 evictable cached prefix pages count as free)
  prefix_cache_* — radix prefix-cache hits / hit tokens / LRU
                 evictions (counters) + cached pages (gauge): every
                 hit token is prefill FLOPs the pool skipped

Every metric is registered (serving_-prefixed) into the default
MetricsRegistry with replace semantics, so rebuilding ``ServingMetrics``
(the bench's reset idiom) swaps fresh series into the global snapshot —
and ``bench.py`` / Prometheus exposition / the profiler's counter events
all see serving telemetry with no extra wiring.  Engine phases are
additionally wrapped in profiler.RecordEvent, so a
paddle_tpu.profiler.Profiler session captures serving activity in its
host trace/summary.
"""
from __future__ import annotations

from ..observability.metrics import (  # noqa: F401  (re-export compat)
    Counter,
    Gauge,
    Histogram,
    default_registry,
)

__all__ = ["Counter", "Gauge", "Histogram", "ServingMetrics",
           "RouterMetrics", "AutoscalerMetrics"]


class ServingMetrics:
    """The engine's metric facade; snapshot() is the bench/ops surface.

    ``registry=None`` publishes into the process-wide default registry
    (pass an explicit MetricsRegistry to isolate, e.g. in tests)."""

    def __init__(self, registry=None):
        self.registry = default_registry() if registry is None else registry
        reg = self.registry

        def add(metric):
            return reg.register(metric, replace=True)

        # counter names carry the Prometheus _total suffix —
        # tools/check_metric_names.py (tier-1) enforces the convention
        self.requests_submitted = add(Counter(
            "serving_requests_submitted_total"))
        self.requests_admitted = add(Counter(
            "serving_requests_admitted_total"))
        self.requests_finished = add(Counter(
            "serving_requests_finished_total"))
        self.requests_rejected = add(Counter(
            "serving_requests_rejected_total"))
        self.requests_preempted = add(Counter(
            "serving_requests_preempted_total"))
        self.requests_shed = add(Counter(
            "serving_requests_shed_total",
            help="requests refused with RETRY_AFTER by watermark "
                 "load shedding"))
        self.deadline_evictions = add(Counter(
            "serving_deadline_evictions_total",
            help="requests evicted (mid-decode or queued) past their "
                 "deadline/TTL"))
        self.requests_failed = add(Counter(
            "serving_requests_failed_total",
            help="requests retired FAILED by per-row exception "
                 "isolation — the row broke, the engine (and every "
                 "co-batched request) survived"))
        self.engine_healthy = add(Gauge(
            "serving_engine_healthy",
            help="1 = healthy (admitting), 0 = degraded (shedding)"))
        self.engine_healthy.set(1)
        self.prefix_cache_hits = add(Counter(
            "serving_prefix_cache_hits_total",
            help="admissions whose prompt prefix was served from the "
                 "radix cache (a refcount bump instead of prefill)"))
        self.prefix_cache_evictions = add(Counter(
            "serving_prefix_cache_evictions_total",
            help="zero-ref cached prefix pages LRU-evicted to make "
                 "room for new allocations"))
        self.prefix_hit_tokens = add(Counter(
            "serving_prefix_hit_tokens_total",
            help="prompt tokens served from the prefix cache — each is "
                 "one token of prefill FLOPs avoided"))
        self.prefix_cache_pages = add(Gauge(
            "serving_prefix_cache_pages",
            help="pages currently held by the radix prefix cache "
                 "(shared + evictable)"))
        self.prefill_tokens = add(Counter("serving_prefill_tokens_total"))
        self.prefill_chunks = add(Counter(
            "serving_prefill_chunks_total",
            help="prompt chunks run through the unified step (chunked "
                 "prefill: a prompt is ceil(len/chunk_len) of these)"))
        self.tokens_generated = add(Counter(
            "serving_tokens_generated_total"))
        # unit suffixes are canonical (_seconds, not _s) —
        # tools/check_metric_names.py (tier-1) enforces that too
        self.queue_wait = add(Histogram("serving_queue_wait_seconds"))
        self.ttft = add(Histogram("serving_ttft_seconds"))
        self.decode_token = add(Histogram("serving_decode_token_seconds"))
        self.page_occupancy = add(Gauge("serving_page_occupancy"))
        self.queue_depth = add(Gauge(
            "serving_queue_depth",
            help="requests waiting in the admission queue"))
        self.estimated_drain_s = add(Gauge(
            "serving_estimated_drain_seconds",
            help="estimated seconds to drain all queued + running work "
                 "at the EWMA decode rate — the RETRY_AFTER hint"))

    def snapshot(self):
        return {
            "requests": {
                "submitted": self.requests_submitted.value,
                "admitted": self.requests_admitted.value,
                "finished": self.requests_finished.value,
                "rejected": self.requests_rejected.value,
                "preempted": self.requests_preempted.value,
                "shed": self.requests_shed.value,
                "deadline_evicted": self.deadline_evictions.value,
                "failed": self.requests_failed.value,
            },
            "engine_healthy": self.engine_healthy.value,
            "tokens": {
                "prefill": self.prefill_tokens.value,
                "prefill_chunks": self.prefill_chunks.value,
                "generated": self.tokens_generated.value,
            },
            "prefix_cache": {
                "hits": self.prefix_cache_hits.value,
                "hit_tokens": self.prefix_hit_tokens.value,
                "evictions": self.prefix_cache_evictions.value,
                "cached_pages": self.prefix_cache_pages.value,
            },
            "queue_wait_s": self.queue_wait.summary(),
            "ttft_s": self.ttft.summary(),
            "decode_token_s": self.decode_token.summary(),
            "page_occupancy": {"current": self.page_occupancy.value,
                               "peak": self.page_occupancy.peak},
            "queue_depth": self.queue_depth.value,
            "estimated_drain_s": self.estimated_drain_s.value,
        }

    def summary(self):
        """Human-readable one-screen summary (Profiler.summary style)."""
        s = self.snapshot()
        lines = [f"{'requests':<16} " + "  ".join(
            f"{k}={v}" for k, v in s["requests"].items())]
        lines.append(f"{'tokens':<16} prefill={s['tokens']['prefill']} "
                     f"generated={s['tokens']['generated']}")
        def ms(v):
            # empty histograms report None (fresh process, nothing
            # observed) — render as a dash, not a crash
            return f"{v * 1e3:8.2f}ms" if v is not None else "       -"

        for key in ("queue_wait_s", "ttft_s", "decode_token_s"):
            h = s[key]
            lines.append(
                f"{key:<16} n={h['count']:<6} mean={ms(h['mean'])} "
                f"p50={ms(h['p50'])} p95={ms(h['p95'])}")
        occ = s["page_occupancy"]
        lines.append(f"{'page_occupancy':<16} current={occ['current']:.2f} "
                     f"peak={occ['peak']:.2f}")
        lines.append(f"{'health':<16} "
                     f"{'healthy' if s['engine_healthy'] else 'degraded'}")
        return "\n".join(lines)


class RouterMetrics:
    """Fleet-router metric facade (``router_*`` series, per-replica
    labels).  One instance per :class:`~paddle_tpu.serving.FleetRouter`;
    like :class:`ServingMetrics` it registers into the default registry
    with replace semantics unless an explicit registry is passed."""

    def __init__(self, registry=None):
        self.registry = default_registry() if registry is None else registry
        reg = self.registry

        def add(metric):
            return reg.register(metric, replace=True)

        self.dispatches = add(Counter(
            "router_dispatches_total", labelnames=("replica",),
            help="requests handed to a replica engine (re-dispatches "
                 "after failover/drain included)"))
        self.failovers = add(Counter(
            "router_failovers_total", labelnames=("replica", "reason"),
            help="replica failures that opened the circuit breaker and "
                 "moved every in-flight request elsewhere"))
        self.redispatched = add(Counter(
            "router_redispatched_requests_total",
            help="in-flight requests re-enqueued off a failed or "
                 "drained replica (each exactly once per event)"))
        self.finished = add(Counter(
            "router_requests_finished_total",
            help="fleet requests harvested to FINISHED — the goodput "
                 "numerator the autoscaler reads"))
        self.backpressure_retries = add(Counter(
            "router_backpressure_retries_total", labelnames=("replica",),
            help="dispatches deferred because the replica answered "
                 "RETRY_AFTER (router backs off by the drain hint)"))
        self.cache_aware_dispatches = add(Counter(
            "router_cache_aware_dispatches_total",
            help="dispatches placed on a replica whose gossiped radix "
                 "summary predicted a prefix-cache hit for the request"))
        self.drains = add(Counter(
            "router_drains_total", labelnames=("replica",),
            help="graceful drains started (rolling restarts)"))
        self.restarts = add(Counter(
            "router_replica_restarts_total", labelnames=("replica",),
            help="replica engines rebuilt (post-drain or manual revive)"))
        self.lost = add(Counter(
            "router_requests_lost_total",
            help="requests the router could not place or recover — "
                 "MUST stay 0; anything else is a failover bug"))
        self.quarantined = add(Counter(
            "router_requests_quarantined_total",
            help="requests retired terminal QUARANTINED: suspected of "
                 "poisoning replicas and convicted by killing a canary "
                 "they ran on alone"))
        self.canary_dispatches = add(Counter(
            "router_canary_dispatches_total",
            help="suspect requests admitted alone to a reserved canary "
                 "replica (no co-batched innocents in the blast radius)"))
        self.canary_deaths = add(Counter(
            "router_canary_deaths_total",
            help="canary replicas killed by the lone suspect aboard — "
                 "each is a conviction, not a failover (the replica is "
                 "rebuilt, the request is quarantined, nothing is "
                 "re-dispatched)"))
        self.failure_events = add(Counter(
            "router_replica_failure_events_total",
            help="uncontrolled replica failures (breaker-opening "
                 "crashes/stalls/probe losses; canary deaths excluded) "
                 "— the cascade breaker's sliding-window input"))
        self.cascade_opens = add(Counter(
            "router_cascade_breaker_opens_total",
            help="times the fleet-wide cascade breaker opened "
                 "(>= K uncontrolled replica failures in the window)"))
        self.cascade_open = add(Gauge(
            "router_cascade_breaker_open",
            help="1 = cascade breaker open: suspected requests drain "
                 "through canary-only dispatch and the autoscaler "
                 "holds scale-up (poison is not load)"))
        self.suspects = add(Gauge(
            "router_suspected_requests",
            help="prompt-hash keys currently holding >= 1 suspicion "
                 "point (present at a replica failure)"))
        self.breaker_open = add(Gauge(
            "router_breaker_open", labelnames=("replica",),
            help="1 = circuit breaker open (replica out of rotation)"))
        self.replicas_admittable = add(Gauge(
            "router_replicas_admittable",
            help="replicas currently accepting new admissions"))
        self.fleet_healthy = add(Gauge(
            "router_fleet_healthy",
            help="1 = at least one replica can admit (the /healthz "
                 "fleet fold)"))
        self.pending_depth = add(Gauge(
            "router_pending_depth",
            help="requests waiting in the router queue (not yet on "
                 "any replica)"))
        self.ttft = add(Histogram(
            "router_ttft_seconds",
            help="fleet-level submit -> first token, failover and "
                 "backpressure delays included"))

    @staticmethod
    def _family(metric):
        return {",".join(lv) or "": child.snapshot_value()
                for lv, child in metric._series()}

    def snapshot(self):
        return {
            "dispatches": self._family(self.dispatches),
            "failovers": self._family(self.failovers),
            "redispatched": self.redispatched.value,
            "finished": self.finished.value,
            "backpressure_retries": self._family(self.backpressure_retries),
            "cache_aware_dispatches": self.cache_aware_dispatches.value,
            "drains": self._family(self.drains),
            "restarts": self._family(self.restarts),
            "lost": self.lost.value,
            "quarantined": self.quarantined.value,
            "canary_dispatches": self.canary_dispatches.value,
            "canary_deaths": self.canary_deaths.value,
            "failure_events": self.failure_events.value,
            "cascade_breaker_opens": self.cascade_opens.value,
            "cascade_breaker_open": self.cascade_open.value,
            "suspected_requests": self.suspects.value,
            "breaker_open": self._family(self.breaker_open),
            "replicas_admittable": self.replicas_admittable.value,
            "fleet_healthy": self.fleet_healthy.value,
            "pending_depth": self.pending_depth.value,
            "ttft_s": self.ttft.summary(),
        }


class AutoscalerMetrics:
    """Autoscaler metric facade (``autoscaler_*`` series).  One
    instance per :class:`~paddle_tpu.serving.Autoscaler`; registers
    into the default registry with replace semantics unless an
    explicit registry is passed (the test-isolation idiom)."""

    def __init__(self, registry=None):
        self.registry = default_registry() if registry is None else registry
        reg = self.registry

        def add(metric):
            return reg.register(metric, replace=True)

        self.scale_events = add(Counter(
            "autoscaler_scale_events_total",
            labelnames=("direction", "reason"),
            help="scale decisions acted on — direction up|down, reason "
                 "pressure|pending|shed|no_capacity|idle"))
        self.spawn_failures = add(Counter(
            "autoscaler_spawn_failures_total",
            help="scale-up attempts that exhausted the bounded spawn "
                 "retry budget (backoff included) without a replica"))
        self.target_replicas = add(Gauge(
            "autoscaler_target_replicas",
            help="in-rotation replica count the last decision aimed "
                 "for (healthy count when holding steady)"))
        self.ready_replicas = add(Gauge(
            "autoscaler_ready_replicas",
            help="healthy replicas with a real decode-rate sample — "
                 "warming replicas are excluded from capacity"))
        self.pressure = add(Gauge(
            "autoscaler_pressure_seconds",
            help="fleet pressure signal: mean estimated drain seconds "
                 "per ready replica plus the pending-depth term"))

    def snapshot(self):
        return {
            "scale_events": RouterMetrics._family(self.scale_events),
            "spawn_failures": self.spawn_failures.value,
            "target_replicas": self.target_replicas.value,
            "ready_replicas": self.ready_replicas.value,
            "pressure_s": self.pressure.value,
        }
