"""Serving metrics — counters, gauges and latency histograms.

The serving analog of the reference's inference benchmark counters
(paddle/fluid/inference/api/details reported QPS/latency); here every
engine step feeds a small registry the bench and operators read:

  queue_wait   — submit -> admission (scheduler pressure)
  ttft         — submit -> first token (prefill + queueing, the user-felt
                 latency of a streaming response's first byte)
  decode_token — per-token decode step time (steady-state speed)
  page_occupancy — page-pool utilisation gauge, 0..1

Histograms keep fixed log-spaced buckets (Prometheus-style) plus exact
percentiles over a bounded reservoir.  Engine phases are additionally
wrapped in profiler.RecordEvent, so a paddle_tpu.profiler.Profiler
session captures serving activity in its host trace/summary with no
extra wiring.
"""
from __future__ import annotations

import bisect
import math

__all__ = ["Counter", "Gauge", "Histogram", "ServingMetrics"]


class Counter:
    """Monotonic event counter."""

    def __init__(self, name):
        self.name = name
        self.value = 0

    def inc(self, n=1):
        self.value += n


class Gauge:
    """Last-value gauge that also tracks its peak."""

    def __init__(self, name):
        self.name = name
        self.value = 0.0
        self.peak = 0.0

    def set(self, v):
        self.value = float(v)
        self.peak = max(self.peak, self.value)


class Histogram:
    """Log-bucketed latency histogram with exact bounded-reservoir
    percentiles (the reservoir keeps the newest ``reservoir`` samples —
    serving metrics should reflect current behavior, not cold-start)."""

    def __init__(self, name, start=1e-4, factor=2.0, count=20,
                 reservoir=2048):
        self.name = name
        self.buckets = [start * factor ** i for i in range(count)]
        self.counts = [0] * (count + 1)          # +1 for the overflow bucket
        self.total = 0
        self.sum = 0.0
        self._reservoir = reservoir
        self._samples = []

    def observe(self, v):
        v = float(v)
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.total += 1
        self.sum += v
        self._samples.append(v)
        if len(self._samples) > self._reservoir:
            del self._samples[:len(self._samples) - self._reservoir]

    @property
    def mean(self):
        return self.sum / self.total if self.total else 0.0

    def percentile(self, p):
        """Exact percentile over the reservoir (p in 0..100)."""
        if not self._samples:
            return 0.0
        s = sorted(self._samples)
        idx = min(len(s) - 1, max(0, math.ceil(p / 100.0 * len(s)) - 1))
        return s[idx]

    def summary(self):
        return {"count": self.total, "mean": self.mean,
                "p50": self.percentile(50), "p95": self.percentile(95),
                "p99": self.percentile(99)}


class ServingMetrics:
    """The engine's metric registry; snapshot() is the bench/ops surface."""

    def __init__(self):
        self.requests_submitted = Counter("requests_submitted")
        self.requests_admitted = Counter("requests_admitted")
        self.requests_finished = Counter("requests_finished")
        self.requests_rejected = Counter("requests_rejected")
        self.requests_preempted = Counter("requests_preempted")
        self.prefill_tokens = Counter("prefill_tokens")
        self.tokens_generated = Counter("tokens_generated")
        self.queue_wait = Histogram("queue_wait_s")
        self.ttft = Histogram("ttft_s")
        self.decode_token = Histogram("decode_token_s")
        self.page_occupancy = Gauge("page_occupancy")

    def snapshot(self):
        return {
            "requests": {
                "submitted": self.requests_submitted.value,
                "admitted": self.requests_admitted.value,
                "finished": self.requests_finished.value,
                "rejected": self.requests_rejected.value,
                "preempted": self.requests_preempted.value,
            },
            "tokens": {
                "prefill": self.prefill_tokens.value,
                "generated": self.tokens_generated.value,
            },
            "queue_wait_s": self.queue_wait.summary(),
            "ttft_s": self.ttft.summary(),
            "decode_token_s": self.decode_token.summary(),
            "page_occupancy": {"current": self.page_occupancy.value,
                               "peak": self.page_occupancy.peak},
        }

    def summary(self):
        """Human-readable one-screen summary (Profiler.summary style)."""
        s = self.snapshot()
        lines = [f"{'requests':<16} " + "  ".join(
            f"{k}={v}" for k, v in s["requests"].items())]
        lines.append(f"{'tokens':<16} prefill={s['tokens']['prefill']} "
                     f"generated={s['tokens']['generated']}")
        for key in ("queue_wait_s", "ttft_s", "decode_token_s"):
            h = s[key]
            lines.append(
                f"{key:<16} n={h['count']:<6} mean={h['mean']*1e3:8.2f}ms "
                f"p50={h['p50']*1e3:8.2f}ms p95={h['p95']*1e3:8.2f}ms")
        occ = s["page_occupancy"]
        lines.append(f"{'page_occupancy':<16} current={occ['current']:.2f} "
                     f"peak={occ['peak']:.2f}")
        return "\n".join(lines)
