"""Fleet prefix-cache gossip — radix summaries over the TCPStore plane.

Cache-aware routing needs every router to know, cheaply and staleness-
tolerantly, which replica already holds which prompt prefixes.  Shipping
radix trees (or token ids) around would be unbounded and leak prompt
content; instead each replica publishes the **bounded** summary
``Engine.prefix_summary()`` builds — the chain hashes of its most
recently used cached page-aligned prefixes plus hit stats — and routers
test an incoming prompt's own chain hashes (:func:`...kv_cache.prefix_hashes`)
against it.  The transport is the same
:class:`~paddle_tpu.observability.aggregate.StorePublisher` machinery
every other per-rank publisher rides (metric snapshots, hang-watchdog
heartbeats): one TCPStore key per replica, overwritten in place, a
daemon thread that survives a flaky store, nothing started on import.

Correctness note: gossip is *advisory*.  The dispatch target re-walks
its own tree at admission, so a stale or lost summary mis-scores a
placement (cold prefill where a warm replica existed) but can never
break greedy parity or the router's exactly-once failover contract.

Wiring::

    # each replica process
    PrefixSummaryPublisher(engine, replica_id=r, store=store).start(1.0)

    # the router process
    router = FleetRouter(..., prefix_summary_source=lambda:
        collect_prefix_summaries(store, range(n_replicas)))
"""
from __future__ import annotations

import json

from ..observability.aggregate import StorePublisher

__all__ = ["PrefixSummaryPublisher", "collect_prefix_summaries"]


def _replica_key(prefix, replica_id):
    return f"{prefix}/replica_{int(replica_id)}"


class PrefixSummaryPublisher(StorePublisher):
    """Publish one engine's bounded radix summary under its fleet key.

    ``publish()`` pushes once; ``start(interval_s)`` runs the inherited
    daemon loop.  ``max_entries`` bounds the payload no matter how warm
    the cache gets (the most recently used prefixes win the slots)."""

    def __init__(self, engine, replica_id, store, key_prefix="prefix",
                 max_entries=32, clock=None):
        super().__init__(store, _replica_key(key_prefix, replica_id),
                         clock=clock)
        self.engine = engine
        self.replica_id = int(replica_id)
        self.max_entries = int(max_entries)
        self.thread_name = f"prefix-gossip-{self.replica_id}"

    def payload(self):
        return {"replica": self.replica_id, "time": self._clock(),
                "summary": self.engine.prefix_summary(
                    max_entries=self.max_entries)}


def collect_prefix_summaries(store, replica_ids, key_prefix="prefix",
                             stale_after_s=None, clock=None):
    """Read every replica's published summary in ONE ``mget`` round
    trip.  Returns ``{replica_id: summary}``; replicas that never
    published, published garbage, or whose stamp is older than
    ``stale_after_s`` (publisher wall clock) are simply absent — the
    router then scores them with no cache credit, which is the correct
    cold assumption.  Non-blocking by construction: a router tick never
    waits on a slow store."""
    import time as _time

    replica_ids = list(replica_ids)
    keys = [_replica_key(key_prefix, r) for r in replica_ids]
    out = {}
    now = (clock or _time.time)()
    for rid, raw in zip(replica_ids, store.mget(keys)):
        if raw is None:
            continue
        try:
            payload = json.loads(raw)
        except (ValueError, TypeError):
            continue            # torn/garbled publish: treat as absent
        if stale_after_s is not None and \
                now - float(payload.get("time") or 0.0) > stale_after_s:
            continue
        summary = payload.get("summary")
        if isinstance(summary, dict):
            out[int(rid)] = summary
    return out
