"""Per-replica serve loop — the process a fleet replica lives in.

PR 14's prefix gossip made the publisher machinery
(:class:`~.prefix_gossip.PrefixSummaryPublisher`) available but left
wiring it to callers: in-process fleets pull ``engine.prefix_summary()``
directly, and a replica running as its own process had nothing driving
its gossip.  :class:`ReplicaServer` is that missing piece — the
canonical body of one replica process:

- builds (or adopts) the engine,
- owns exactly one :class:`~.prefix_gossip.PrefixSummaryPublisher` and
  one :class:`~paddle_tpu.observability.trace_gossip.TraceRingPublisher`
  when a TCPStore is given, started for precisely the serve loop's
  lifetime (started in :meth:`serve`, stopped in its ``finally`` —
  a crashed loop never leaves a publisher gossiping for a corpse),
- drives ``engine.step()`` whenever the scheduler has work.

With each replica process running a ``ReplicaServer`` and the router
built with ``prefix_summary_source=lambda:
collect_prefix_summaries(store, ids)``, the autoscaler's cache-warmth
victim selection and the router's cache-aware placement both see
cross-process warmth — the same scores the in-process fleet gets, now
over the TCPStore plane.  The trace publisher is the distributed-
tracing leg of the same plane: each replica's completed-trace ring
(globally-unique, nonce-prefixed trace ids) lands under its own store
key, and ``collect_fleet_traces(store, ids)`` merges them by trace_id
into the one-trace-per-request fleet view.

Wiring::

    # each replica process
    srv = ReplicaServer(lambda: Engine(cfg, params), replica_id=r,
                        store=store, gossip_interval_s=1.0)
    srv.serve(should_stop=shutdown_event.is_set)

    # the router/autoscaler process
    router = FleetRouter(..., prefix_summary_source=lambda:
        collect_prefix_summaries(store, range(n_replicas)))
    fleet_view = collect_fleet_traces(store, range(n_replicas))
"""
from __future__ import annotations

import time

from ..observability.trace_gossip import TraceRingPublisher
from .prefix_gossip import PrefixSummaryPublisher

__all__ = ["ReplicaServer"]


class ReplicaServer:
    """One replica process's serve loop + its gossip publishers.

    ``engine_or_factory`` is a live engine or a zero-arg factory
    (``warmup=True`` runs :meth:`~.engine.Engine.warmup` on a
    factory-built engine before serving — rotation entry is warm but
    the decode EWMA stays unsampled).  ``store=None`` serves without
    gossip (a single-process deployment); with a store, one
    :class:`PrefixSummaryPublisher` publishes this replica's bounded
    radix summary and one :class:`TraceRingPublisher` its completed-
    trace ring, both every ``gossip_interval_s`` while :meth:`serve`
    runs (``trace_gossip=False`` opts the trace leg out;
    ``trace_max_traces`` bounds its payload).  ``idle_sleep_s`` is the
    poll interval when the scheduler is empty."""

    def __init__(self, engine_or_factory, replica_id, *, store=None,
                 gossip_interval_s=1.0, gossip_max_entries=32,
                 key_prefix="prefix", trace_gossip=True,
                 trace_key_prefix="traces", trace_max_traces=64,
                 warmup=True, idle_sleep_s=0.001, clock=None):
        if callable(engine_or_factory) and \
                not hasattr(engine_or_factory, "step"):
            self.engine = engine_or_factory()
            if warmup:
                self.engine.warmup()
        else:
            self.engine = engine_or_factory
        self.replica_id = int(replica_id)
        self.gossip_interval_s = float(gossip_interval_s)
        self.idle_sleep_s = float(idle_sleep_s)
        self.steps = 0
        self.publisher = None
        self.trace_publisher = None
        if store is not None:
            self.publisher = PrefixSummaryPublisher(
                self.engine, self.replica_id, store,
                key_prefix=key_prefix, max_entries=gossip_max_entries,
                clock=clock)
            if trace_gossip and \
                    getattr(self.engine, "tracer", None) is not None:
                self.trace_publisher = TraceRingPublisher(
                    self.engine.tracer, self.replica_id, store,
                    key_prefix=trace_key_prefix,
                    max_traces=trace_max_traces, clock=clock)

    def _publishers(self):
        return [p for p in (self.publisher, self.trace_publisher)
                if p is not None]

    def step(self):
        """One scheduler step (inline-driving hook for tests)."""
        self.steps += 1
        return self.engine.step()

    def serve(self, should_stop=None, max_steps=None):
        """Drive the engine until ``should_stop()`` (or ``max_steps``
        scheduler steps).  The gossip publisher threads run for exactly
        this loop's lifetime and push one final payload on the way
        out, so a replica that drained-and-exited leaves its last
        summary (and its final trace ring — the fleet view keeps its
        segments) behind, not a stale mid-run one.  Returns the number
        of steps served."""
        if should_stop is None and max_steps is None:
            raise ValueError("serve() needs should_stop and/or "
                             "max_steps — an unbounded serve loop has "
                             "no exit")
        served = 0
        for pub in self._publishers():
            pub.start(self.gossip_interval_s)
        try:
            # lint-ok: bounded-retries the loop's bound is the caller's
            # should_stop()/max_steps, validated non-None above — a
            # serve loop, not a retry loop
            while True:
                if should_stop is not None and should_stop():
                    return served
                if max_steps is not None and served >= max_steps:
                    return served
                if self.engine.has_work():
                    self.step()
                    served += 1
                else:
                    time.sleep(self.idle_sleep_s)
        finally:
            for pub in self._publishers():
                pub.stop()
                try:
                    pub.publish()
                except Exception:
                    pass    # silent-ok: a flaky store at shutdown
                    #         cannot matter — collectors treat the
                    #         absent/stale key as a cold replica

    def __enter__(self):
        for pub in self._publishers():
            pub.start(self.gossip_interval_s)
        return self

    def __exit__(self, *exc):
        for pub in self._publishers():
            pub.stop()
        return False
