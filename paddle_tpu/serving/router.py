"""Fault-tolerant serving fleet — the multi-replica router.

One engine (``serving/engine.py``) is fast; a fleet of them is only
*survivable* if something above the replicas treats failure as routine.
Since PR 3–4 every engine exports the router signals — health gauge,
queue depth, ``estimated_drain_s``, soft ``RETRY_AFTER`` with a
machine-readable back-off hint — and this module is their consumer:

- **drain-based load balancing** — new admissions go to the replica
  with the smallest ``estimated_drain_s`` (queue depth breaks ties),
  so a slow or backlogged replica sheds traffic to its peers instead
  of growing an unbounded queue.
- **cache-aware placement** — every replica publishes a bounded radix
  summary of its prefix cache (chain hashes of cached page-aligned
  prefixes + hit stats; :mod:`.prefix_gossip` rides the TCPStore plane
  for cross-process fleets, in-process fleets pull
  ``engine.prefix_summary()`` directly).  Dispatch scores each
  candidate by ``drain − expected_hit_tokens × cache_hit_token_s``:
  a request whose system prompt is warm on replica 2 goes there even
  when replica 1 is marginally less drained — the prefill FLOPs
  avoided outweigh the wait.  The summary is advisory: the chosen
  replica re-walks its OWN tree at admission (failover re-dispatches
  included), so stale gossip can only cost FLOPs, never correctness
  or the exactly-once guarantee.
- **backpressure, not hammering** — a replica answering RETRY_AFTER is
  put in a per-replica back-off window: ``max(retry_after_s hint,
  jittered exponential delay)`` capped at ``backoff_cap_s`` (the delay
  generator is :func:`paddle_tpu.resilience.retry.backoff_delays` —
  the same full-jitter scheme every other blocking edge uses).  The
  window resets on the next successful dispatch.
- **failure detection + circuit breaker** — a replica fails by raising
  ``OSError`` from ``step()``/``add_request()``/``health()`` (a real
  deployment's RPC error; the ``serving.step`` io_error fault site
  reproduces it deterministically), by wedging in admission (wall time
  over ``stall_timeout_s``; the ``serving.admit`` stall site), or by
  missing ``probe_miss_threshold`` consecutive health probes.  After
  ``breaker_threshold`` failures the per-replica circuit breaker
  opens: the replica leaves rotation (``router_breaker_open`` = 1)
  until it is explicitly restarted.
- **zero-loss failover** — when a breaker opens, every in-flight
  request assigned to that replica is re-enqueued **exactly once** at
  the head of the router queue, as an ordinary admission carrying
  ``prompt + already-harvested tokens``.  The dead replica's paged KV
  state is rebuilt elsewhere, never trusted; only tokens harvested
  after a *completed* step count as emitted, so nothing is delivered
  twice and greedy output stays token-identical to an un-failed run
  (the engine's own recompute-parity guarantee, lifted to the fleet).
- **blast-radius containment** — replica failures are attributed to
  *requests*, not just replicas.  Every request aboard at an
  uncontrolled replica failure earns one suspicion point (keyed by
  prompt hash, so failover re-dispatches and retries accumulate); a
  request present at ≥ ``canary_threshold`` distinct failures is only
  ever dispatched ALONE on a reserved *canary* replica, and killing
  the canary too convicts it: terminal ``QUARANTINED`` with the
  failure evidence attached, never re-dispatched.  Canary deaths are
  controlled (the replica restarts from its factory; counted in
  ``router_canary_deaths_total``, not the failure window).  A *cascade
  breaker* opens at ≥ ``cascade_threshold`` uncontrolled failures
  inside ``cascade_window_s``: every suspect (≥ 1 point) then goes
  through canary trial before rejoining normal dispatch, a
  ``router::cascade`` span brackets the storm, and the autoscaler
  holds scale-up while the breaker is open (poison is not load).
  Innocent co-batched requests keep the exactly-once token-identical
  failover guarantee throughout — re-dispatch replays
  ``prompt + harvested tokens`` and host-side greedy sampling is
  batch-composition-independent, so a neighbour's quarantine never
  perturbs their output.
- **graceful drain / rolling restart** — :meth:`FleetRouter.drain`
  marks a replica draining: no new admissions, in-flight decode runs
  to completion bounded by a drain deadline, stragglers are
  re-dispatched exactly once, then the replica's engine is rebuilt
  from its factory and re-enters rotation.  Restart a whole fleet one
  replica at a time with zero dropped requests.

Observability: ``router_*`` metrics (dispatches / failovers /
backpressure retries / breaker state / restarts per replica, fleet
TTFT histogram), tracer spans ``router::dispatch`` /
``router::failover`` / ``router::drain``, and — with the router handed
to :func:`~paddle_tpu.observability.exporter.start_telemetry_server` —
a ``/fleet`` endpoint plus the ``/healthz`` fleet fold (503 only when
*no* replica can admit).

Clocks: scheduling (backpressure windows, drain deadlines, TTLs) reads
the injectable ``clock``; stall detection always uses the real
``time.perf_counter``, because an injected stall sleeps wall time no
matter what the logical clock says.  Replica engines should share the
router's clock so TTL hand-off across failover stays coherent.
"""
from __future__ import annotations

import dataclasses
import random
import threading
import time
from collections import deque

from ..observability.tracing import Tracer, activate, default_tracer
from ..resilience.faults import fault_point
from ..resilience.retry import backoff_delays
from .engine import Engine, RequestState, SamplingParams
from .kv_cache import prefix_hashes
from .metrics import RouterMetrics

__all__ = ["FleetRouter", "FleetRequest", "FleetRequestState",
           "Replica", "ReplicaState"]

_wall = time.perf_counter      # stall detection is real elapsed time


class ReplicaState:
    HEALTHY = "healthy"        # in rotation (may be shedding — that's soft)
    DRAINING = "draining"      # no new admissions; finishing in-flight work
    DEAD = "dead"              # breaker open / drained-out; needs restart


class FleetRequestState:
    PENDING = "pending"        # in the router queue, on no replica
    DISPATCHED = "dispatched"  # admitted to some replica's scheduler
    FINISHED = "finished"
    REJECTED = "rejected"      # infeasible on the replica that saw it
    EVICTED = "evicted"        # fleet-level TTL passed
    FAILED = "failed"          # the replica's per-row isolation pinned an
    #                            exception on THIS request (terminal)
    QUARANTINED = "quarantined"  # convicted poison: suspected at >= 2
    #                              replica failures, then killed the
    #                              canary it ran on alone (terminal,
    #                              evidence attached — never re-dispatched)


@dataclasses.dataclass
class FleetRequest:
    """The router's view of one request across dispatches.

    ``tokens_out`` holds every token *harvested* so far — synced from
    the current replica after each successful step, and the only token
    state that survives a failover (what a streaming front-end has
    already sent downstream).  ``redispatches`` counts how many times
    the request was pulled off a failed/drained replica; the zero-loss
    tests assert it is exactly 1 per failure event."""

    id: int
    prompt: list
    sampling: SamplingParams
    state: str = FleetRequestState.PENDING
    tokens_out: list = dataclasses.field(default_factory=list)
    replica_id: int = None
    finish_reason: str = None
    dispatches: int = 0
    redispatches: int = 0
    t_submit: float = 0.0
    t_first_token: float = None
    t_finished: float = None
    deadline: float = None       # router-clock absolute; None = no TTL
    quarantine_evidence: dict = None   # set iff state == QUARANTINED
    _engine_req: object = None   # Request on the current replica
    _dispatch_base: int = 0      # len(tokens_out) when this dispatch began
    _span: object = None         # root trace span
    _prompt_key: int = 0         # content hash — suspicion is keyed by
    #                              prompt so retries/failovers accumulate

    @property
    def output(self):
        return list(self.tokens_out)


class Replica:
    """One engine slot in the fleet: the live engine, its factory (how
    a rolling restart rebuilds it), breaker/backpressure bookkeeping."""

    def __init__(self, replica_id, engine, factory=None):
        self.replica_id = replica_id
        self.engine = engine
        self.factory = factory
        self.state = ReplicaState.HEALTHY
        self.consecutive_failures = 0
        self.probe_misses = 0
        self.not_before = 0.0          # backpressure window (router clock)
        self.backoff = None            # lazy backoff_delays generator
        self.drain_deadline = None
        self.restart_after_drain = True
        self._drain_span = None
        self.canary_for = None         # FleetRequest.id reserved alone here

    def __repr__(self):
        return (f"Replica({self.replica_id}, {self.state}, "
                f"failures={self.consecutive_failures})")


class _DeadEngine:
    """Stand-in for a hard-killed replica process: every access fails
    the way a connection to a dead host does, so the router's normal
    detection path — failed step, missed probe — finds the corpse."""

    def __init__(self, replica_id):
        object.__setattr__(self, "_rid", replica_id)

    def __getattr__(self, name):
        raise OSError(f"replica {self._rid} process is dead "
                      f"(attempted .{name})")


class FleetRouter:
    """Health-routed fan-out over N in-process serving engines.

    ``replicas`` is a list whose items are either zero-arg callables
    returning a fresh :class:`~paddle_tpu.serving.Engine` (the normal
    form — restarts rebuild through the factory) or live ``Engine``
    instances (restart unavailable).  Drive it like an engine:
    :meth:`submit` then :meth:`step` in a loop, or :meth:`generate`.

    Knobs: ``breaker_threshold`` failures open a replica's breaker
    (default 1 — fail fast, re-dispatch is exactly-once and cheap);
    ``probe_miss_threshold`` consecutive failed health probes count as
    one failure path; ``stall_timeout_s`` bounds the *wall* time an
    admission may take before the replica is declared wedged;
    ``backoff_base_s``/``backoff_cap_s`` shape the jittered
    backpressure window; ``drain_deadline_s`` is the default rolling-
    restart drain budget; ``warmup`` (a callable taking an Engine) runs
    on every factory-rebuilt engine before it re-enters rotation, so a
    restarted replica doesn't serve its first request cold.

    Cache-aware placement: ``cache_aware`` (default on) folds each
    replica's expected prefix-cache hit into the dispatch score at
    ``cache_hit_token_s`` seconds of credit per hit token.
    ``prefix_summary_source`` (a zero-arg callable returning
    ``{replica_id: summary}``, e.g.
    :func:`~paddle_tpu.serving.prefix_gossip.collect_prefix_summaries`
    bound to a TCPStore) replaces the default in-process
    ``engine.prefix_summary()`` pull — the cross-host gossip path.
    ``clock``/``tracer``/``registry`` mirror the engine's injection
    points."""

    def __init__(self, replicas, *, clock=None, tracer=None, registry=None,
                 breaker_threshold=1, probe_miss_threshold=2,
                 stall_timeout_s=0.25, backoff_base_s=0.05,
                 backoff_cap_s=2.0, drain_deadline_s=5.0, warmup=None,
                 cache_aware=True, cache_hit_token_s=0.01,
                 prefix_summary_source=None, rng=None,
                 canary_threshold=2, cascade_threshold=3,
                 cascade_window_s=10.0):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.warmup = warmup
        self._clock = clock or time.perf_counter
        if tracer is None:
            tracer = (default_tracer() if clock is None
                      else Tracer(clock=self._clock))
        self.tracer = tracer
        self.metrics = RouterMetrics(registry=registry)
        self.breaker_threshold = int(breaker_threshold)
        self.probe_miss_threshold = int(probe_miss_threshold)
        self.stall_timeout_s = float(stall_timeout_s)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.drain_deadline_s = float(drain_deadline_s)
        # cache-aware dispatch: score replicas by expected prefix-hit
        # length jointly with the drain estimate.  Each hit token is
        # worth ``cache_hit_token_s`` seconds of avoided prefill in the
        # score (default ~one assumed decode-step per token), so a warm
        # replica beats an equally-drained cold one but a deeply
        # backlogged warm replica still loses to an idle cold peer.
        self.cache_aware = bool(cache_aware)
        self.cache_hit_token_s = float(cache_hit_token_s)
        self._summary_source = prefix_summary_source
        # blast-radius containment: a request in flight at a replica
        # failure earns one suspicion point per DISTINCT failure event
        # (keyed by prompt hash).  At ``canary_threshold`` points it is
        # only ever dispatched alone, on a canary replica; killing the
        # canary too is conviction -> terminal QUARANTINED.
        # ``cascade_threshold`` uncontrolled replica failures inside
        # ``cascade_window_s`` open the fleet cascade breaker: suspects
        # (>=1 point) drain through canary mode only, and the attached
        # autoscaler treats the storm as poison, not load.
        self.canary_threshold = int(canary_threshold)
        self.cascade_threshold = int(cascade_threshold)
        self.cascade_window_s = float(cascade_window_s)
        self._suspects = {}          # prompt_key -> set(failure event ids)
        # prompt_key -> conviction evidence: the verdict OUTLIVES the
        # convicted request, so a storm of requests all carrying the
        # same poison content is quarantined at admission after the
        # first conviction instead of serially re-killing canaries
        self._convicted = {}
        self._failure_seq = 0        # distinct uncontrolled failure events
        self._failure_times = deque()  # their router-clock timestamps
        self._cascade_open = False
        self._cascade_span = None
        self._rng = rng or random
        self.replicas = []
        for item in replicas:
            rid = len(self.replicas)
            # a callable (that isn't itself an engine) is a factory —
            # restarts rebuild through it; anything else is taken as a
            # live engine-shaped object (restart unavailable)
            if callable(item) and not isinstance(item, Engine):
                self.replicas.append(Replica(rid, item(), factory=item))
            else:
                self.replicas.append(Replica(rid, item, factory=None))
            self.metrics.breaker_open.labels(replica=str(rid)).set(0)
        # the telemetry server's scrape thread reads fleet_status()/
        # fleet_health()/has_work() while the driving thread mutates
        # routing state mid-step — serialize on one re-entrant lock
        # (step() nests into helpers that retake it)
        self._lock = threading.RLock()
        self._pending = deque()     # guarded-by: self._lock
        # guarded-by: self._lock
        self._assigned = {rep.replica_id: {} for rep in self.replicas}
        self._next_id = 0           # guarded-by: self._lock
        # per-replica radix gossip: the freshest bounded prefix summary
        # each replica published (direct engine pull, or a TCPStore
        # collector via prefix_summary_source)
        self._prefix_summaries = {}  # guarded-by: self._lock
        self._autoscaler = None      # attach_autoscaler() wires one
        self._update_gauges()

    # ------------------------------------------------------------- lookup
    def _rep(self, replica_id):
        for rep in self.replicas:
            if rep.replica_id == replica_id:
                return rep
        raise KeyError(f"no replica {replica_id!r}")

    # ------------------------------------------------------------- submit
    def submit(self, prompt, sampling: SamplingParams = None):
        """Enqueue a prompt with the router; returns a
        :class:`FleetRequest`.  Dispatch to a replica happens on the
        next :meth:`step` (drain-based placement needs fresh health)."""
        sampling = sampling or SamplingParams()
        now = self._clock()
        with self._lock:
            freq = FleetRequest(id=self._next_id, prompt=list(prompt),
                                sampling=sampling, t_submit=now)
            # suspicion is tracked by CONTENT, not request id: a poison
            # prompt re-submitted (or failover re-dispatched) keeps
            # accumulating points instead of starting innocent
            freq._prompt_key = hash(tuple(freq.prompt))
            self._next_id += 1
            if sampling.ttl_s is not None:
                # the fleet-level deadline: survives failover (the
                # remaining budget, not a fresh TTL, rides to the next
                # replica)
                freq.deadline = now + float(sampling.ttl_s)
            freq._span = self.tracer.start_trace(
                f"fleet#{freq.id}", start_s=now,
                attributes={"request_id": freq.id,
                            "prompt_len": len(freq.prompt),
                            "max_new_tokens": sampling.max_new_tokens})
            self._pending.append(freq)
            self.metrics.pending_depth.set(len(self._pending))
        return freq

    # ----------------------------------------------------------- lifecycle
    def _finish(self, freq, state, reason):
        freq.state = state
        freq.finish_reason = reason
        freq.t_finished = self._clock()
        if freq._span is not None:
            freq._span.set_attributes({
                "state": state, "finish_reason": reason,
                "tokens_out": len(freq.tokens_out),
                "dispatches": freq.dispatches,
                "redispatches": freq.redispatches})
            freq._span.end(freq.t_finished)
            freq._span = None

    def _harvest(self, rep, finished):
        """Sync sampled tokens off ``rep`` after a successful step and
        retire requests the engine finished.  Harvested tokens are the
        failover ground truth — what the fleet has already emitted."""
        with self._lock:
            table = self._assigned[rep.replica_id]
            self._harvest_table(table, finished)
            if rep.canary_for is not None and rep.canary_for not in table:
                # the canaried suspect reached a terminal state without
                # killing its host: the reservation lifts
                rep.canary_for = None

    def _harvest_table(self, table, finished):
        for freq in list(table.values()):
            ereq = freq._engine_req
            out = ereq.output
            # engine preemption rewinds ereq.output and replays the
            # identical tokens; never un-harvest on the rewind
            if len(out) > len(freq.tokens_out) - freq._dispatch_base:
                freq.tokens_out[freq._dispatch_base:] = list(out)
                if freq.t_first_token is None and freq.tokens_out:
                    freq.t_first_token = self._clock()
                    self.metrics.ttft.observe(
                        freq.t_first_token - freq.t_submit,
                        exemplar=getattr(freq._span, "trace_id", None))
            if ereq.state == RequestState.FINISHED:
                del table[freq.id]
                self._finish(freq, FleetRequestState.FINISHED,
                             ereq.finish_reason)
                self.metrics.finished.inc()
                # completing normally exonerates the prompt: a suspect
                # that survives a full run was collateral, not poison
                self._suspects.pop(freq._prompt_key, None)
                finished.append(freq)
            elif ereq.state == RequestState.EVICTED:
                del table[freq.id]
                self._finish(freq, FleetRequestState.EVICTED,
                             ereq.finish_reason)
                self._suspects.pop(freq._prompt_key, None)
                finished.append(freq)
            elif ereq.state == RequestState.FAILED:
                # the engine's per-row isolation pinned an exception on
                # this specific request — terminal at fleet level too,
                # never re-dispatched (the failure is deterministic to
                # the row, not the replica)
                del table[freq.id]
                self._finish(freq, FleetRequestState.FAILED,
                             ereq.finish_reason)
                self._suspects.pop(freq._prompt_key, None)
                finished.append(freq)

    # ------------------------------------------------------------ failure
    def _reclaim(self, rep, reason="failover", exc=None,
                 failure_event=None):
        """Pull every request assigned to ``rep`` back into the router
        queue (front, original admission order), each exactly once.
        Only tokens harvested after a completed step ride along — the
        re-dispatch admission is ``prompt + tokens_out``, so the next
        replica rebuilds KV state from scratch and cannot double-emit.
        ``failure_event`` (a distinct uncontrolled-failure id) charges
        every reclaimed request one suspicion point — all of them were
        aboard when the replica died, and one of them may be why.  Each
        moved request gets a ``router::failover`` child span on ITS OWN
        fleet trace — the original trace continues through re-dispatch
        instead of being severed at the most interesting moment."""
        with self._lock:
            table = self._assigned[rep.replica_id]
            # sort by request id (== admission order): the assignment
            # table is keyed per-dispatch, so relying on dict insertion
            # order would re-enqueue a mixed harvest (original + prior
            # failovers) in arbitrary relative order
            moved = sorted(table.values(), key=lambda f: f.id)
            table.clear()
            rep.canary_for = None
            try:
                # frees the abandoned engine's pages (and closes
                # request traces) when it is still reachable; a
                # hard-dead engine has nothing left to salvage
                rep.engine.evacuate()
            except Exception:
                pass  # silent-ok: a hard-dead engine has nothing to free
            now = self._clock()
            for freq in reversed(moved):
                freq.state = FleetRequestState.PENDING
                freq.replica_id = None
                freq._engine_req = None
                freq.redispatches += 1
                if failure_event is not None:
                    self._suspects.setdefault(
                        freq._prompt_key, set()).add(failure_event)
                if freq._span is not None:
                    self.tracer.start_span(
                        "router::failover", freq._span, start_s=now,
                        attributes={
                            "replica": rep.replica_id, "reason": reason,
                            "error": (repr(exc) if exc is not None
                                      else None),
                            "harvested_tokens": len(freq.tokens_out),
                        }).end(now)
                self._pending.appendleft(freq)
                self.metrics.redispatched.inc()
            self.metrics.pending_depth.set(len(self._pending))
        return moved

    def _on_replica_failure(self, rep, reason, exc=None):
        """Count a failure against ``rep``; at ``breaker_threshold``
        open the breaker and fail everything over.  A canary replica
        dying under its lone suspect is handled as a conviction
        (quarantine + controlled restart) instead — it never feeds the
        cascade window, because the blast was contained by design."""
        if rep.state == ReplicaState.DEAD:
            return
        rep.consecutive_failures += 1
        if rep.consecutive_failures < self.breaker_threshold:
            return
        with self._lock:
            if rep.canary_for is not None and \
                    self._assigned[rep.replica_id]:
                self._on_canary_death(rep, reason, exc)
                return
            rep.canary_for = None   # reservation died before admission
        if rep._drain_span is not None:      # failed mid-drain
            rep._drain_span.set_attributes({"failed": reason})
            rep._drain_span.end()
            rep._drain_span = None
        rep.state = ReplicaState.DEAD
        rep.drain_deadline = None
        rid = str(rep.replica_id)
        self.metrics.breaker_open.labels(replica=rid).set(1)
        self.metrics.failovers.labels(replica=rid, reason=reason).inc()
        # an UNCONTROLLED failure: distinct event id charges suspicion
        # to everything aboard, its timestamp feeds the cascade window
        now = self._clock()
        with self._lock:
            self._failure_seq += 1
            event = self._failure_seq
            self._failure_times.append(now)
            self.metrics.failure_events.inc()
            self._maybe_open_cascade_locked(now)
        # no standalone failover trace: the event lands as a
        # router::failover span on every affected request's own trace
        # (see _reclaim), so the timeline survives the re-dispatch
        self._reclaim(rep, reason=reason, exc=exc, failure_event=event)
        self._update_gauges()

    def _on_canary_death(self, rep, reason, exc):
        """The canary replica died while running its suspect ALONE —
        conclusive guilt.  The suspect goes terminal ``QUARANTINED``
        with the evidence attached (never re-dispatched), the canary is
        rebuilt from its factory (a controlled death: counted in
        ``canary_deaths``, not in the cascade window — the blast radius
        was exactly one reserved replica).  Caller holds ``self._lock``."""
        table = self._assigned[rep.replica_id]
        victims = sorted(table.values(), key=lambda f: f.id)
        table.clear()
        rep.canary_for = None
        try:
            rep.engine.evacuate()
        except Exception:
            pass  # silent-ok: a hard-dead engine has nothing to free
        self.metrics.canary_deaths.inc()
        for freq in victims:
            self._quarantine_locked(freq, rep, reason, exc)
        if rep.factory is not None:
            self._restart(rep)
        else:
            rep.state = ReplicaState.DEAD
            self.metrics.breaker_open.labels(
                replica=str(rep.replica_id)).set(1)
        self._update_gauges()

    def _quarantine_locked(self, freq, rep, reason, exc):
        evidence = {
            "suspicion": len(self._suspects.get(freq._prompt_key, ())),
            "failure_events": sorted(
                self._suspects.get(freq._prompt_key, ())),
            "canary_replica": rep.replica_id,
            "reason": reason,
            "error": repr(exc) if exc is not None else None,
        }
        freq.quarantine_evidence = evidence
        self._convicted[freq._prompt_key] = evidence
        self._suspects.pop(freq._prompt_key, None)
        if freq._span is not None:
            self.tracer.start_span(
                "router::quarantine", freq._span,
                start_s=self._clock(),
                attributes=dict(evidence)).end(self._clock())
        self._finish(freq, FleetRequestState.QUARANTINED,
                     f"poison request: killed canary replica "
                     f"{rep.replica_id} ({reason})")
        self.metrics.quarantined.inc()

    # --------------------------------------------------- cascade breaker
    def _trim_failure_window_locked(self, now):
        cutoff = now - self.cascade_window_s
        while self._failure_times and self._failure_times[0] <= cutoff:
            self._failure_times.popleft()

    def _maybe_open_cascade_locked(self, now):
        self._trim_failure_window_locked(now)
        if self._cascade_open or \
                len(self._failure_times) < self.cascade_threshold:
            return
        self._cascade_open = True
        self.metrics.cascade_opens.inc()
        self.metrics.cascade_open.set(1)
        self._cascade_span = self.tracer.start_trace(
            "router::cascade", start_s=now,
            attributes={"failures_in_window": len(self._failure_times),
                        "threshold": self.cascade_threshold,
                        "window_s": self.cascade_window_s})

    def _maybe_close_cascade_locked(self, now):
        if not self._cascade_open:
            return
        self._trim_failure_window_locked(now)
        if self._failure_times:
            return            # a failure is still inside the window
        if any(rep.canary_for is not None for rep in self.replicas):
            return            # a suspect is mid-trial on a canary
        if any(self._suspicion_locked(f) > 0 for f in self._pending):
            return            # suspects still queued for canary trial
        self._cascade_open = False
        self.metrics.cascade_open.set(0)
        if self._cascade_span is not None:
            self._cascade_span.set_attribute(
                "quarantined_total", int(self.metrics.quarantined.value))
            self._cascade_span.end(now)
            self._cascade_span = None

    def _suspicion_locked(self, freq):
        return len(self._suspects.get(freq._prompt_key, ()))

    def cascade_open(self):
        """Whether the fleet cascade breaker is open (>= K uncontrolled
        replica failures inside the sliding window; suspects draining
        through canary mode).  The autoscaler reads this to keep a
        poison storm from masquerading as load."""
        with self._lock:
            return self._cascade_open

    # ---------------------------------------------------- prefix gossip
    def _refresh_prefix_summaries(self):
        """Pull the freshest per-replica radix summaries: from the
        configured gossip source (a TCPStore collector) when one is
        wired, else straight off each live engine.  A replica whose
        summary can't be fetched keeps its previous one — stale gossip
        only mis-scores a dispatch, it never blocks one."""
        if self._summary_source is not None:
            try:
                fresh = dict(self._summary_source())
            except Exception:   # silent-ok: stale gossip is tolerated —
                return          # scoring falls back to the last summaries
        else:
            fresh = {}
            for rep in self.replicas:
                if rep.state != ReplicaState.HEALTHY:
                    continue
                try:
                    fresh[rep.replica_id] = rep.engine.prefix_summary()
                except (OSError, AttributeError):
                    continue    # dead/foreign engine: keep what we had
        with self._lock:
            self._prefix_summaries.update(fresh)

    def _expected_hit_tokens_locked(self, tokens, replica_id,
                                    hash_cache=None):
        """Expected prefix-cache hit length (tokens) of an admission
        carrying ``tokens`` on ``replica_id``, from its gossiped
        summary: hash the prompt's page-aligned prefixes client-side
        and take the deepest hash the replica's radix summary knows.
        The hash chain depends only on the prompt and the page size —
        ``hash_cache`` (page_size -> chain) lets the _admit loop hash a
        queue head once and score every candidate replica against it.
        Caller holds ``self._lock`` (summaries are shared state)."""
        summary = self._prefix_summaries.get(replica_id)
        if not summary or not summary.get("enabled", True):
            return 0
        entries = summary.get("entries") or {}
        if not entries:
            return 0
        page_size = int(summary.get("page_size") or 16)
        if hash_cache is None:
            hash_cache = {}
        hashes = hash_cache.get(page_size)
        if hashes is None:
            hashes = hash_cache[page_size] = prefix_hashes(
                tokens, page_size)
        best = 0
        for i, h in enumerate(hashes):
            if h in entries:
                best = (i + 1) * page_size
        return min(best, max(len(tokens) - 1, 0))

    # -------------------------------------------------------------- admit
    def _can_admit(self, rep, now):
        # a replica reserved as a canary admits ONLY its suspect: no
        # innocent may be co-batched with a request on trial
        return (rep.state == ReplicaState.HEALTHY
                and now >= rep.not_before
                and rep.canary_for is None)

    def _pick_canary_locked(self, now):
        """An idle healthy replica to run a suspect ALONE on — nothing
        assigned, no reservation, admission window open.  Lowest id
        wins (determinism)."""
        cands = [rep for rep in self.replicas
                 if rep.state == ReplicaState.HEALTHY
                 and rep.canary_for is None
                 and now >= rep.not_before
                 and not self._assigned[rep.replica_id]]
        return min(cands, key=lambda r: r.replica_id) if cands else None

    def _backpressure(self, rep, hint_s, now):
        """RETRY_AFTER from ``rep``: close its admission window for
        max(drain hint, jittered exponential delay), capped — bounded
        backoff that neither hammers nor abandons a loaded replica."""
        if rep.backoff is None:
            rep.backoff = backoff_delays(base=self.backoff_base_s,
                                         cap=self.backoff_cap_s,
                                         rng=self._rng)
        delay = min(self.backoff_cap_s,
                    max(float(hint_s or 0.0), next(rep.backoff)))
        rep.not_before = now + delay
        self.metrics.backpressure_retries.labels(
            replica=str(rep.replica_id)).inc()
        return delay

    def _dispatch_locked(self, freq, rep, now, expected_hit=0,
                         canary=False):
        """Try the queue-head request on ``rep`` (caller holds
        ``self._lock`` — the ``_admit`` loop owns the queue while it
        places work).  ``expected_hit`` is the gossip-predicted prefix
        hit length that steered the placement (telemetry only — the
        target replica re-walks its own tree at admission, so a stale
        prediction costs FLOPs, never correctness).  Returns one of
        "dispatched" / "backpressure" / "rejected" / "evicted" /
        "failed" (replica, not request, at fault)."""
        already = len(freq.tokens_out)
        kw = {"max_new_tokens": freq.sampling.max_new_tokens - already}
        if freq.deadline is not None:
            remaining = freq.deadline - now
            if remaining <= 0:
                self._pending.popleft()
                self._finish(freq, FleetRequestState.EVICTED, "deadline")
                return "evicted"
            kw["ttl_s"] = remaining
        esp = dataclasses.replace(freq.sampling, **kw)
        # the dispatch span is a CHILD of the fleet trace, opened
        # *before* admission so its context rides ``add_request`` into
        # the engine: the replica's request#N segment parents here, and
        # a fault firing inside admission lands on this span (activate)
        dattrs = {"request_id": freq.id, "replica": rep.replica_id,
                  "expected_prefix_hit_tokens": expected_hit,
                  "redispatch": freq.redispatches > 0}
        if canary:
            dattrs["canary"] = True
        if freq._span is not None:
            dspan = self.tracer.start_span("router::dispatch", freq._span,
                                           start_s=now, attributes=dattrs)
        else:
            dspan = self.tracer.start_trace("router::dispatch",
                                            start_s=now, attributes=dattrs)
        t0 = _wall()
        try:
            with activate(dspan):
                ereq = rep.engine.add_request(
                    freq.prompt + freq.tokens_out, esp,
                    trace_context=dspan.context())
        except OSError as e:
            dspan.set_attributes({"outcome": "replica_failed",
                                  "error": repr(e)}).end()
            self._on_replica_failure(rep, "io_error", e)
            return "failed"
        except BaseException as e:
            # SimulatedCrash (and any other non-OSError) rides through;
            # the span still closes so the trace shows where it died
            dspan.set_attribute("error", repr(e)).end()
            raise
        stalled = (_wall() - t0) > self.stall_timeout_s
        if ereq.state == RequestState.RETRY_AFTER:
            dspan.set_attribute("outcome", "backpressure").end()
            self._backpressure(rep, ereq.retry_after_s, now)
            if stalled:
                self._on_replica_failure(rep, "stall")
            return "backpressure"
        if ereq.state == RequestState.REJECTED:
            dspan.set_attribute("outcome", "rejected").end()
            self._pending.popleft()
            self._finish(freq, FleetRequestState.REJECTED,
                         ereq.finish_reason)
            return "rejected"
        # QUEUED: the replica's scheduler owns it now
        self._pending.popleft()
        freq.state = FleetRequestState.DISPATCHED
        freq.replica_id = rep.replica_id
        freq._engine_req = ereq
        freq._dispatch_base = already
        freq.dispatches += 1
        self._assigned[rep.replica_id][freq.id] = freq
        rep.backoff = None                   # successful admission resets
        self.metrics.dispatches.labels(replica=str(rep.replica_id)).inc()
        if expected_hit > 0:
            self.metrics.cache_aware_dispatches.inc()
        dspan.set_attribute("outcome", "dispatched").end()
        if stalled:
            # admission wedge (serving.admit stall site): the request IS
            # assigned, so the failure path reclaims it exactly once
            self._on_replica_failure(rep, "stall")
        return "dispatched"

    def _canary_dispatch_locked(self, head, now, suspicion, skip):
        """Route the queue-head suspect to a canary: an idle healthy
        replica reserved for it ALONE.  Returns ``"wait"`` when no
        replica is free to canary on (the head blocks; in-flight work
        keeps completing elsewhere, so a replica frees up next ticks),
        otherwise the ``_dispatch_locked`` status.  Caller holds
        ``self._lock``."""
        rep = self._pick_canary_locked(now)
        if rep is None or rep.replica_id in skip:
            return "wait"
        try:
            # the canary-dispatch RPC edge: an injected io_error here
            # is a transient dispatch failure — the suspect stays at
            # the queue head and the trial retries next tick
            fault_point("router.canary_dispatch")
        except OSError:
            return "wait"
        rep.canary_for = head.id
        self.metrics.canary_dispatches.inc()
        if head._span is not None:
            self.tracer.start_span(
                "router::canary", head._span, start_s=now,
                attributes={"replica": rep.replica_id,
                            "suspicion": suspicion}).end(now)
        status = self._dispatch_locked(head, rep, now, canary=True)
        if status != "dispatched":
            rep.canary_for = None
        if status in ("backpressure", "failed"):
            skip.add(rep.replica_id)
        return status

    def _admit(self, now):
        """Place queued requests on the best admittable replica.  The
        score is the drain estimate MINUS the expected prefix-cache
        credit (hit tokens x cache_hit_token_s): the fleet routes a
        shared-system-prompt request to the replica already holding its
        prefix unless that replica's backlog outweighs the prefill it
        would save.  A backpressuring or failing replica is skipped for
        the rest of this tick."""
        skip = set()
        with self._lock:
            while self._pending:
                head = self._pending[0]
                verdict = self._convicted.get(head._prompt_key)
                if verdict is not None:
                    # identical content to an already-convicted poison:
                    # the kill is deterministic, so skip the canary and
                    # quarantine on the sibling's evidence
                    self._pending.popleft()
                    head.quarantine_evidence = dict(
                        verdict, convicted_sibling=True)
                    if head._span is not None:
                        self.tracer.start_span(
                            "router::quarantine", head._span,
                            start_s=now,
                            attributes=dict(
                                head.quarantine_evidence)).end(now)
                    self._finish(
                        head, FleetRequestState.QUARANTINED,
                        "poison request: prompt content already "
                        "convicted")
                    self.metrics.quarantined.inc()
                    continue
                suspicion = self._suspicion_locked(head)
                if suspicion >= self.canary_threshold or \
                        (self._cascade_open and suspicion >= 1):
                    # suspect: canary trial only — alone, on a reserved
                    # replica, so a kill convicts exactly one request
                    # and co-batched innocents don't exist to lose
                    status = self._canary_dispatch_locked(
                        head, now, suspicion, skip)
                    if status == "wait":
                        break       # no idle replica to canary on yet
                    continue
                admission_tokens = head.prompt + head.tokens_out
                hash_cache = {}    # page_size -> prefix hash chain
                cands = []
                for rep in self.replicas:
                    if rep.replica_id in skip or \
                            not self._can_admit(rep, now):
                        continue
                    try:
                        h = rep.engine.health()
                    except OSError as e:
                        self._on_replica_failure(rep, "probe", e)
                        continue
                    drain = float(h.get("estimated_drain_s") or 0.0)
                    hit = (self._expected_hit_tokens_locked(
                        admission_tokens, rep.replica_id, hash_cache)
                        if self.cache_aware else 0)
                    cands.append(
                        (drain - hit * self.cache_hit_token_s,
                         (h.get("queue_depth") or 0)
                         + (h.get("running") or 0),
                         rep.replica_id, rep, hit))
                if not cands:
                    break
                cands.sort(key=lambda c: c[:3])
                rep, hit = cands[0][3], cands[0][4]
                status = self._dispatch_locked(head, rep, now,
                                               expected_hit=hit)
                if status in ("backpressure", "failed"):
                    skip.add(rep.replica_id)
            self.metrics.pending_depth.set(len(self._pending))

    # --------------------------------------------------------------- drain
    def drain(self, replica_id, deadline_s=None, restart=True):
        """Graceful rolling-restart entry: stop admitting to the
        replica, let in-flight decode finish within the deadline
        (stragglers re-dispatched), then rebuild its engine from the
        factory and re-enter rotation (``restart=False`` leaves it out
        of rotation instead)."""
        rep = self._rep(replica_id)
        if rep.state != ReplicaState.HEALTHY:
            raise ValueError(f"replica {replica_id} is {rep.state}; only "
                             f"a healthy replica can start draining")
        if restart and rep.factory is None:
            raise ValueError(f"replica {replica_id} has no factory; "
                             f"drain(restart=False) or rebuild manually")
        rep.state = ReplicaState.DRAINING
        rep.drain_deadline = self._clock() + (
            self.drain_deadline_s if deadline_s is None else
            float(deadline_s))
        rep.restart_after_drain = restart
        with self._lock:
            in_flight = len(self._assigned[replica_id])
        rep._drain_span = self.tracer.start_trace(
            "router::drain",
            attributes={"replica": replica_id,
                        "deadline_s": rep.drain_deadline,
                        "in_flight": in_flight})
        self.metrics.drains.labels(replica=str(replica_id)).inc()
        self._update_gauges()
        return rep

    def _finish_drain(self, rep, now):
        stragglers = self._reclaim(rep, reason="drain_deadline")
        if rep._drain_span is not None:
            rep._drain_span.set_attributes(
                {"stragglers": len(stragglers),
                 "deadline_hit": bool(stragglers)})
            rep._drain_span.end(now)
            rep._drain_span = None
        rep.drain_deadline = None
        if rep.restart_after_drain:
            self._restart(rep)
        else:
            rep.state = ReplicaState.DEAD
            self.metrics.breaker_open.labels(
                replica=str(rep.replica_id)).set(1)

    # ------------------------------------------------------------- restart
    def _restart(self, rep):
        eng = rep.factory()
        if self.warmup is not None:
            # e.g. a tiny generate() that compiles the unified step:
            # a replica re-enters rotation warm, so the first real
            # request routed to it doesn't pay the compile
            self.warmup(eng)
        rep.engine = eng
        rep.state = ReplicaState.HEALTHY
        rep.consecutive_failures = 0
        rep.probe_misses = 0
        rep.not_before = 0.0
        rep.backoff = None
        rep.drain_deadline = None
        self.metrics.breaker_open.labels(replica=str(rep.replica_id)).set(0)
        self.metrics.restarts.labels(replica=str(rep.replica_id)).inc()
        self._update_gauges()

    def restart_replica(self, replica_id):
        """Rebuild a dead/drained replica's engine from its factory and
        close the breaker — the fleet supervisor's revive hook."""
        rep = self._rep(replica_id)
        if rep.factory is None:
            raise ValueError(f"replica {replica_id} was built from a "
                             f"live Engine, not a factory — cannot "
                             f"restart")
        self._restart(rep)
        return rep

    def kill_replica(self, replica_id):
        """Emulate a hard replica death (process SIGKILL): the engine
        is replaced by a stub whose every access raises ``OSError``, so
        the normal detection path — failed step, missed probe — finds
        the corpse on the next tick.  Test/bench/ops hook."""
        rep = self._rep(replica_id)
        rep.engine = _DeadEngine(replica_id)
        return rep

    def add_replica(self, factory):
        """Append fresh capacity mid-flight: build an engine through
        ``factory`` (zero-arg callable), run the router warmup on it,
        and enter it into rotation — the autoscaler's scale-up path.
        The engine is built and warmed *before* the replica becomes
        visible, so in-rotation replicas are never half-constructed."""
        if not callable(factory):
            raise ValueError("add_replica needs a zero-arg engine "
                             "factory (restarts rebuild through it)")
        eng = factory()
        if self.warmup is not None:
            self.warmup(eng)
        with self._lock:
            rid = max((r.replica_id for r in self.replicas),
                      default=-1) + 1
            rep = Replica(rid, eng, factory=factory)
            self.replicas.append(rep)
            self._assigned[rid] = {}
            self.metrics.breaker_open.labels(replica=str(rid)).set(0)
        self._update_gauges()
        return rep

    # ---------------------------------------------------------------- step
    def step(self):
        """One fleet tick: advance every live replica one scheduler
        step (harvesting outputs and detecting failures), progress
        drains, probe health, then place queued requests.  Returns the
        fleet requests that reached a terminal state this tick."""
        now = self._clock()
        finished = []
        for rep in self.replicas:
            if rep.state == ReplicaState.DEAD:
                continue
            try:
                has_work = rep.engine.has_work()
            except OSError as e:
                self._on_replica_failure(rep, "crash", e)
                continue
            if not has_work or (rep.state == ReplicaState.DRAINING
                                and now >= rep.drain_deadline):
                continue          # deadline-hit drains reclaim below
            try:
                rep.engine.step()
            except OSError as e:
                self._on_replica_failure(rep, "io_error", e)
                continue
            rep.consecutive_failures = 0
            self._harvest(rep, finished)
        # drain completion runs after the step pass so the tick that
        # harvests a draining replica's last request also restarts it —
        # callers looping on has_work() never strand a drain
        for rep in self.replicas:
            if rep.state != ReplicaState.DRAINING:
                continue
            try:
                drained = not rep.engine.has_work()
            except OSError as e:
                self._on_replica_failure(rep, "crash", e)
                continue
            if drained or now >= rep.drain_deadline:
                self._finish_drain(rep, now)
        # health probes: a wedged-but-idle replica never fails a step,
        # so the probe path is what retires it
        for rep in self.replicas:
            if rep.state == ReplicaState.DEAD:
                continue
            try:
                rep.engine.health()
                rep.probe_misses = 0
            except OSError as e:
                rep.probe_misses += 1
                if rep.probe_misses >= self.probe_miss_threshold:
                    self._on_replica_failure(rep, "probe", e)
        if self.cache_aware:
            # refresh the radix gossip before placement so this tick's
            # admissions (failover re-dispatches included) score
            # against each target replica's current tree
            self._refresh_prefix_summaries()
        self._admit(now)
        with self._lock:
            # re-read the clock: a poison trial earlier in this tick
            # may have burned real window time (canary restart)
            self._maybe_close_cascade_locked(self._clock())
            self.metrics.suspects.set(len(self._suspects))
        self._update_gauges()
        return finished

    def has_work(self):
        with self._lock:
            return bool(self._pending) or \
                any(self._assigned[rep.replica_id]
                    for rep in self.replicas)

    def pending_depth(self):
        """Requests waiting in the router queue (on no replica yet) —
        one of the autoscaler's scale-up signals."""
        with self._lock:
            return len(self._pending)

    def in_flight_counts(self):
        """``{replica_id: requests currently assigned}`` — the
        autoscaler's victim-selection tie-break input."""
        with self._lock:
            return {rep.replica_id: len(self._assigned[rep.replica_id])
                    for rep in self.replicas}

    def prefix_summaries(self):
        """The freshest gossiped radix summary per replica (a copy) —
        the autoscaler scores cache warmth from these."""
        with self._lock:
            return dict(self._prefix_summaries)

    def refresh_prefix_summaries(self):
        """Public refresh hook: re-pull every replica's radix summary
        now (the autoscaler calls this before picking a drain victim,
        so warmth scores reflect the current trees, not the last
        dispatch tick's)."""
        self._refresh_prefix_summaries()

    def attach_autoscaler(self, scaler):
        """Surface ``scaler.status()`` inside the ``/fleet`` payload.
        The fold happens after the router lock is released (the
        autoscaler takes its own lock *before* calling router methods,
        so the two locks must never interleave the other way)."""
        self._autoscaler = scaler
        return scaler

    def generate(self, prompts, sampling=None):
        """Batch convenience mirroring ``Engine.generate``: submit all,
        step the fleet until every request is terminal (or no replica
        is left alive), return each request's output tokens."""
        if sampling is None or isinstance(sampling, SamplingParams):
            sampling = [sampling] * len(prompts)
        reqs = [self.submit(p, s) for p, s in zip(prompts, sampling)]
        while self.has_work():
            if all(rep.state == ReplicaState.DEAD
                   for rep in self.replicas):
                break                     # nobody left to run on
            self.step()
        return [r.output for r in reqs]

    # -------------------------------------------------------------- health
    def _update_gauges(self):
        admittable = sum(1 for rep in self.replicas
                         if rep.state == ReplicaState.HEALTHY)
        self.metrics.replicas_admittable.set(admittable)
        self.metrics.fleet_healthy.set(1 if admittable else 0)

    def fleet_health(self):
        """The ``/healthz`` fleet fold: healthy iff at least one
        replica can admit new work.  A single shedding replica is a
        soft signal (its own RETRY_AFTER says so) — only a fleet where
        every breaker is open or every replica is draining is down."""
        with self._lock:
            per = {}
            for rep in self.replicas:
                per[str(rep.replica_id)] = {
                    "state": rep.state,
                    "breaker_open": rep.state == ReplicaState.DEAD,
                    "in_flight": len(self._assigned[rep.replica_id]),
                }
            admittable = sum(1 for rep in self.replicas
                             if rep.state == ReplicaState.HEALTHY)
            # the cascade breaker being open is SOFT while any replica
            # can still admit: suspects drain through canary trials and
            # innocents keep flowing, so /healthz must not 503
            return {"healthy": admittable > 0,
                    "replicas_admittable": admittable,
                    "replicas_total": len(self.replicas),
                    "pending": len(self._pending),
                    "quarantined": int(self.metrics.quarantined.value),
                    "suspects": len(self._suspects),
                    "cascade_breaker_open": self._cascade_open,
                    "replicas": per}

    def fleet_status(self):
        """The ``/fleet`` endpoint payload: per-replica state + live
        engine health (guarded — a dead replica reports its error
        instead of wedging the scrape) and the router counters."""
        now = self._clock()
        with self._lock:
            per = {}
            for rep in self.replicas:
                entry = {
                    "state": rep.state,
                    "breaker_open": rep.state == ReplicaState.DEAD,
                    "consecutive_failures": rep.consecutive_failures,
                    "probe_misses": rep.probe_misses,
                    "backpressure_for_s": max(0.0,
                                              rep.not_before - now),
                    "in_flight": len(self._assigned[rep.replica_id]),
                    "restartable": rep.factory is not None,
                    "canary_for": rep.canary_for,
                }
                if rep.drain_deadline is not None:
                    entry["drain_deadline_in_s"] = \
                        rep.drain_deadline - now
                try:
                    entry["engine"] = rep.engine.health()
                except OSError as e:
                    entry["engine"] = {"error": repr(e)}
                summary = self._prefix_summaries.get(rep.replica_id)
                if summary is not None:
                    entry["prefix_cache"] = {
                        "enabled": summary.get("enabled", True),
                        "summary_entries": len(summary.get("entries")
                                               or {}),
                        **(summary.get("stats") or {})}
                per[str(rep.replica_id)] = entry
            out = self.fleet_health()
            out["replicas"] = per
            out["cache_aware"] = self.cache_aware
            out["counters"] = self.metrics.snapshot()
        # autoscaler fold OUTSIDE the router lock: status() takes the
        # autoscaler's lock, and ticks take that lock before calling
        # into the router — folding under the router lock would
        # interleave the two in opposite orders (deadlock hazard)
        scaler = self._autoscaler
        if scaler is not None:
            try:
                out["autoscaler"] = scaler.status()
            except Exception as e:
                out["autoscaler"] = {"error": repr(e)}
        return out

    def collect_traces(self, limit=None):
        """The in-process fleet trace view: the router's ring plus each
        live replica engine's ring, merged by trace_id
        (:func:`~paddle_tpu.observability.tracing.merge_traces`) — the
        ``/traces?fleet=1`` payload when the fleet shares one process.
        Tracer objects shared between router and engines (the
        default-tracer case) are read once; a replica whose tracer is
        unreachable (hard-killed engine stub) is skipped — exactly the
        information a SIGKILLed process would lose.  Cross-process
        fleets use the store-plane
        :func:`~paddle_tpu.observability.trace_gossip.collect_fleet_traces`
        instead."""
        from ..observability.tracing import merge_traces

        rings = [("router", self.tracer.traces(limit=limit))]
        seen = {id(self.tracer)}
        for rep in self.replicas:
            try:
                tracer = rep.engine.tracer
            except Exception:
                continue    # silent-ok: a dead engine's ring died with it
            if tracer is None or id(tracer) in seen:
                continue
            seen.add(id(tracer))
            rings.append((f"replica{rep.replica_id}",
                          tracer.traces(limit=limit)))
        return merge_traces(rings)
