"""Chaos soak harness — every resilience subsystem at once, for hours.

Unit tests kill one replica at one site; the soak replays a *diurnal,
bursty, shared-prefix* traffic trace (:mod:`.traffic`) through an
**autoscaled** fleet (:mod:`.autoscaler` over :mod:`.router`) while a
chaos timeline fires hard replica kills, admission stalls, control-
loop stalls, and spawn I/O errors — the standing kill matrix.  One
driver, :func:`run_soak`, backs both ``bench.py --section soak`` (the
long variant) and the compressed tier-1 test, so the invariants are
asserted by CI on every run and measured at scale by the bench:

- ``lost_requests == 0`` — every submitted request reaches FINISHED
  despite kills, stalls, drains, and scale events (the router's
  exactly-once failover contract, held across the whole run);
- **bounded TTFT p99** — recoveries cost latency, never starvation;
- **elasticity both ways** — at least one scale-up (burst) and one
  scale-down (trough) mid-run, recorded in ``/fleet``;
- **visibility** — every chaos event lands a ``soak::<action>`` record
  in the flight recorder (``/flight``) and every recovery shows in
  ``/fleet`` (failovers, drains, restarts, autoscaler events), scraped
  live over HTTP from the run's own telemetry server.

Chaos is a timeline of :class:`ChaosEvent`\\ s, not a random spray:
``kill`` hard-kills a healthy replica (``router.kill_replica`` — the
SIGKILL emulation), ``stall_admit``/``stall_poll`` arm a one-shot
``stall`` at the ``serving.admit`` / ``autoscaler.poll`` fault sites,
``spawn_io_error`` arms a one-shot ``io_error`` at
``autoscaler.scale_up`` (the next spawn attempt dies and is retried
out of the bounded backoff budget), ``bitflip`` arms a one-shot
seeded bit flip in a live KV page at ``serving.step`` (silent state
corruption: at worst one request's output degrades — the fleet must
not notice), and ``poison_storm`` arms a content-matched
``poison_request`` spec (``ev.pattern``) and submits ``ev.count``
requests CARRYING that pattern — every replica they board dies, and
the run asserts the router's blast-radius containment quarantines
them while innocents keep the zero-loss guarantee.  Arming appends a
``FaultSpec(site, kind, occurrence=hits+1)`` to the installed
injector (the poison spec is content-matched instead — it fires on
every step whose batch carries the pattern), so each event fires
deterministically and fully audited (``report["injector_fired"]``).
"""
from __future__ import annotations

import dataclasses
import json
import time
import urllib.error
import urllib.request

from ..observability.flight import FlightRecorder
from ..observability.exporter import ResourceSampler, \
    start_telemetry_server
from ..observability.profiling import StackSampler, \
    phase as profiling_phase
from ..observability.slo import SLOEngine
from ..observability.timeseries import TimeSeriesStore
from ..resilience.faults import FaultInjector, FaultSpec, install, uninstall
from .autoscaler import Autoscaler
from .engine import SamplingParams
from .router import FleetRouter, FleetRequestState, ReplicaState

__all__ = ["ChaosEvent", "run_soak"]

_wall = time.perf_counter


@dataclasses.dataclass
class ChaosEvent:
    """One scheduled chaos action: at trace-time ``t`` (seconds from
    run start), do ``action`` — one of ``kill`` (hard replica death),
    ``stall_admit`` / ``stall_poll`` (one-shot stall at the
    ``serving.admit`` / ``autoscaler.poll`` site, ``stall_s`` long),
    ``spawn_io_error`` (one-shot OSError at ``autoscaler.scale_up``),
    ``bitflip`` (one-shot KV-page bit flip at ``serving.step`` —
    silent live-state corruption), ``poison_storm`` (arm a
    ``poison_request`` spec matching ``pattern`` and submit ``count``
    poison requests carrying it; their FleetRequest ids land in
    ``detail["request_ids"]``).  ``fired``/``detail`` are filled in by
    the run."""

    t: float
    action: str
    stall_s: float = 0.3
    pattern: tuple = None        # poison_storm: the token-ID pattern
    count: int = 3               # poison_storm: poison requests to send
    max_new_tokens: int = 8      # poison_storm: their decode budget
    fired: bool = False
    detail: object = None


def _percentile(values, pct):
    if not values:
        return None
    vs = sorted(values)
    idx = min(len(vs) - 1, max(0, int(round(pct / 100.0 * (len(vs) - 1)))))
    return vs[idx]


def _get_json(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _fire_chaos(ev, router, inj, flight, log, reqs):
    """Apply one due chaos event; every action leaves a flight-recorder
    record so ``/flight`` shows the full chaos timeline.  Actions that
    submit traffic (``poison_storm``) append their FleetRequests to
    ``reqs`` so the run's accounting covers them."""
    detail = None
    if ev.action == "kill":
        victim = next((rep for rep in router.replicas
                       if rep.state == ReplicaState.HEALTHY), None)
        if victim is None:
            detail = "no healthy replica to kill"
        else:
            router.kill_replica(victim.replica_id)
            detail = {"replica": victim.replica_id}
    elif ev.action == "stall_admit":
        inj.specs.append(FaultSpec(
            "serving.admit", "stall",
            occurrence=inj.hits("serving.admit") + 1,
            stall_s=ev.stall_s))
        detail = {"site": "serving.admit", "stall_s": ev.stall_s}
    elif ev.action == "stall_poll":
        inj.specs.append(FaultSpec(
            "autoscaler.poll", "stall",
            occurrence=inj.hits("autoscaler.poll") + 1,
            stall_s=ev.stall_s))
        detail = {"site": "autoscaler.poll", "stall_s": ev.stall_s}
    elif ev.action == "spawn_io_error":
        inj.specs.append(FaultSpec(
            "autoscaler.scale_up", "io_error",
            occurrence=inj.hits("autoscaler.scale_up") + 1))
        detail = {"site": "autoscaler.scale_up"}
    elif ev.action == "bitflip":
        # one seeded bit flip in a live KV page on the next step: the
        # blast radius is at most the request whose page corrupted —
        # the fleet must sail on (no replica failure, no cascade)
        inj.specs.append(FaultSpec(
            "serving.step", "bitflip",
            occurrence=inj.hits("serving.step") + 1))
        detail = {"site": "serving.step"}
    elif ev.action == "poison_storm":
        if not ev.pattern:
            raise ValueError("poison_storm needs a token-ID pattern")
        pattern = tuple(int(t) for t in ev.pattern)
        inj.specs.append(FaultSpec(
            "serving.step", "poison_request", pattern=pattern))
        storm = [router.submit(list(pattern),
                               SamplingParams(
                                   max_new_tokens=ev.max_new_tokens))
                 for _ in range(int(ev.count))]
        reqs.extend(storm)
        detail = {"site": "serving.step", "pattern": list(pattern),
                  "request_ids": [r.id for r in storm]}
    else:
        raise ValueError(f"unknown chaos action {ev.action!r}")
    ev.fired = True
    ev.detail = detail
    with flight.record(f"soak::{ev.action}", group="chaos"):
        pass
    log.append({"t": ev.t, "action": ev.action, "detail": detail})


def run_soak(engine_factory, traffic, horizon_s, *,
             initial_replicas=2, chaos=(), scaler_kw=None,
             router_kw=None, registry=None, deadline_s=120.0,
             grace_s=10.0, min_down_events=1, ttft_bound_s=None,
             prewarm=True, telemetry=True, time_scale=1.0,
             slos=None, scrape_interval_s=0.05,
             rss_slope_bound_bytes_per_s=None, profile=True,
             burn_feedback=None):
    """Replay ``traffic.trace(horizon_s)`` through an autoscaled fleet
    under the ``chaos`` timeline; return the invariant report.

    ``engine_factory`` is the zero-arg factory both the initial fleet
    and every scale-up build through.  ``scaler_kw``/``router_kw``
    override :class:`Autoscaler`/:class:`FleetRouter` knobs.
    ``deadline_s`` hard-bounds the drive loop (wall time);
    ``grace_s`` bounds the post-trace settle loop that lets drains
    finish and the trough scale-down land (``min_down_events``).
    ``time_scale`` multiplies arrival timestamps (0.5 = replay the
    trace twice as fast).  ``ttft_bound_s`` is echoed into the report
    (``ttft_p99_ok``) when set.  With ``telemetry=True`` the run hosts
    its own telemetry server and the report's ``scraped`` section is
    fetched over live HTTP — the recoveries-visible-in-``/fleet``-and-
    ``/flight`` check, not an in-process shortcut.

    Every run hosts a :class:`TimeSeriesStore` scraping the router's
    registry (plus a :class:`ResourceSampler` feeding it) every
    ``scrape_interval_s``, wired into the autoscaler's windowed
    shed/goodput signals and the ``/timeseries`` endpoint; the report
    carries the whole-run RSS leak slope
    (``rss_slope_bytes_per_s``; ``rss_slope_ok`` when a bound is
    given).  Passing ``slos`` (a tuple of
    :class:`~paddle_tpu.observability.slo.SLO`) adds an
    :class:`SLOEngine` evaluated at every scrape: its alert
    transitions land in ``report["slo"]`` and on the scraped ``/slo``
    endpoint, a firing page escalates the autoscaler, and the settle
    loop also waits (inside ``grace_s``) for every alert to clear
    through its hysteresis.

    ``profile=True`` (default) hosts a continuous
    :class:`~paddle_tpu.observability.profiling.StackSampler`: the
    sampler thread runs for the whole soak, a firing SLO page arms a
    high-rate capture linked to the transition span, the report
    carries ``report["profiling"]`` (self-stats + finished captures),
    and the scraped section fetches the live ``/profilez`` payload.
    ``burn_feedback`` closes the load loop: ``True`` thins due
    arrivals by the run's own SLO burn
    (:meth:`~paddle_tpu.observability.slo.SLOEngine.max_burn_rate`
    through :meth:`~.traffic.TrafficGenerator.feedback_factor`) but
    only *while a page is active* — backoff is a mitigation for a
    firing page, not a pre-emptive throttle, and thinning at sub-page
    burns would starve the short-window dispatch denominator the page
    detector itself needs (a traffic-free window reads as burn 0).  A
    callable supplies the burn itself, ungated, and ``None`` defers to
    the generator's own ``burn_feedback`` hook (open loop when
    absent).
    Thinning decisions use each arrival's pre-drawn ``u``, so the
    precomputed trace — and the replay contract — are untouched;
    drops are accounted in ``report["burn_feedback"]``, never counted
    as lost."""
    scaler_kw = dict(scaler_kw or {})
    router_kw = dict(router_kw or {})
    arrivals = traffic.trace(horizon_s)
    chaos = sorted((dataclasses.replace(ev) for ev in chaos),
                   key=lambda ev: ev.t)
    router_kw.setdefault("warmup", lambda eng: eng.warmup())
    router = FleetRouter([engine_factory] * int(initial_replicas),
                         registry=registry, **router_kw)
    store = TimeSeriesStore(registry=registry, clock=_wall,
                            interval_s=scrape_interval_s,
                            max_points=4096)
    sampler = ResourceSampler(registry=store.registry)
    profiler = None
    if profile:
        profiler = StackSampler(registry=store.registry,
                                tracer=router.tracer, clock=_wall)
    slo_engine = None
    if slos:
        slo_engine = SLOEngine(store, slos, registry=registry,
                               tracer=router.tracer, clock=_wall,
                               profiler=profiler)
        scaler_kw.setdefault("slo", slo_engine)
    scaler_kw.setdefault("timeseries", store)
    scaler = Autoscaler(router, engine_factory, registry=registry,
                        **scaler_kw)
    if prewarm:
        # pay every initial replica's jit compile before t=0 (scale-ups
        # still pay theirs mid-run — that's part of the scenario) while
        # keeping the decode EWMA unsampled: replicas start on the
        # drain floor exactly like freshly spawned ones
        for rep in router.replicas:
            rep.engine.warmup()
    flight = FlightRecorder()
    server = None
    if telemetry:
        server = start_telemetry_server(
            port=0, router=router, registry=registry,
            tracer=router.tracer, flight=flight,
            slo=slo_engine, timeseries=store, profiler=profiler)
    inj = install(FaultInjector([], seed=traffic.seed))
    if profiler is not None:
        profiler.start()
    # closed-loop load: resolve the burn source once, thin per arrival.
    # The engine-driven loop reports burn 0 until the page fires —
    # see the docstring for why backoff must be page-gated.
    feedback = None
    if burn_feedback is True and slo_engine is not None:
        def feedback(engine=slo_engine):
            return engine.max_burn_rate() if engine.page_active() \
                else 0.0
    elif callable(burn_feedback):
        feedback = burn_feedback
    fb_dropped, fb_dropped_page = 0, 0
    chaos_log, reqs = [], []
    timed_out = False
    t0 = _wall()
    last_scrape = None

    def _observe():
        # one scrape+evaluate beat per scrape_interval_s of wall time:
        # resources → gauges → store point, then the SLO windows read
        # the fresh history (driven inline, never on a thread — the
        # soak is single-driver by design)
        nonlocal last_scrape
        now_w = _wall()
        if last_scrape is not None and \
                now_w - last_scrape < scrape_interval_s:
            return
        last_scrape = now_w
        with profiling_phase("scrape"):
            sampler.sample_once()
            store.scrape_once()
            if slo_engine is not None:
                slo_engine.evaluate()

    try:
        idx = 0
        while True:
            now = (_wall() - t0) / time_scale
            for ev in chaos:
                if not ev.fired and now >= ev.t:
                    _fire_chaos(ev, router, inj, flight, chaos_log,
                                reqs)
            while idx < len(arrivals) and arrivals[idx].t <= now:
                a = arrivals[idx]
                idx += 1
                # closed-loop backoff: keep iff u < factor (u is the
                # arrival's pre-drawn uniform; factor is 1.0 open-loop,
                # so nothing drops without feedback)
                factor = (traffic.feedback_factor(feedback())
                          if feedback is not None
                          else traffic.live_factor())
                if a.u >= factor:
                    fb_dropped += 1
                    if slo_engine is not None \
                            and slo_engine.page_active():
                        fb_dropped_page += 1
                    continue
                reqs.append(router.submit(a.prompt, SamplingParams(
                    max_new_tokens=a.max_new_tokens)))
            router.step()
            scaler.tick()
            _observe()
            if _wall() - t0 >= deadline_s:
                timed_out = True
                break
            if idx >= len(arrivals) and not router.has_work() and \
                    all(ev.fired for ev in chaos):
                break
        # settle: the trace is over and the fleet is idle — keep the
        # control loop beating so in-progress drains complete, the
        # quiet-trough scale-down lands (its cooldown may still be
        # running when the last request finishes), and every SLO alert
        # clears through its hysteresis (the storm's fire/clear pair
        # must both be on record before the report is cut)
        g0 = _wall()
        while _wall() - g0 < grace_s:
            router.step()
            scaler.tick()
            _observe()
            downs = scaler.status()["scale_events"]["down"]
            draining = any(rep.state == ReplicaState.DRAINING
                           for rep in router.replicas)
            alerts_pending = (slo_engine is not None
                              and slo_engine.alerts_active())
            if downs >= min_down_events and not draining and \
                    not router.has_work() and not alerts_pending:
                break
            time.sleep(0.002)
    finally:
        uninstall()
        if profiler is not None:
            profiler.stop()
    # ---- invariants -----------------------------------------------------
    ttfts = [r.t_first_token - r.t_submit for r in reqs
             if r.t_first_token is not None]
    finished = sum(1 for r in reqs
                   if r.state == FleetRequestState.FINISHED)
    quarantined = [r.id for r in reqs
                   if r.state == FleetRequestState.QUARANTINED]
    failed = [r.id for r in reqs
              if r.state == FleetRequestState.FAILED]
    fleet = router.fleet_status()
    # lost = requests in NO terminal state: a quarantined poison or a
    # row-failed request was contained and accounted, not lost
    terminal = (FleetRequestState.FINISHED, FleetRequestState.REJECTED,
                FleetRequestState.EVICTED, FleetRequestState.FAILED,
                FleetRequestState.QUARANTINED)
    lost = (sum(1 for r in reqs if r.state not in terminal)
            + int(fleet["counters"]["lost"]))
    p99 = _percentile(ttfts, 99)
    report = {
        "wall_s": _wall() - t0,
        "horizon_s": horizon_s,
        "timed_out": timed_out,
        "requests_submitted": len(reqs),
        "requests_finished": finished,
        "requests_quarantined": quarantined,
        "requests_failed": failed,
        # per-request outcome: lets callers parity-check innocents
        # against a poison-free oracle (greedy output is token-
        # identical no matter what was co-batched or quarantined)
        "requests": [{"id": r.id, "state": r.state,
                      "prompt": list(r.prompt), "output": r.output}
                     for r in reqs],
        "lost_requests": lost,
        "ttft_p50_s": _percentile(ttfts, 50),
        "ttft_p99_s": p99,
        "redispatched": fleet["counters"]["redispatched"],
        "scale_events": fleet.get("autoscaler", {}).get(
            "scale_events", {}),
        "spawn_failures": fleet.get("autoscaler", {}).get(
            "spawn_failures", 0),
        "chaos": chaos_log,
        "injector_fired": [{"site": s, "kind": k, "occurrence": o}
                           for s, k, o in inj.fired],
        "traffic": traffic.summary(horizon_s),
        "fleet": fleet,
        "flight": flight.summary(),
        "timeseries": store.stats(),
        # the leak query: least-squares RSS trend over the whole run
        # (bytes/s) — a soak that grows memory shows it here long
        # before the OOM killer would
        "rss_slope_bytes_per_s": store.slope(
            "process_rss_bytes", window_s=_wall() - t0 + 1.0),
    }
    if rss_slope_bound_bytes_per_s is not None:
        slope = report["rss_slope_bytes_per_s"]
        report["rss_slope_bound_bytes_per_s"] = float(
            rss_slope_bound_bytes_per_s)
        report["rss_slope_ok"] = (
            slope is None
            or slope <= float(rss_slope_bound_bytes_per_s))
    if slo_engine is not None:
        report["slo"] = slo_engine.status()
    if profiler is not None:
        report["profiling"] = {"stats": profiler.stats(),
                               "captures": profiler.captures()}
    report["burn_feedback"] = {
        "enabled": (feedback is not None
                    or traffic.burn_feedback is not None),
        "dropped": fb_dropped,
        "dropped_while_page": fb_dropped_page,
    }
    if ttft_bound_s is not None:
        report["ttft_bound_s"] = float(ttft_bound_s)
        report["ttft_p99_ok"] = (p99 is not None
                                 and p99 <= float(ttft_bound_s))
    if server is not None:
        try:
            scraped = {"url": server.url,
                       "fleet": _get_json(server.url + "/fleet"),
                       "flight": _get_json(server.url + "/flight"),
                       # the merged fleet trace view: a hard-killed-and-
                       # failed-over request must read as ONE trace here
                       "traces": _get_json(
                           server.url + "/traces?fleet=1"),
                       "timeseries": _get_json(
                           server.url + "/timeseries")}
            if slo_engine is not None:
                scraped["slo"] = _get_json(server.url + "/slo")
            if profiler is not None:
                scraped["profilez"] = _get_json(
                    server.url + "/profilez")
            try:
                scraped["healthz"] = _get_json(server.url + "/healthz")
                scraped["healthz_ok"] = True
            except urllib.error.HTTPError as e:
                # /healthz answers 503 when no replica can admit — a
                # fleet scaled to zero at the end of the settle is a
                # report field, not a crash
                scraped["healthz_ok"] = False
                scraped["healthz_status"] = e.code
            report["scraped"] = scraped
        finally:
            server.stop()
    return report
