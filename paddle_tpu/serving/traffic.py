"""Seeded deterministic traffic generation — the soak harness's load.

A serving fleet's hard problems are shaped by *when* requests arrive
and *what* they share, not just how many there are.  This module
models both, deterministically from one seed:

- **arrival process** — a Poisson process whose instantaneous rate is
  the ``base_rate_per_s`` modulated by a **diurnal curve** (a sinusoid
  with ``diurnal_amplitude`` over ``day_period_s`` — the day/night
  swing that makes a fixed-size fleet either over-provisioned or
  shedding) and by **burst episodes** (``(start_s, duration_s,
  multiplier)`` windows — the traffic spike that forces a scale-up
  mid-trace).  Sampling is Poisson thinning at the peak rate, so the
  trace is exact for the time-varying intensity, not a per-bin
  approximation.
- **prompt mix with shared-prefix cohorts** — a ``cohort_fraction`` of
  requests draw a cohort id and start with that cohort's fixed prefix
  (the shared-system-prompt population the radix prefix cache and the
  router's cache-aware placement exist for); the rest are unique
  prompts.  Cohort prefixes are generated once at construction, so the
  same seed replays byte-identical traffic.

Everything is pure after construction: :meth:`trace` re-seeds its own
``numpy`` generator from ``seed`` on every call (two calls return
identical traces), :meth:`rate_at` is a pure function of time, and no
method mutates the generator — there is no shared mutable state, so
the object needs no lock and may be read from any thread.

Closed-loop load: the optional ``burn_feedback=`` hook (a zero-arg
callable returning the live SLO burn rate, e.g.
``engine.max_burn_rate``) lets a *driver* thin the precomputed trace
at submission time — each :class:`Arrival` carries a pre-drawn
uniform ``u`` from a **separate** seeded stream, and the driver keeps
the arrival iff ``u < feedback_factor(burn)``.  The trace itself stays
byte-identical (the replay contract is untouched); only the live
keep/drop decision varies with the burn the run actually produced.
"""
from __future__ import annotations

import dataclasses
import math

import numpy as np

__all__ = ["Arrival", "TrafficGenerator"]


@dataclasses.dataclass
class Arrival:
    """One request in a generated trace: when it lands, what it asks.

    ``cohort`` is the shared-prefix cohort id (None for a unique
    prompt) — the soak report groups cache-hit expectations by it.
    ``u`` is the pre-drawn closed-loop thinning uniform: a driver with
    burn feedback submits the arrival iff
    ``u < feedback_factor(burn)``, so backoff is deterministic given
    the burn sequence."""

    t: float
    prompt: list
    max_new_tokens: int
    cohort: int = None
    u: float = 0.0


class TrafficGenerator:
    """Deterministic diurnal + bursty Poisson traffic with a shared-
    prefix prompt mix.

    ``base_rate_per_s`` is the mean arrival rate; the diurnal curve
    multiplies it by ``1 + diurnal_amplitude·sin(2π(t+phase_s)/
    day_period_s)`` and each ``(start_s, duration_s, multiplier)`` in
    ``bursts`` multiplies it again inside its window.  ``prompt_len``
    and ``max_new_tokens`` are inclusive ``(lo, hi)`` ranges; callers
    must keep ``hi + hi`` within the serving model's ``max_seq_len``.
    ``cohort_fraction`` of arrivals share one of ``n_cohorts`` fixed
    ``cohort_prefix_len``-token prefixes.  Identical seeds produce
    identical traces — the soak's replay/repro contract."""

    def __init__(self, base_rate_per_s=20.0, *, diurnal_amplitude=0.6,
                 day_period_s=60.0, phase_s=0.0, bursts=(),
                 n_cohorts=3, cohort_prefix_len=16, cohort_fraction=0.5,
                 prompt_len=(8, 24), max_new_tokens=(4, 8),
                 vocab_size=1024, seed=0, burn_feedback=None,
                 feedback_floor=0.1):
        if not 0.0 <= float(diurnal_amplitude) <= 1.0:
            raise ValueError("diurnal_amplitude must be in [0, 1] "
                             "(>1 would drive the rate negative)")
        if prompt_len[0] < 1 or prompt_len[1] < prompt_len[0]:
            raise ValueError(f"bad prompt_len range {prompt_len!r}")
        self.base_rate_per_s = float(base_rate_per_s)
        self.diurnal_amplitude = float(diurnal_amplitude)
        self.day_period_s = float(day_period_s)
        self.phase_s = float(phase_s)
        self.bursts = tuple((float(s), float(d), float(m))
                            for s, d, m in bursts)
        self.n_cohorts = int(n_cohorts)
        self.cohort_fraction = float(cohort_fraction)
        self.prompt_len = (int(prompt_len[0]), int(prompt_len[1]))
        self.max_new_tokens = (int(max_new_tokens[0]),
                               int(max_new_tokens[1]))
        self.vocab_size = int(vocab_size)
        self.seed = int(seed)
        self.burn_feedback = burn_feedback
        self.feedback_floor = float(feedback_floor)
        # cohort prefixes are fixed at construction (and derived from
        # the seed alone) so every trace of this generator — and every
        # generator built with the same seed — shares them
        prefix_rng = np.random.default_rng((self.seed, 0xC0))
        self.cohort_prefixes = tuple(
            tuple(int(x) for x in prefix_rng.integers(
                0, self.vocab_size, int(cohort_prefix_len)))
            for _ in range(max(self.n_cohorts, 0)))

    # ----------------------------------------------------------- intensity
    def rate_at(self, t):
        """Instantaneous arrival intensity (requests/s) at ``t``."""
        rate = self.base_rate_per_s * (
            1.0 + self.diurnal_amplitude * math.sin(
                2.0 * math.pi * (t + self.phase_s) / self.day_period_s))
        for start, dur, mult in self.bursts:
            if start <= t < start + dur:
                rate *= mult
        return max(0.0, rate)

    def peak_rate(self):
        """An upper bound on :meth:`rate_at` — the thinning envelope."""
        peak = self.base_rate_per_s * (1.0 + self.diurnal_amplitude)
        worst = 1.0
        for _, _, mult in self.bursts:
            worst = max(worst, mult)
        return peak * worst

    # --------------------------------------------------------------- trace
    def _arrival(self, t, rng):
        lo, hi = self.prompt_len
        total_len = int(rng.integers(lo, hi + 1))
        cohort = None
        prompt = []
        if self.cohort_prefixes and \
                rng.uniform() < self.cohort_fraction:
            cohort = int(rng.integers(len(self.cohort_prefixes)))
            prompt = list(self.cohort_prefixes[cohort])
        suffix = max(1, total_len - len(prompt))
        prompt = prompt + [int(x) for x in
                           rng.integers(0, self.vocab_size, suffix)]
        mlo, mhi = self.max_new_tokens
        return Arrival(t=float(t), prompt=prompt,
                       max_new_tokens=int(rng.integers(mlo, mhi + 1)),
                       cohort=cohort)

    def trace(self, horizon_s):
        """The full arrival list over ``[0, horizon_s)``, time-sorted.
        Poisson thinning: candidates at the constant peak rate, each
        kept with probability ``rate_at(t)/peak`` — an exact sample of
        the inhomogeneous process.  Re-seeds from ``self.seed``:
        calling twice returns identical traces (the replay contract)."""
        rng = np.random.default_rng((self.seed, 0xA1))
        fb_rng = np.random.default_rng((self.seed, 0xFB))
        peak = self.peak_rate()
        out = []
        if peak <= 0.0:
            return out
        t = 0.0
        while True:
            t += float(rng.exponential(1.0 / peak))
            if t >= horizon_s:
                return out
            keep = rng.uniform()     # drawn unconditionally: the kept/
            # dropped decision must not perturb downstream draws' order
            if keep * peak <= self.rate_at(t):
                arr = self._arrival(t, rng)
                # closed-loop thinning uniform from a SEPARATE stream,
                # drawn per kept arrival: the main stream's draw order
                # — and therefore the trace — is unchanged whether or
                # not a driver uses burn feedback
                arr.u = float(fb_rng.uniform())
                out.append(arr)

    # --------------------------------------------------- closed-loop load
    def feedback_factor(self, burn):
        """Keep-probability for one arrival given a live burn rate — a
        pure function: 1.0 at or below burn 1 (the budget refills as
        fast as it spends — full load), ``1/burn`` above it, floored at
        ``feedback_floor`` so the fleet still sees *some* traffic and
        the alert can observe recovery."""
        if burn is None or burn != burn or burn <= 1.0:
            return 1.0
        return max(self.feedback_floor, 1.0 / float(burn))

    def live_factor(self):
        """:meth:`feedback_factor` of the ``burn_feedback`` hook's
        current value — 1.0 without a hook, and 1.0 on a hook error
        (feedback must never stall submission)."""
        if self.burn_feedback is None:
            return 1.0
        try:
            return self.feedback_factor(float(self.burn_feedback()))
        except Exception:
            return 1.0      # silent-ok: a broken hook means open loop

    def summary(self, horizon_s, samples=64):
        """Telemetry-shaped description of the configured load: rate
        envelope over the horizon plus the mix knobs (what the soak
        report embeds so a run is interpretable without the code)."""
        ts = [horizon_s * i / max(samples - 1, 1) for i in range(samples)]
        rates = [self.rate_at(t) for t in ts]
        return {
            "base_rate_per_s": self.base_rate_per_s,
            "diurnal_amplitude": self.diurnal_amplitude,
            "day_period_s": self.day_period_s,
            "bursts": list(self.bursts),
            "n_cohorts": len(self.cohort_prefixes),
            "cohort_fraction": self.cohort_fraction,
            "rate_min": min(rates), "rate_max": max(rates),
            "rate_mean": sum(rates) / len(rates),
            "seed": self.seed,
        }
