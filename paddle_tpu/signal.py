"""Signal processing: frame / overlap_add / stft / istft.

Reference parity: python/paddle/signal.py (frame, overlap_add, stft,
istft over the frame_op/overlap_add ops and paddle.fft).

TPU-native notes: framing is a gather with a static index grid (one
XLA gather, MXU-friendly downstream), overlap-add is a segment-sum via
scatter-add; fft rides jnp.fft (XLA's native FFT).  All shapes static.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .core.tensor import Tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _arr(x):
    return x.data if isinstance(x, Tensor) else jnp.asarray(x)


def _check_axis(axis, what):
    if axis not in (0, -1):
        raise ValueError(f"{what} supports axis 0 or -1 (reference "
                         f"signal.py contract), got {axis}")


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice ``x`` into overlapping frames (reference signal.py frame).
    axis=-1: (..., n) → (..., frame_length, num_frames);
    axis=0:  (n, ...) → (frame_length, num_frames, ...)."""
    _check_axis(axis, "frame")
    a = _arr(x)
    if frame_length > a.shape[axis]:
        raise ValueError(
            f"frame_length ({frame_length}) > axis size ({a.shape[axis]})")
    if hop_length <= 0:
        raise ValueError("hop_length must be positive")
    if axis == 0:
        a = jnp.moveaxis(a, 0, -1)
    n = a.shape[-1]
    num = 1 + (n - frame_length) // hop_length
    idx = (np.arange(frame_length)[None, :]
           + hop_length * np.arange(num)[:, None])       # [num, flen]
    out = a[..., idx]                                    # [..., num, flen]
    out = jnp.swapaxes(out, -1, -2)                      # [..., flen, num]
    if axis == 0:
        out = jnp.moveaxis(out, (-2, -1), (0, 1))        # [flen, num, ...]
    return Tensor(out) if isinstance(x, Tensor) else out


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame: sum overlapping frames (reference overlap_add).
    axis=-1: (..., frame_length, num) → (..., n);
    axis=0:  (frame_length, num, ...) → (n, ...)."""
    _check_axis(axis, "overlap_add")
    a = _arr(x)
    if axis == 0:
        a = jnp.moveaxis(a, (0, 1), (-2, -1))
    flen, num = a.shape[-2], a.shape[-1]
    n = (num - 1) * hop_length + flen
    seg = jnp.swapaxes(a, -1, -2)                        # [..., num, flen]
    idx = (np.arange(flen)[None, :]
           + hop_length * np.arange(num)[:, None])       # [num, flen]
    out = jnp.zeros(a.shape[:-2] + (n,), a.dtype)
    out = out.at[..., idx].add(seg)
    if axis == 0:
        out = jnp.moveaxis(out, -1, 0)
    return Tensor(out) if isinstance(x, Tensor) else out


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False,
         onesided=True, name=None):
    """Short-time Fourier transform (reference signal.py stft).
    x: [..., n]; returns [..., n_fft//2+1 or n_fft, num_frames] complex."""
    a = _arr(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        win = jnp.ones((win_length,), jnp.float32)
    else:
        win = _arr(window).astype(jnp.float32)
    # center-pad the window to n_fft (reference behavior)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        win = jnp.pad(win, (lpad, n_fft - win_length - lpad))
    if center:
        pad = n_fft // 2
        a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(pad, pad)],
                    mode=pad_mode)
    frames = frame(a, n_fft, hop_length)                 # [..., n_fft, num]
    frames = jnp.swapaxes(frames, -1, -2) * win          # [..., num, n_fft]
    spec = (jnp.fft.rfft(frames, n=n_fft, axis=-1) if onesided
            else jnp.fft.fft(frames, n=n_fft, axis=-1))
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
    spec = jnp.swapaxes(spec, -1, -2)                    # [..., freq, num]
    return Tensor(spec) if isinstance(x, Tensor) else spec


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with the standard window-square normalization
    (reference signal.py istft)."""
    spec = _arr(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        win = jnp.ones((win_length,), jnp.float32)
    else:
        win = _arr(window).astype(jnp.float32)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        win = jnp.pad(win, (lpad, n_fft - win_length - lpad))
    if normalized:
        spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
    frames = jnp.swapaxes(spec, -1, -2)                  # [..., num, freq]
    t = (jnp.fft.irfft(frames, n=n_fft, axis=-1) if onesided
         else jnp.fft.ifft(frames, n=n_fft, axis=-1))
    if not return_complex:
        t = jnp.real(t)
    t = t * win
    y = overlap_add(jnp.swapaxes(t, -1, -2), hop_length)
    # window-square envelope normalization
    num = frames.shape[-2]
    wsq = jnp.tile((win * win)[None, :], (num, 1))
    env = overlap_add(jnp.swapaxes(wsq, -1, -2), hop_length)
    y = y / jnp.maximum(env, 1e-10)
    if center:
        pad = n_fft // 2
        y = y[..., pad:y.shape[-1] - pad]
    if length is not None:
        y = y[..., :length]
    return Tensor(y) if isinstance(x, Tensor) else y
