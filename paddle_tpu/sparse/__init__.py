"""Sparse tensors (parity: python/paddle/sparse/ + the reference's
SparseCooTensor/SparseCsrTensor, paddle/phi/core/sparse_*_tensor.h).

TPU-native: COO rides jax.experimental.sparse.BCOO — XLA lowers sparse
matmul/sddmm-style ops to gather/scatter compute the MXU can chew on.
CSR is represented as (crows, cols, values) and converted through BCOO
for compute (the reference likewise converts between formats).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "matmul", "add", "to_dense"]


def _arr(x):
    return x.data if isinstance(x, Tensor) else jnp.asarray(x)


class SparseCooTensor:
    """COO wrapper over BCOO (dense_tensor zoo row N8)."""

    def __init__(self, bcoo):
        self._bcoo = bcoo

    @property
    def shape(self):
        return tuple(self._bcoo.shape)

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor(self._bcoo.indices.T)   # [ndim, nnz] like paddle

    def values(self):
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def to_sparse_csr(self):
        assert len(self.shape) == 2
        bcsr = jsparse.BCSR.from_bcoo(self._bcoo.sort_indices())
        return SparseCsrTensor.from_bcsr(bcsr)

    def __repr__(self):
        return f"SparseCooTensor(shape={self.shape}, nnz={self.nnz})"


class SparseCsrTensor:
    def __init__(self, crows, cols, values, shape):
        self._crows = jnp.asarray(crows, jnp.int32)
        self._cols = jnp.asarray(cols, jnp.int32)
        self._values = _arr(values)
        self._shape = tuple(shape)

    @classmethod
    def from_bcsr(cls, bcsr):
        return cls(bcsr.indptr, bcsr.indices, bcsr.data, bcsr.shape)

    @property
    def shape(self):
        return self._shape

    @property
    def nnz(self):
        return int(self._values.shape[0])

    def crows(self):
        return Tensor(self._crows)

    def cols(self):
        return Tensor(self._cols)

    def values(self):
        return Tensor(self._values)

    def _bcoo(self):
        bcsr = jsparse.BCSR((self._values, self._cols, self._crows),
                            shape=self._shape)
        return bcsr.to_bcoo()

    def to_dense(self):
        return Tensor(self._bcoo().todense())

    def to_sparse_coo(self, sparse_dim=2):
        return SparseCooTensor(self._bcoo())

    def __repr__(self):
        return f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz})"


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    """paddle.sparse.sparse_coo_tensor parity: indices [ndim, nnz]."""
    idx = jnp.asarray(_arr(indices), jnp.int32)
    vals = _arr(values)
    if dtype is not None:
        vals = vals.astype(dtype)
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(idx.max(axis=1)))
    bcoo = jsparse.BCOO((vals, idx.T), shape=tuple(shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    vals = _arr(values)
    if dtype is not None:
        vals = vals.astype(dtype)
    return SparseCsrTensor(_arr(crows), _arr(cols), vals, shape)


def to_dense(x):
    return x.to_dense()


def matmul(a, b):
    """sparse @ dense (paddle.sparse.matmul)."""
    bd = _arr(b)
    if isinstance(a, SparseCsrTensor):
        a = a.to_sparse_coo()
    out = a._bcoo @ bd
    return Tensor(out)


def add(a, b):
    """sparse + sparse → sparse (same format as ``a``)."""
    if a.shape != b.shape:
        raise ValueError(f"sparse add shape mismatch: {a.shape} vs "
                         f"{b.shape}")
    want_csr = isinstance(a, SparseCsrTensor)
    aa = a.to_sparse_coo() if want_csr else a
    bb = b.to_sparse_coo() if isinstance(b, SparseCsrTensor) else b
    summed = jsparse.bcoo_sum_duplicates(_coo_add(aa._bcoo, bb._bcoo))
    out = SparseCooTensor(summed)
    return out.to_sparse_csr() if want_csr else out


def _coo_add(x, y):
    data = jnp.concatenate([x.data, y.data])
    idx = jnp.concatenate([x.indices, y.indices], axis=0)
    return jsparse.BCOO((data, idx), shape=x.shape)
