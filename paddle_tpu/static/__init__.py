"""paddle.static — the Program/Executor facade (reference parity:
python/paddle/static/ over fluid/framework.py Program:4777 +
fluid/executor.py Executor:619).

On TPU the Executor compiles the captured op-list Program with jax.jit —
instruction scheduling/streams/GC are XLA's (the InterpreterCore jobs);
the Program remains a REWRITABLE IR for passes (static/passes.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from . import passes
from .passes import new_pass
from .program import (Program, current_program, data, default_main_program,
                      program_guard)
from .control_flow import cond, while_loop
from . import nn

__all__ = ["Program", "program_guard", "default_main_program", "data",
           "Executor", "CompiledProgram", "new_pass", "passes",
           "cond", "while_loop", "nn"]


class Executor:
    """Compile-and-run a Program (fluid/executor.py:619 Executor.run)."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None,
            use_passes=("dead_code_elimination",)):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_vids = []
        for t in fetch_list:
            vid = program.lookup(t)
            if vid is None:
                raise ValueError("fetch target was not produced by this "
                                 "program")
            fetch_vids.append(vid)

        # version catches in-place mutation (appended ops, user-applied
        # passes); the cached entry holds a strong ref to the source
        # program so id() cannot be recycled while the entry lives
        key = (id(program), program.version, tuple(fetch_vids),
               tuple(sorted(feed)), tuple(use_passes or ()))
        entry = self._cache.get(key)
        if entry is None:
            prog = program.clone()
            for name in (use_passes or ()):
                new_pass(name).apply(prog, fetch_vids)

            def fn(feed_arrays, param_arrays):
                return prog.replay(feed_arrays, fetch_vids, param_arrays)

            entry = (jax.jit(fn), prog, program)
            self._cache[key] = entry
        runner, prog, _src = entry
        # params enter as jit INPUTS, so weight updates between runs are
        # visible (the reference's scope-variable semantics)
        out = runner(
            {k: jnp.asarray(v.data if isinstance(v, Tensor) else v)
             for k, v in feed.items()},
            [t.data for t in prog.param_refs()])
        return [np.asarray(o) for o in out]

    def close(self):
        self._cache.clear()


class CompiledProgram:
    """Parity shim for fluid.compiler.CompiledProgram: a Program bundled
    with its pass pipeline."""

    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy

    def __getattr__(self, item):
        return getattr(self.program, item)
