"""Static control flow — cond / while_loop ops in the Program IR.

Reference parity: paddle.static.nn.cond / while_loop over
operators/controlflow/conditional_block_op.cc and while_op.cc (sub-block
execution with scope-hierarchy variable lookup), built by
fluid/layers/control_flow.py.

TPU-native design: a branch/body is captured into a CHILD Program whose
free variables (references to enclosing-block vids) and parameters become
inputs of ONE parent-block op; that op's pure function lowers to
``jax.lax.cond`` / ``jax.lax.while_loop`` over the child's replay.  The
whole construct stays a single rewritable OpDesc for passes, and XLA
compiles real device-side control flow — where the reference interprets
sub-blocks with a second Executor on host.

Both APIs also run EAGERLY (no program being captured): pred/cond are
concrete, so Python control flow is the dygraph path, exactly the
reference's dygraph fallback in layers.cond.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .program import Program, current_program, program_guard

__all__ = ["cond", "while_loop"]


def _as_tensor_list(out, what):
    if out is None:
        return []
    outs = list(out) if isinstance(out, (tuple, list)) else [out]
    for o in outs:
        if not isinstance(o, Tensor):
            raise TypeError(f"{what} must return Tensor(s), got "
                            f"{type(o).__name__}")
    return outs


def _aval(t):
    return (tuple(t.data.shape), str(t.data.dtype))


class _Block:
    """One captured sub-block: child Program + out vids + free/param lists."""

    def __init__(self, fn, parent, placeholders=()):
        self.sub = Program(parent=parent)
        phs, self.ph_vids = [], []
        for t in placeholders:
            ph, vid = self.sub.add_local_like(t)
            phs.append(ph)
            self.ph_vids.append(vid)
        with program_guard(self.sub):
            outs = _as_tensor_list(fn(*phs), getattr(fn, "__name__", "block"))
        self.outs = outs
        self.out_vids = []
        for o in outs:
            vid = self.sub.lookup(o)
            if vid is None:
                # pass-through of an outer/placeholder tensor
                vid = self.sub.lookup_chain(o)
            if vid is None:
                raise ValueError(
                    "control-flow block returned a tensor that was not "
                    "computed from its inputs or enclosing-block variables")
            self.out_vids.append(vid)
        # free outer vars discovered during capture; out pass-throughs of
        # outer vars are in free_vars via the lookup_chain above
        self.free = dict(self.sub.free_vars)       # vid -> Tensor
        self.params = self.sub.param_refs()


def _is_traced(t):
    return isinstance(t, Tensor) and isinstance(t.data, jax.core.Tracer)


def _lax_tree(fn):
    """Run a branch fn, unwrapping Tensor outputs to arrays (for direct
    lax lowering when already under a jax trace)."""
    out = fn() if fn is not None else None
    return jax.tree_util.tree_map(
        lambda x: x.data if isinstance(x, Tensor) else x, out,
        is_leaf=lambda x: isinstance(x, Tensor))


def cond(pred, true_fn=None, false_fn=None, name=None):
    """paddle.static.nn.cond parity.  Eagerly: Python if/else.  Under
    program capture: ONE cond OpDesc lowering to jax.lax.cond.  Under an
    active jax trace (jit.to_static, no program_guard): lower straight
    to lax.cond — the construct this error path tells users to reach for
    must itself work there."""
    prog = current_program()
    if prog is None:
        if _is_traced(pred):
            out = jax.lax.cond(pred.data.reshape(()),
                               lambda _: _lax_tree(true_fn),
                               lambda _: _lax_tree(false_fn), None)
            return jax.tree_util.tree_map(Tensor, out)
        taken = true_fn if bool(pred) else false_fn
        return taken() if taken is not None else None

    tb = _Block(true_fn, prog) if true_fn else _Block(lambda: [], prog)
    fb = _Block(false_fn, prog) if false_fn else _Block(lambda: [], prog)
    if len(tb.outs) != len(fb.outs):
        raise ValueError(
            f"cond branches must return the same number of tensors: "
            f"true_fn returned {len(tb.outs)}, false_fn {len(fb.outs)}")
    for i, (a, b) in enumerate(zip(tb.outs, fb.outs)):
        if _aval(a) != _aval(b):
            raise ValueError(
                f"cond branch output {i} mismatch: true_fn "
                f"{_aval(a)} vs false_fn {_aval(b)} — both branches must "
                f"produce identical shapes/dtypes (XLA control flow is "
                f"shape-static)")

    free_vids = sorted(set(tb.free) | set(fb.free))
    free_tensors = [tb.free[v] if v in tb.free else fb.free[v]
                    for v in free_vids]
    params, seen = [], set()
    for p in tb.params + fb.params:
        if id(p) not in seen:
            seen.add(id(p))
            params.append(p)
    n_free = len(free_vids)
    t_runner_vids, f_runner_vids = tb.out_vids, fb.out_vids
    tb_sub, fb_sub = tb.sub, fb.sub
    param_ids = [id(p) for p in params]

    def pure_fn(pred_val, *vals):
        free_env = dict(zip(free_vids, vals[:n_free]))
        param_env = dict(zip(param_ids, vals[n_free:]))
        p = jnp.asarray(pred_val).reshape(())

        def t_run(_):
            return tuple(tb_sub.replay_env(dict(free_env), t_runner_vids,
                                           param_env))

        def f_run(_):
            return tuple(fb_sub.replay_env(dict(free_env), f_runner_vids,
                                           param_env))

        return jax.lax.cond(p, t_run, f_run, None)

    # build-time eager value: the true branch's outputs are representative
    # (both branches verified shape/dtype-identical above)
    out_tensors = [Tensor(o.data) for o in tb.outs]
    leaves, treedef = jax.tree_util.tree_flatten(
        ((pred, *free_tensors, *params), {}),
        is_leaf=lambda x: isinstance(x, Tensor))
    prog.record("cond", pure_fn, treedef, leaves, out_tensors)
    if not out_tensors:
        return None
    return out_tensors[0] if len(out_tensors) == 1 else out_tensors


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """paddle.static.nn.while_loop parity.  Eagerly: a Python while.
    Under capture: ONE while OpDesc lowering to jax.lax.while_loop
    (body and condition each captured into a child Program with the
    loop vars as block-local placeholders)."""
    loop_vars = list(loop_vars)
    for v in loop_vars:
        if not isinstance(v, Tensor):
            raise TypeError("while_loop loop_vars must be Tensors")
    prog = current_program()
    if prog is None:
        probe = cond_fn(*loop_vars)       # reused as the first loop test
        if any(_is_traced(v) for v in loop_vars) or _is_traced(probe):
            # under a jax trace (jit.to_static): lower directly
            def c_run(carry):
                r = cond_fn(*[Tensor(c) for c in carry])
                return jnp.asarray(
                    r.data if isinstance(r, Tensor) else r).reshape(())

            def b_run(carry):
                out = body_fn(*[Tensor(c) for c in carry])
                outs = (list(out) if isinstance(out, (tuple, list))
                        else [out])
                return tuple(o.data if isinstance(o, Tensor) else o
                             for o in outs)

            final = jax.lax.while_loop(
                c_run, b_run, tuple(v.data for v in loop_vars))
            return [Tensor(f) for f in final]
        vals = loop_vars
        cont = probe
        while bool(cont):
            out = body_fn(*vals)
            vals = list(out) if isinstance(out, (tuple, list)) else [out]
            if len(vals) != len(loop_vars):
                raise ValueError(
                    f"while_loop body returned {len(vals)} vars for "
                    f"{len(loop_vars)} loop_vars")
            cont = cond_fn(*vals)
        return vals

    cb = _Block(cond_fn, prog, placeholders=loop_vars)
    bb = _Block(body_fn, prog, placeholders=loop_vars)
    if len(cb.outs) != 1 or cb.outs[0].data.size != 1:
        raise ValueError("while_loop condition must return one scalar "
                         "boolean tensor")
    if len(bb.outs) != len(loop_vars):
        raise ValueError(
            f"while_loop body returned {len(bb.outs)} vars for "
            f"{len(loop_vars)} loop_vars")
    for i, (v, o) in enumerate(zip(loop_vars, bb.outs)):
        if _aval(v) != _aval(o):
            raise ValueError(
                f"while_loop carry {i} changed signature: init {_aval(v)} "
                f"vs body output {_aval(o)} — XLA loop carries are "
                f"shape-static")

    free_vids = sorted(set(cb.free) | set(bb.free))
    free_tensors = [cb.free[v] if v in cb.free else bb.free[v]
                    for v in free_vids]
    params, seen = [], set()
    for p in cb.params + bb.params:
        if id(p) not in seen:
            seen.add(id(p))
            params.append(p)
    n_loop, n_free = len(loop_vars), len(free_vids)
    param_ids = [id(p) for p in params]
    cb_sub, bb_sub = cb.sub, bb.sub
    cb_ph, bb_ph = cb.ph_vids, bb.ph_vids
    cb_out, bb_out = cb.out_vids, bb.out_vids

    def pure_fn(*vals):
        init = tuple(vals[:n_loop])
        free_env = dict(zip(free_vids, vals[n_loop:n_loop + n_free]))
        param_env = dict(zip(param_ids, vals[n_loop + n_free:]))

        def c_run(carry):
            env = dict(free_env)
            env.update(zip(cb_ph, carry))
            (res,) = cb_sub.replay_env(env, cb_out, param_env)
            return jnp.asarray(res).reshape(())

        def b_run(carry):
            env = dict(free_env)
            env.update(zip(bb_ph, carry))
            return tuple(bb_sub.replay_env(env, bb_out, param_env))

        return jax.lax.while_loop(c_run, b_run, init)

    out_tensors = [Tensor(v.data) for v in loop_vars]
    leaves, treedef = jax.tree_util.tree_flatten(
        ((*loop_vars, *free_tensors, *params), {}),
        is_leaf=lambda x: isinstance(x, Tensor))
    prog.record("while", pure_fn, treedef, leaves, out_tensors)
    return out_tensors
