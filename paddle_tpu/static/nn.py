"""paddle.static.nn namespace parity (control flow + static layer fns).

The reference exposes cond/while_loop/case/switch_case under
python/paddle/static/nn/control_flow.py; the layer builders (fc, conv2d,
...) are the same nn.functional ops captured by program_guard, so they
need no static-specific variants here.
"""
from __future__ import annotations

from .control_flow import cond, while_loop

__all__ = ["cond", "while_loop", "case", "switch_case"]


def case(pred_fn_pairs, default=None, name=None):
    """Chained cond (reference static/nn/control_flow.py case): the first
    true predicate's fn runs; lowered as nested cond ops.  With
    ``default=None`` the LAST pair's fn is the default (reference
    semantics — every path must produce the same outputs under XLA)."""
    pairs = list(pred_fn_pairs)
    if not pairs:
        raise ValueError("case() needs at least one (pred, fn) pair")
    if default is None:
        _, default = pairs.pop()
        if not pairs:
            return default()

    def build(rest):
        (pred, fn), tail = rest[0], rest[1:]
        if not tail:
            return cond(pred, fn, default)
        return cond(pred, fn, lambda: build(tail))

    return build(pairs)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Integer-indexed dispatch (reference switch_case), lowered via
    nested cond on equality tests.  With ``default=None`` the fn of the
    max index is the default (reference semantics)."""
    from .. import ops

    items = (sorted(branch_fns.items()) if isinstance(branch_fns, dict)
             else list(enumerate(branch_fns)))
    if default is None:
        _, default = items.pop()          # max index (items sorted)
        if not items:
            return default()
    pairs = [(ops.equal(branch_index, int(i)), fn) for i, fn in items]
    return case(pairs, default=default)
