"""Program passes (reference parity: framework/ir/pass.h:53 ``Pass`` +
REGISTER_PASS:317, and the python pass registry distributed/passes/
pass_base.py).

Passes rewrite the Program's op list in place.  The reference ships ~150
graph-fusion passes whose work XLA does automatically here; the ones that
remain MEANINGFUL on TPU are program-level rewrites ahead of the
compiler: dead-op elimination (shrinks the traced program) and bf16
auto-cast (the static-AMP pass, contrib/mixed_precision analog).
"""
from __future__ import annotations

import functools

import numpy as np

import jax.numpy as jnp

from .program import OpDesc, Program, _ParamRef, _VarRef

__all__ = ["Pass", "register_pass", "new_pass", "PASS_REGISTRY",
           "DeadCodeEliminationPass", "AmpBf16Pass"]

PASS_REGISTRY: dict[str, type] = {}

# ops whose replay must DRAW, not replay a baked sample: folding or
# merging them changes semantics (the reference constant_folding_pass
# excludes nondeterministic ops the same way)
RANDOM_OPS = {"rand", "randn", "randint", "randperm", "uniform", "normal",
              "gaussian", "bernoulli", "multinomial", "exponential",
              "poisson", "dropout", "rrelu", "shuffle"}


def register_pass(name):
    def deco(cls):
        cls.pass_name = name
        PASS_REGISTRY[name] = cls
        return cls

    return deco


def new_pass(name, attrs=None):
    cls = PASS_REGISTRY[name]
    return cls(**(attrs or {}))


class Pass:
    pass_name = "base"

    def apply(self, program: Program, fetch_vids=()):
        raise NotImplementedError


@register_pass("dead_code_elimination")
class DeadCodeEliminationPass(Pass):
    """Drop ops whose outputs reach neither a fetch target nor another
    live op (prune.cc / graph DCE analog)."""

    def apply(self, program, fetch_vids=()):
        live = set(fetch_vids)
        kept = []
        for op in reversed(program.ops):
            if any(v in live for v in op.out_vids):
                kept.append(op)
                live.update(op.input_vids())
        removed = len(program.ops) - len(kept)
        program.ops = list(reversed(kept))
        program.version += 1
        return removed


@register_pass("amp_bf16")
class AmpBf16Pass(Pass):
    """Static AMP: wrap matmul-class ops so their floating inputs compute
    in bf16 and the result returns in the original dtype (the reference's
    fluid/contrib/mixed_precision program rewrite; white-list style)."""

    WHITE_LIST = {"matmul", "mm", "bmm", "einsum", "conv2d", "linear"}

    def __init__(self, white_list=None):
        self.white = set(white_list) if white_list else set(self.WHITE_LIST)

    def apply(self, program, fetch_vids=()):
        count = 0
        for op in program.ops:
            if op.name not in self.white:
                continue
            op.pure_fn = self._wrap(op.pure_fn)
            count += 1
        program.version += 1
        return count

    @staticmethod
    def _wrap(fn):
        if getattr(fn, "_amp_bf16_wrapped", False):
            return fn

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            def cast_in(x):
                if hasattr(x, "dtype") and x.dtype == jnp.float32:
                    return x.astype(jnp.bfloat16)
                return x

            import jax

            out_dtype = None
            for a in jax.tree_util.tree_leaves(args):
                if hasattr(a, "dtype") and a.dtype == jnp.float32:
                    out_dtype = jnp.float32
            args = jax.tree_util.tree_map(cast_in, args)
            out = fn(*args, **kwargs)
            if out_dtype is not None:
                out = jax.tree_util.tree_map(
                    lambda o: o.astype(out_dtype)
                    if hasattr(o, "dtype") and o.dtype == jnp.bfloat16
                    else o, out)
            return out

        wrapped._amp_bf16_wrapped = True
        return wrapped


@register_pass("constant_folding")
class ConstantFoldingPass(Pass):
    """Evaluate ops whose inputs are all compile-time constants and
    splice the result in as a literal (reference:
    framework/ir/constant_folding_pass.cc).  Plain captured tensors are
    constants; trainable Parameters fold only when ``fold_params``
    (inference mode) — training reads them live."""

    def __init__(self, fold_params=False):
        self.fold_params = fold_params

    def apply(self, program, fetch_vids=()):
        import jax

        from ..core.tensor import Parameter

        folded_vals = {}
        count = 0
        new_ops = []
        for op in program.ops:
            def resolve(leaf):
                if isinstance(leaf, _VarRef):
                    return folded_vals.get(leaf.vid, leaf)
                if isinstance(leaf, _ParamRef):
                    if self.fold_params or not isinstance(leaf.tensor,
                                                          Parameter):
                        return leaf.tensor.data
                    return leaf
                return leaf

            res = [resolve(l) for l in op.leaves]
            if (op.name in RANDOM_OPS
                    or any(isinstance(l, (_VarRef, _ParamRef))
                           for l in res)):
                # random ops never fold but STILL need their folded
                # inputs spliced in (their producers may be removed);
                # partially-constant ops likewise keep resolved leaves
                op.leaves = res
                new_ops.append(op)
                continue
            args, kwargs = jax.tree_util.tree_unflatten(op.treedef, res)
            out = op.pure_fn(*args, **kwargs)
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            for vid, o in zip(op.out_vids, outs):
                folded_vals[vid] = o
            count += 1
        # fetched vids that folded away need a passthrough const op
        for vid in fetch_vids:
            if vid in folded_vals:
                val = folded_vals[vid]
                leaves, treedef = jax.tree_util.tree_flatten(((), {}))
                new_ops.append(OpDesc("const", lambda v=val: v,
                                      treedef, leaves, [vid]))
        program.ops = new_ops
        program.version += 1
        return count


@register_pass("common_subexpression_elimination")
class CSEPass(Pass):
    """Merge ops with identical (name, pure_fn, resolved inputs) —
    framework/ir CSE analog.  VarRefs compare by vid, params by tensor
    identity, array literals by raw bytes (repr elides large arrays and
    would merge distinct constants), other literals by value repr."""

    def apply(self, program, fetch_vids=()):
        seen = {}          # key -> out_vids of the first occurrence
        alias = {}         # dropped vid -> kept vid
        kept = []
        count = 0
        for op in program.ops:

            def leaf_key(leaf):
                if isinstance(leaf, _VarRef):
                    return ("v", alias.get(leaf.vid, leaf.vid))
                if isinstance(leaf, _ParamRef):
                    return ("p", id(leaf.tensor))
                if hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
                    arr = np.asarray(leaf)
                    return ("a", str(arr.dtype), arr.shape,
                            arr.tobytes())
                return ("l", repr(leaf))

            # fresh leaf list: OpDesc.leaves objects are SHARED with the
            # source program across clone() — in-place vid rewrites would
            # leak into it (and past its version counter)
            op.leaves = [
                _VarRef(alias[l.vid])
                if isinstance(l, _VarRef) and l.vid in alias else l
                for l in op.leaves]
            key = (op.name, id(op.pure_fn), op.treedef,
                   tuple(leaf_key(l) for l in op.leaves))
            prev = seen.get(key)
            if (prev is not None and len(prev) == len(op.out_vids)
                    and op.name not in RANDOM_OPS
                    and not any(v in fetch_vids for v in op.out_vids)):
                # fetch targets keep their producer: replay fetches the
                # vid directly, aliases are invisible to it
                for dropped, kept_vid in zip(op.out_vids, prev):
                    alias[dropped] = kept_vid
                count += 1
                continue
            seen[key] = op.out_vids
            kept.append(op)
        program.ops = kept
        program.version += 1
        return count
