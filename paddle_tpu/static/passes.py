"""Program passes (reference parity: framework/ir/pass.h:53 ``Pass`` +
REGISTER_PASS:317, and the python pass registry distributed/passes/
pass_base.py).

Passes rewrite the Program's op list in place.  The reference ships ~150
graph-fusion passes whose work XLA does automatically here; the ones that
remain MEANINGFUL on TPU are program-level rewrites ahead of the
compiler: dead-op elimination (shrinks the traced program) and bf16
auto-cast (the static-AMP pass, contrib/mixed_precision analog).
"""
from __future__ import annotations

import functools

import jax.numpy as jnp

from .program import OpDesc, Program, _ParamRef, _VarRef

__all__ = ["Pass", "register_pass", "new_pass", "PASS_REGISTRY",
           "DeadCodeEliminationPass", "AmpBf16Pass"]

PASS_REGISTRY: dict[str, type] = {}


def register_pass(name):
    def deco(cls):
        cls.pass_name = name
        PASS_REGISTRY[name] = cls
        return cls

    return deco


def new_pass(name, attrs=None):
    cls = PASS_REGISTRY[name]
    return cls(**(attrs or {}))


class Pass:
    pass_name = "base"

    def apply(self, program: Program, fetch_vids=()):
        raise NotImplementedError


@register_pass("dead_code_elimination")
class DeadCodeEliminationPass(Pass):
    """Drop ops whose outputs reach neither a fetch target nor another
    live op (prune.cc / graph DCE analog)."""

    def apply(self, program, fetch_vids=()):
        live = set(fetch_vids)
        kept = []
        for op in reversed(program.ops):
            if any(v in live for v in op.out_vids):
                kept.append(op)
                live.update(op.input_vids())
        removed = len(program.ops) - len(kept)
        program.ops = list(reversed(kept))
        program.version += 1
        return removed


@register_pass("amp_bf16")
class AmpBf16Pass(Pass):
    """Static AMP: wrap matmul-class ops so their floating inputs compute
    in bf16 and the result returns in the original dtype (the reference's
    fluid/contrib/mixed_precision program rewrite; white-list style)."""

    WHITE_LIST = {"matmul", "mm", "bmm", "einsum", "conv2d", "linear"}

    def __init__(self, white_list=None):
        self.white = set(white_list) if white_list else set(self.WHITE_LIST)

    def apply(self, program, fetch_vids=()):
        count = 0
        for op in program.ops:
            if op.name not in self.white:
                continue
            op.pure_fn = self._wrap(op.pure_fn)
            count += 1
        program.version += 1
        return count

    @staticmethod
    def _wrap(fn):
        if getattr(fn, "_amp_bf16_wrapped", False):
            return fn

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            def cast_in(x):
                if hasattr(x, "dtype") and x.dtype == jnp.float32:
                    return x.astype(jnp.bfloat16)
                return x

            import jax

            out_dtype = None
            for a in jax.tree_util.tree_leaves(args):
                if hasattr(a, "dtype") and a.dtype == jnp.float32:
                    out_dtype = jnp.float32
            args = jax.tree_util.tree_map(cast_in, args)
            out = fn(*args, **kwargs)
            if out_dtype is not None:
                out = jax.tree_util.tree_map(
                    lambda o: o.astype(out_dtype)
                    if hasattr(o, "dtype") and o.dtype == jnp.bfloat16
                    else o, out)
            return out

        wrapped._amp_bf16_wrapped = True
        return wrapped
