"""Program IR — the rewritable op-list program (reference parity:
framework.proto ProgramDesc/OpDesc/VarDesc + python Program/Block
(fluid/framework.py:4777,3199) + append_op capture).

TPU-native design: the reference builds programs by appending OpDescs
from python and compiles them with C++ executors.  Here the SAME eager op
calls are captured: while a Program is being built (program_guard), every
dispatched op ALSO appends an OpDesc recording its pure function, its
input variables (placeholders or earlier outputs), and its captured
parameters (live Tensor references, so optimizer updates are visible at
run time).  The op list is a real IR: passes rewrite it
(static/passes.py), Executor replays it under jax.jit.
"""
from __future__ import annotations

import contextlib
import itertools
import weakref

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["Program", "program_guard", "default_main_program",
           "current_program", "data", "OpDesc", "VarDesc"]

_counter = itertools.count()

# tensors defined inside a control-flow sub-block, so an enclosing block
# can refuse them loudly instead of baking a stale trace-time value
# (reference: scope hierarchy makes inner-block vars invisible outside).
# Keyed by id() — Tensor.__eq__ is elementwise, so hash-based weak maps
# would recurse into dispatch; a finalizer purges entries on GC.
_block_owner: dict = {}


def _register_block_tensor(t, prog):
    tid = id(t)
    # both refs weak: a strong Program ref here would keep the Program's
    # _keepalive (and thus t) alive forever, so the finalizer never fires
    _block_owner[tid] = (weakref.ref(t), weakref.ref(prog))
    weakref.finalize(t, _block_owner.pop, tid, None)


def _owner_of(t):
    entry = _block_owner.get(id(t))
    if entry is not None and entry[0]() is t:
        return entry[1]()
    return None


def _root(p):
    while p.parent is not None:
        p = p.parent
    return p


class VarDesc:
    __slots__ = ("vid", "name", "shape", "dtype", "is_feed")

    def __init__(self, vid, name, shape, dtype, is_feed=False):
        self.vid = vid
        self.name = name
        self.shape = tuple(shape)
        self.dtype = dtype
        self.is_feed = is_feed

    def __repr__(self):
        kind = "feed" if self.is_feed else "var"
        return f"{kind} {self.name}: {self.dtype}{list(self.shape)}"


class _VarRef:
    """Marker replacing a Tensor leaf in an OpDesc's arg structure."""

    __slots__ = ("vid",)

    def __init__(self, vid):
        self.vid = vid


class _ParamRef:
    """A leaf bound to a LIVE Tensor (layer parameter): its value is read
    at run time, so training updates flow into subsequent runs."""

    __slots__ = ("tensor",)

    def __init__(self, tensor):
        self.tensor = tensor


class OpDesc:
    __slots__ = ("name", "pure_fn", "treedef", "leaves", "out_vids")

    def __init__(self, name, pure_fn, treedef, leaves, out_vids):
        self.name = name
        self.pure_fn = pure_fn
        self.treedef = treedef
        self.leaves = leaves          # list of _VarRef/_ParamRef/literal
        self.out_vids = out_vids

    def input_vids(self):
        return [l.vid for l in self.leaves if isinstance(l, _VarRef)]

    def __repr__(self):
        ins = ",".join(f"v{v}" for v in self.input_vids())
        outs = ",".join(f"v{v}" for v in self.out_vids)
        return f"{self.name}({ins}) -> {outs}"


class Program:
    """An ordered op list over named variables (ProgramDesc analog).

    ``parent`` links a control-flow sub-block to its enclosing program
    (the reference's BlockDesc.parent_idx): vids are globally unique, so
    a sub-block op may reference an outer variable directly — such free
    variables are tracked in ``free_vars`` and become inputs of the
    enclosing cond/while op (conditional_block_op's input list)."""

    def __init__(self, parent=None):
        self.parent = parent
        self.vars: dict[int, VarDesc] = {}
        self.ops: list[OpDesc] = []
        self._tensor_vids: dict[int, int] = {}   # id(Tensor) -> vid
        self._feed_names: dict[str, int] = {}
        self.free_vars: dict[int, Tensor] = {}   # outer vid -> Tensor
        # strong refs to every tensor we keyed by id(): CPython reuses
        # addresses after GC, which would miswire lookup()
        self._keepalive: list = []
        # bumped on every mutation (record / pass application) so the
        # Executor's compile cache can detect in-place rewrites
        self.version = 0

    # ---------------------------------------------------------- building
    def add_feed(self, name, shape, dtype):
        vid = next(_counter)
        self.vars[vid] = VarDesc(vid, name, shape, dtype, is_feed=True)
        self._feed_names[name] = vid
        concrete = [1 if (d is None or d < 0) else d for d in shape]
        t = Tensor(jnp.zeros(concrete, dtype))
        self._tensor_vids[id(t)] = vid
        self._keepalive.append(t)
        return t

    def add_local_like(self, tensor, name="ph"):
        """A block-local placeholder (while-loop carry var)."""
        vid = next(_counter)
        self.vars[vid] = VarDesc(vid, f"{name}_{vid}", tensor.data.shape,
                                 str(tensor.data.dtype))
        t = Tensor(jnp.zeros_like(tensor.data))
        self._tensor_vids[id(t)] = vid
        self._keepalive.append(t)
        if self.parent is not None:
            _register_block_tensor(t, self)
        return t, vid

    def lookup(self, tensor):
        return self._tensor_vids.get(id(tensor))

    def lookup_chain(self, tensor):
        """Resolve through enclosing blocks; marks outer hits as free."""
        vid = self.lookup(tensor)
        if vid is not None:
            return vid
        outer = self.parent
        while outer is not None:
            vid = outer.lookup(tensor)
            if vid is not None:
                self.free_vars[vid] = tensor
                return vid
            outer = outer.parent
        return None

    def record(self, op_name, pure_fn, treedef, leaves, out_tensors):
        enc = []
        for leaf in leaves:
            if isinstance(leaf, Tensor):
                vid = (self.lookup_chain(leaf) if self.parent is not None
                       else self.lookup(leaf))
                if vid is None:
                    owner = _owner_of(leaf)
                    if owner is not None and _root(owner) is _root(self):
                        raise RuntimeError(
                            "a tensor defined inside a control-flow "
                            "sub-block (cond/while) was used outside it; "
                            "inner-block variables are invisible to the "
                            "enclosing block — return the value from the "
                            "branch/body instead")
                enc.append(_VarRef(vid) if vid is not None
                           else _ParamRef(leaf))
            else:
                enc.append(leaf)
        out_vids = []
        for t in out_tensors:
            vid = next(_counter)
            self.vars[vid] = VarDesc(vid, f"tmp_{vid}", t.data.shape,
                                     str(t.data.dtype))
            self._tensor_vids[id(t)] = vid
            self._keepalive.append(t)
            if self.parent is not None:
                _register_block_tensor(t, self)
            out_vids.append(vid)
        self.ops.append(OpDesc(op_name, pure_fn, treedef, enc, out_vids))
        self.version += 1

    # ----------------------------------------------------------- replay
    def param_refs(self):
        """The live parameter Tensors this program reads, in first-use
        order — the Executor passes their CURRENT values as jit inputs so
        training updates are visible across runs (scope semantics)."""
        refs, seen = [], set()
        for op in self.ops:
            for leaf in op.leaves:
                if isinstance(leaf, _ParamRef) and id(leaf.tensor) not in seen:
                    seen.add(id(leaf.tensor))
                    refs.append(leaf.tensor)
        return refs

    def replay(self, feed_arrays, fetch_vids, param_arrays=None):
        """Execute the op list: feed name→array, return fetch values.
        Pure in the feeds + params (jit-friendly when param_arrays are
        passed as traced inputs)."""
        values = {self._feed_names[k]: jnp.asarray(v)
                  for k, v in feed_arrays.items()}
        param_env = None
        if param_arrays is not None:
            param_env = {id(t): param_arrays[i]
                         for i, t in enumerate(self.param_refs())}
        return self.replay_env(values, fetch_vids, param_env)

    def replay_env(self, values, fetch_vids, param_env=None):
        """Replay over a prepopulated {vid: array} environment — also the
        entry control-flow blocks use, seeded with their free/carry vars
        (the reference's scope-hierarchy lookup in conditional_block)."""

        def resolve(leaf):
            if isinstance(leaf, _VarRef):
                return values[leaf.vid]
            if isinstance(leaf, _ParamRef):
                if param_env is not None and id(leaf.tensor) in param_env:
                    return param_env[id(leaf.tensor)]
                return leaf.tensor.data
            return leaf

        for op in self.ops:
            full = [resolve(l) for l in op.leaves]
            args, kwargs = jax.tree_util.tree_unflatten(op.treedef, full)
            out = op.pure_fn(*args, **kwargs)
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            for vid, o in zip(op.out_vids, outs):
                values[vid] = o
        return [values[v] for v in fetch_vids]

    # ------------------------------------------------------------- intro
    def to_string(self):
        lines = [f"program ({len(self.ops)} ops, {len(self.vars)} vars)"]
        for v in self.vars.values():
            if v.is_feed:
                lines.append(f"  {v!r}")
        for op in self.ops:
            lines.append(f"  {op!r}")
        return "\n".join(lines)

    __str__ = to_string

    def clone(self, for_test=False):
        p = Program(parent=self.parent)
        p.free_vars = dict(self.free_vars)
        p.vars = dict(self.vars)
        # deep-copy OpDescs: passes mutate pure_fn in place and must not
        # leak their rewrites into the original program
        p.ops = [OpDesc(o.name, o.pure_fn, o.treedef, list(o.leaves),
                        list(o.out_vids)) for o in self.ops]
        p._tensor_vids = dict(self._tensor_vids)
        p._feed_names = dict(self._feed_names)
        p._keepalive = list(self._keepalive)
        return p


_default_main = Program()
_stack: list[Program] = []


def default_main_program():
    return _default_main


def current_program():
    return _stack[-1] if _stack else None


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """Capture subsequently dispatched ops into ``main_program``."""
    _stack.append(main_program)
    try:
        yield
    finally:
        _stack.pop()


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed variable (reference: paddle.static.data)."""
    prog = current_program() or _default_main
    return prog.add_feed(name, shape, dtype)


def maybe_record(op_name, pure_fn, treedef, leaves, out_tensors):
    """Dispatch hook: called by core.dispatch on every eager op."""
    prog = current_program()
    if prog is not None:
        prog.record(op_name, pure_fn, treedef, leaves, out_tensors)
