"""paddle.text parity: Viterbi decoding + NLP datasets.

Reference: python/paddle/text/viterbi_decode.py (:24 viterbi_decode,
:91 ViterbiDecoder over the viterbi_decode op) and text/datasets/
(Imdb, Imikolov, UCIHousing, Conll05, Movielens, WMT14/16 — downloaders
+ parsers).

TPU-native notes: the Viterbi forward pass is a lax.scan whose body is
one [B,T,T] max-reduction (MXU/VPU-friendly, no Python loop over time);
backtracking scans the argmax trail in reverse.  Datasets parse LOCAL
files only — this environment has no egress, so download-on-miss raises
with instructions instead of silently fetching.
"""
from __future__ import annotations

import gzip
import os
import tarfile

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..io.dataset import Dataset
from ..nn.layer.layers import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder",
           "Imdb", "Imikolov", "UCIHousing", "Conll05", "Movielens",
           "WMT14", "WMT16"]


# ----------------------------------------------------------------- viterbi


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decode (reference viterbi_decode.py:24).

    potentials: [B, T, N] unary emission scores; transition_params:
    [N, N] (with BOS=N-2/EOS=N-1 rows when include_bos_eos_tag);
    lengths: [B] int actual lengths.  Returns (scores [B], paths [B, T]).
    """
    emis = potentials.data if isinstance(potentials, Tensor) \
        else jnp.asarray(potentials)
    trans = transition_params.data if isinstance(transition_params, Tensor) \
        else jnp.asarray(transition_params)
    B, T, N = emis.shape
    if lengths is None:
        lens = jnp.full((B,), T, jnp.int32)
    else:
        lens = (lengths.data if isinstance(lengths, Tensor)
                else jnp.asarray(lengths)).astype(jnp.int32)

    if include_bos_eos_tag:
        # row N-2 = BOS->tag, col N-1 = tag->EOS (reference convention)
        start = trans[N - 2]
        stop = trans[:, N - 1]
    else:
        start = jnp.zeros((N,), emis.dtype)
        stop = jnp.zeros((N,), emis.dtype)

    alpha0 = emis[:, 0] + start                      # [B, N]

    def step(alpha, t):
        # scores[b, i, j] = alpha[b, i] + trans[i, j]
        scores = alpha[:, :, None] + trans[None]
        best_prev = jnp.argmax(scores, axis=1)       # [B, N]
        best_score = jnp.max(scores, axis=1) + emis[:, t]
        live = (t < lens)[:, None]
        alpha = jnp.where(live, best_score, alpha)
        # padded steps get IDENTITY backpointers: backtracking through
        # them carries the final tag unchanged to position len-1
        bp = jnp.where(live, best_prev, jnp.arange(N)[None, :])
        return alpha, bp

    alpha, backptrs = jax.lax.scan(
        step, alpha0, jnp.arange(1, T))              # backptrs [T-1, B, N]

    final = alpha + stop[None]
    scores = jnp.max(final, axis=-1)
    last_tag = jnp.argmax(final, axis=-1)            # [B]

    def back(tag, bp):
        prev = bp[jnp.arange(B), tag]
        return prev, prev

    _, rev = jax.lax.scan(back, last_tag, backptrs, reverse=True)
    paths = jnp.concatenate([jnp.swapaxes(rev, 0, 1),
                             last_tag[:, None]], axis=1)   # [B, T]
    # int32 on purpose: jax truncates int64 without x64 mode (and warns
    # per call); tag indices never need 64 bits
    paths = paths.astype(jnp.int32)
    if isinstance(potentials, Tensor):
        return Tensor(scores), Tensor(paths)
    return scores, paths


class ViterbiDecoder(Layer):
    """Layer form (viterbi_decode.py:91): holds the transition matrix."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(jnp.asarray(transitions))
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


# ----------------------------------------------------------------- datasets


def _need_file(path, what, url_hint):
    if path is None or not os.path.exists(path):
        raise FileNotFoundError(
            f"{what}: no local data file at {path!r}. This environment "
            f"has no network egress — download {url_hint} on a connected "
            f"machine and pass data_file=<local path>.")
    return path


class UCIHousing(Dataset):
    """Boston-housing regression (reference uci_housing.py): whitespace
    table of 13 features + 1 target, normalized per feature."""

    N_FEATURES = 13

    def __init__(self, data_file=None, mode="train"):
        path = _need_file(data_file, "UCIHousing", "the UCI housing.data")
        raw = np.loadtxt(path, dtype=np.float32)
        raw = raw.reshape(-1, self.N_FEATURES + 1)
        feats = raw[:, :-1]
        mn, mx = feats.min(0), feats.max(0)
        feats = (feats - mn) / np.maximum(mx - mn, 1e-8)
        n_train = int(len(raw) * 0.8)
        if mode == "train":
            self.x, self.y = feats[:n_train], raw[:n_train, -1:]
        else:
            self.x, self.y = feats[n_train:], raw[n_train:, -1:]

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class Imikolov(Dataset):
    """PTB n-gram dataset (reference imikolov.py): builds a vocab from a
    local PTB-format text file and yields n-grams."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=1):
        path = _need_file(data_file, "Imikolov", "PTB simple-examples")
        with open(path) as f:
            lines = [l.strip().split() for l in f if l.strip()]
        freq = {}
        for words in lines:
            for w in words:
                freq[w] = freq.get(w, 0) + 1
        vocab = sorted(w for w, c in freq.items() if c >= min_word_freq)
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.data = []
        for words in lines:
            ids = [self.word_idx.get(w, unk) for w in words]
            if data_type.upper() == "NGRAM":
                for j in range(len(ids) - window_size + 1):
                    self.data.append(
                        np.asarray(ids[j:j + window_size], np.int64))
            else:                                # SEQ
                self.data.append(np.asarray(ids, np.int64))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i]


class Imdb(Dataset):
    """IMDB sentiment (reference imdb.py): parses the aclImdb tar from a
    local path; yields (token-id array, 0/1 label)."""

    def __init__(self, data_file=None, mode="train", cutoff=150):
        path = _need_file(data_file, "Imdb", "aclImdb_v1.tar.gz")
        pat = f"aclImdb/{mode}"
        texts, labels = [], []
        opener = tarfile.open
        with opener(path) as tf:
            for m in tf.getmembers():
                if not m.isfile() or not m.name.startswith(pat):
                    continue
                if "/pos/" in m.name:
                    lab = 0
                elif "/neg/" in m.name:
                    lab = 1
                else:
                    continue
                body = tf.extractfile(m).read().decode("utf-8", "ignore")
                texts.append(body.lower().split())
                labels.append(lab)
        freq = {}
        for t in texts:
            for w in t:
                freq[w] = freq.get(w, 0) + 1
        # reference imdb.py build_dict: cutoff is a MINIMUM frequency —
        # keep every word appearing more than cutoff times
        vocab = [w for w, c in sorted(freq.items(),
                                      key=lambda kv: (-kv[1], kv[0]))
                 if c > cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.asarray([self.word_idx.get(w, unk) for w in t],
                                np.int64) for t in texts]
        self.labels = np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, i):
        return self.docs[i], self.labels[i]


class _LocalOnly(Dataset):
    """Stub base for corpora whose full parsers need the real archives:
    constructing without a local file raises the no-egress error."""

    URL_HINT = ""

    def __init__(self, data_file=None, mode="train"):
        _need_file(data_file, type(self).__name__, self.URL_HINT)
        raise NotImplementedError(
            f"{type(self).__name__}: parser lands with the archive "
            f"present; file found but this build parses Imdb/Imikolov/"
            f"UCIHousing only. Open an issue with the archive layout.")


class Conll05(_LocalOnly):
    URL_HINT = "conll05st-tests.tar.gz"


class Movielens(_LocalOnly):
    URL_HINT = "ml-1m.zip"


class WMT14(_LocalOnly):
    URL_HINT = "wmt14.tgz"


class WMT16(_LocalOnly):
    URL_HINT = "wmt16.tar.gz"
