"""paddle.text parity: Viterbi decoding + NLP datasets.

Reference: python/paddle/text/viterbi_decode.py (:24 viterbi_decode,
:91 ViterbiDecoder over the viterbi_decode op) and text/datasets/
(Imdb, Imikolov, UCIHousing, Conll05, Movielens, WMT14/16 — downloaders
+ parsers).

TPU-native notes: the Viterbi forward pass is a lax.scan whose body is
one [B,T,T] max-reduction (MXU/VPU-friendly, no Python loop over time);
backtracking scans the argmax trail in reverse.  Datasets parse LOCAL
files only — this environment has no egress, so download-on-miss raises
with instructions instead of silently fetching.
"""
from __future__ import annotations

import gzip
import os
import tarfile

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..io.dataset import Dataset
from ..nn.layer.layers import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder",
           "Imdb", "Imikolov", "UCIHousing", "Conll05", "Movielens",
           "WMT14", "WMT16"]


# ----------------------------------------------------------------- viterbi


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decode (reference viterbi_decode.py:24).

    potentials: [B, T, N] unary emission scores; transition_params:
    [N, N] (with BOS=N-2/EOS=N-1 rows when include_bos_eos_tag);
    lengths: [B] int actual lengths.  Returns (scores [B], paths [B, T]).
    """
    emis = potentials.data if isinstance(potentials, Tensor) \
        else jnp.asarray(potentials)
    trans = transition_params.data if isinstance(transition_params, Tensor) \
        else jnp.asarray(transition_params)
    B, T, N = emis.shape
    if lengths is None:
        lens = jnp.full((B,), T, jnp.int32)
    else:
        lens = (lengths.data if isinstance(lengths, Tensor)
                else jnp.asarray(lengths)).astype(jnp.int32)

    if include_bos_eos_tag:
        # row N-2 = BOS->tag, col N-1 = tag->EOS (reference convention)
        start = trans[N - 2]
        stop = trans[:, N - 1]
    else:
        start = jnp.zeros((N,), emis.dtype)
        stop = jnp.zeros((N,), emis.dtype)

    alpha0 = emis[:, 0] + start                      # [B, N]

    def step(alpha, t):
        # scores[b, i, j] = alpha[b, i] + trans[i, j]
        scores = alpha[:, :, None] + trans[None]
        best_prev = jnp.argmax(scores, axis=1)       # [B, N]
        best_score = jnp.max(scores, axis=1) + emis[:, t]
        live = (t < lens)[:, None]
        alpha = jnp.where(live, best_score, alpha)
        # padded steps get IDENTITY backpointers: backtracking through
        # them carries the final tag unchanged to position len-1
        bp = jnp.where(live, best_prev, jnp.arange(N)[None, :])
        return alpha, bp

    alpha, backptrs = jax.lax.scan(
        step, alpha0, jnp.arange(1, T))              # backptrs [T-1, B, N]

    final = alpha + stop[None]
    scores = jnp.max(final, axis=-1)
    last_tag = jnp.argmax(final, axis=-1)            # [B]

    def back(tag, bp):
        prev = bp[jnp.arange(B), tag]
        return prev, prev

    _, rev = jax.lax.scan(back, last_tag, backptrs, reverse=True)
    paths = jnp.concatenate([jnp.swapaxes(rev, 0, 1),
                             last_tag[:, None]], axis=1)   # [B, T]
    # int32 on purpose: jax truncates int64 without x64 mode (and warns
    # per call); tag indices never need 64 bits
    paths = paths.astype(jnp.int32)
    if isinstance(potentials, Tensor):
        return Tensor(scores), Tensor(paths)
    return scores, paths


class ViterbiDecoder(Layer):
    """Layer form (viterbi_decode.py:91): holds the transition matrix."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(jnp.asarray(transitions))
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


# ----------------------------------------------------------------- datasets


def _need_file(path, what, url_hint):
    if path is None or not os.path.exists(path):
        raise FileNotFoundError(
            f"{what}: no local data file at {path!r}. This environment "
            f"has no network egress — download {url_hint} on a connected "
            f"machine and pass data_file=<local path>.")
    return path


class UCIHousing(Dataset):
    """Boston-housing regression (reference uci_housing.py): whitespace
    table of 13 features + 1 target, normalized per feature."""

    N_FEATURES = 13

    def __init__(self, data_file=None, mode="train"):
        path = _need_file(data_file, "UCIHousing", "the UCI housing.data")
        raw = np.loadtxt(path, dtype=np.float32)
        raw = raw.reshape(-1, self.N_FEATURES + 1)
        feats = raw[:, :-1]
        mn, mx = feats.min(0), feats.max(0)
        feats = (feats - mn) / np.maximum(mx - mn, 1e-8)
        n_train = int(len(raw) * 0.8)
        if mode == "train":
            self.x, self.y = feats[:n_train], raw[:n_train, -1:]
        else:
            self.x, self.y = feats[n_train:], raw[n_train:, -1:]

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class Imikolov(Dataset):
    """PTB n-gram dataset (reference imikolov.py): builds a vocab from a
    local PTB-format text file and yields n-grams."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=1):
        path = _need_file(data_file, "Imikolov", "PTB simple-examples")
        with open(path) as f:
            lines = [l.strip().split() for l in f if l.strip()]
        freq = {}
        for words in lines:
            for w in words:
                freq[w] = freq.get(w, 0) + 1
        vocab = sorted(w for w, c in freq.items() if c >= min_word_freq)
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.data = []
        for words in lines:
            ids = [self.word_idx.get(w, unk) for w in words]
            if data_type.upper() == "NGRAM":
                for j in range(len(ids) - window_size + 1):
                    self.data.append(
                        np.asarray(ids[j:j + window_size], np.int64))
            else:                                # SEQ
                self.data.append(np.asarray(ids, np.int64))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i]


class Imdb(Dataset):
    """IMDB sentiment (reference imdb.py): parses the aclImdb tar from a
    local path; yields (token-id array, 0/1 label)."""

    def __init__(self, data_file=None, mode="train", cutoff=150):
        path = _need_file(data_file, "Imdb", "aclImdb_v1.tar.gz")
        pat = f"aclImdb/{mode}"
        texts, labels = [], []
        opener = tarfile.open
        with opener(path) as tf:
            for m in tf.getmembers():
                if not m.isfile() or not m.name.startswith(pat):
                    continue
                if "/pos/" in m.name:
                    lab = 0
                elif "/neg/" in m.name:
                    lab = 1
                else:
                    continue
                body = tf.extractfile(m).read().decode("utf-8", "ignore")
                texts.append(body.lower().split())
                labels.append(lab)
        freq = {}
        for t in texts:
            for w in t:
                freq[w] = freq.get(w, 0) + 1
        # reference imdb.py build_dict: cutoff is a MINIMUM frequency —
        # keep every word appearing more than cutoff times
        vocab = [w for w, c in sorted(freq.items(),
                                      key=lambda kv: (-kv[1], kv[0]))
                 if c > cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.asarray([self.word_idx.get(w, unk) for w in t],
                                np.int64) for t in texts]
        self.labels = np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, i):
        return self.docs[i], self.labels[i]


class Conll05(Dataset):
    """CoNLL-2005 SRL test set (reference text/datasets/conll05.py:43):
    parses ``conll05st-tests.tar.gz`` (words + props column files, one
    predicate frame per props column) into per-frame samples.

    Each item: ``(words, predicate, bio_labels)`` — the sentence tokens,
    the frame's predicate word, and per-token B-/I-/O tags decoded from
    the CoNLL bracket spans.  Pass ``word_dict``/``label_dict`` to get
    int32 id arrays instead of strings."""

    WORDS_MEMBER = "conll05st-release/test.wsj/words/test.wsj.words.gz"
    PROPS_MEMBER = "conll05st-release/test.wsj/props/test.wsj.props.gz"

    def __init__(self, data_file=None, mode="test", word_dict=None,
                 label_dict=None):
        path = _need_file(data_file, "Conll05", "conll05st-tests.tar.gz")
        self.word_dict, self.label_dict = word_dict, label_dict
        self.samples = []
        with tarfile.open(path) as tf:
            words_gz = tf.extractfile(self.WORDS_MEMBER)
            props_gz = tf.extractfile(self.PROPS_MEMBER)
            with gzip.GzipFile(fileobj=words_gz) as wf, \
                    gzip.GzipFile(fileobj=props_gz) as pf:
                self._parse(wf, pf)

    def _parse(self, words_file, props_file):
        sent, cols = [], []
        for wline, pline in zip(words_file, props_file):
            word = wline.decode("utf-8").strip()
            props = pline.decode("utf-8").split()
            if not props:                        # blank line = sentence end
                self._emit(sent, cols)
                sent, cols = [], []
                continue
            sent.append(word)
            cols.append(props)
        if sent:
            self._emit(sent, cols)

    def _emit(self, sent, cols):
        if not cols:
            return
        n_frames = len(cols[0]) - 1              # col 0 = target verbs
        for i, row in enumerate(cols):
            if len(row) != len(cols[0]):
                raise ValueError(
                    f"Conll05: malformed props row for token {i} "
                    f"({sent[i]!r}) in sentence starting {sent[0]!r}: "
                    f"expected {len(cols[0])} columns (from the first "
                    f"row), got {len(row)}")
        verbs = [row[0] for row in cols if row[0] != "-"]
        for f in range(n_frames):
            spans = [row[1 + f] for row in cols]
            self.samples.append((list(sent), verbs[f] if f < len(verbs)
                                 else "-", self._bio(spans)))

    @staticmethod
    def _bio(spans):
        """CoNLL bracket spans -> BIO tags: '(TAG*' opens, '*)' closes,
        bare '*' continues the open span (or O outside one)."""
        out, tag = [], None
        for s in spans:
            opens = s.startswith("(")
            closes = s.endswith(")")
            if opens:
                tag = s[1:s.index("*")]
                out.append("B-" + tag)
            elif tag is not None:
                out.append("I-" + tag)
            else:
                out.append("O")
            if closes:
                tag = None
        return out

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        words, pred, labels = self.samples[i]
        if self.word_dict is not None:
            unk = self.word_dict.get("<unk>", 0)
            words = np.asarray([self.word_dict.get(w, unk) for w in words],
                               np.int32)
            pred = np.asarray([self.word_dict.get(pred, unk)], np.int32)
        if self.label_dict is not None:
            labels = np.asarray([self.label_dict[l] for l in labels],
                                np.int32)
        return words, pred, labels


class Movielens(Dataset):
    """MovieLens ml-1m ratings (reference text/datasets/movielens.py):
    parses ``ml-1m.zip`` (movies/users/ratings ``::``-separated, latin-1)
    into per-rating samples.

    Each item: (user_id, gender01, age_bucket, job_id, movie_id,
    category_ids, title_word_ids, rating) as int/float arrays — the
    reference's UserInfo.value() + MovieInfo.value() + [rating] feature
    tuple.  The train/test split hashes the rating line (deterministic;
    the reference consumes global numpy RNG per line, which is not
    reproducible across runs)."""

    AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]

    def __init__(self, data_file=None, mode="train", test_ratio=0.1):
        import re
        import zipfile
        import zlib

        path = _need_file(data_file, "Movielens", "ml-1m.zip")
        self.mode = mode
        pat = re.compile(r"^(.*)\((\d+)\)\s*$")
        movies, users = {}, {}
        title_words, categories = set(), set()
        with zipfile.ZipFile(path) as z:
            with z.open("ml-1m/movies.dat") as f:
                for line in f:
                    mid, title, cats = \
                        line.decode("latin-1").strip().split("::")
                    cats = cats.split("|")
                    m = pat.match(title)
                    title = m.group(1).strip() if m else title
                    movies[int(mid)] = (title, cats)
                    title_words.update(w.lower() for w in title.split())
                    categories.update(cats)
            self.title_dict = {w: i for i, w in
                               enumerate(sorted(title_words))}
            self.cat_dict = {c: i for i, c in enumerate(sorted(categories))}
            with z.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job, _zip = \
                        line.decode("latin-1").strip().split("::")
                    users[int(uid)] = (0 if gender == "M" else 1,
                                       self.AGE_TABLE.index(int(age))
                                       if int(age) in self.AGE_TABLE else 0,
                                       int(job))
            self.data = []
            with z.open("ml-1m/ratings.dat") as f:
                for line in f:
                    text = line.decode("latin-1").strip()
                    uid, mid, rating, _ts = text.split("::")
                    # crc32, not hash(): str hashing is salted per process
                    h = zlib.crc32(text.encode("latin-1")) % 1000
                    is_test = h < int(test_ratio * 1000)
                    if is_test != (mode == "test"):
                        continue
                    uid, mid = int(uid), int(mid)
                    title, cats = movies[mid]
                    g, a, j = users[uid]
                    self.data.append((
                        np.asarray([uid], np.int64),
                        np.asarray([g], np.int64),
                        np.asarray([a], np.int64),
                        np.asarray([j], np.int64),
                        np.asarray([mid], np.int64),
                        np.asarray([self.cat_dict[c] for c in cats],
                                   np.int64),
                        np.asarray([self.title_dict[w.lower()]
                                    for w in title.split()], np.int64),
                        np.asarray([float(rating) * 2 - 5.0], np.float32),
                    ))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i]


class WMT14(Dataset):
    """WMT'14 EN-FR preprocessed archive (reference
    text/datasets/wmt14.py): a tar with ``src.dict``/``trg.dict`` and
    ``{mode}/{mode}`` files of tab-separated parallel sentences.

    Each item: (src_ids, trg_ids, trg_ids_next) with <s>/<e> wrapping on
    the source and <s>-prefixed / <e>-suffixed target pair, UNK id 2,
    sequences longer than 80 tokens dropped in train mode."""

    START, END, UNK_IDX = "<s>", "<e>", 2
    MAX_LEN = 80

    def __init__(self, data_file=None, mode="train", dict_size=30000):
        path = _need_file(data_file, type(self).__name__,
                          "wmt14.tgz (preprocessed)")
        assert dict_size > 0
        self.mode = mode
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(path) as tf:
            self.src_dict = self._dict(tf, "src.dict", dict_size)
            self.trg_dict = self._dict(tf, "trg.dict", dict_size)
            member = f"{mode}/{mode}"
            names = [m.name for m in tf if m.name.endswith(member)]
            for name in names:
                for raw in tf.extractfile(name):
                    parts = raw.decode("utf-8").strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src = [self.src_dict.get(w, self.UNK_IDX)
                           for w in ([self.START] + parts[0].split()
                                     + [self.END])]
                    trg = parts[1].split()
                    # NOTE the asymmetric cap is reference-faithful:
                    # wmt14.py:149-160 measures the WRAPPED source
                    # ([<s>] + words + [<e>]) but the raw target
                    if mode == "train" and (len(src) > self.MAX_LEN or
                                            len(trg) > self.MAX_LEN):
                        continue
                    t = [self.trg_dict.get(w, self.UNK_IDX) for w in trg]
                    self.src_ids.append(src)
                    self.trg_ids.append([self.trg_dict[self.START]] + t)
                    self.trg_ids_next.append(t + [self.trg_dict[self.END]])

    @staticmethod
    def _dict(tf, suffix, size):
        names = [m.name for m in tf if m.name.endswith(suffix)]
        assert len(names) == 1, f"expected one *{suffix} in the archive"
        out = {}
        for i, line in enumerate(tf.extractfile(names[0])):
            if i >= size:
                break
            out[line.decode("utf-8").strip()] = i
        return out

    def __len__(self):
        return len(self.src_ids)

    def __getitem__(self, i):
        return (np.asarray(self.src_ids[i], np.int64),
                np.asarray(self.trg_ids[i], np.int64),
                np.asarray(self.trg_ids_next[i], np.int64))


class WMT16(WMT14):
    """WMT'16 EN-DE (reference text/datasets/wmt16.py): same archive
    protocol as WMT14 (src/trg dicts + {mode}/{mode} parallel files);
    the reference additionally rebuilds dicts from the corpus when
    missing — here the archive's dicts are required."""

