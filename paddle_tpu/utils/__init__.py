"""Utilities: logging (log_util), runtime counters (monitor)."""
from . import log_util, monitor
from .log_util import get_logger, logger, set_log_level, vlog
from .monitor import (StatRegistry, device_memory_stats, stat_add, stat_get,
                      stat_reset)

__all__ = ["log_util", "monitor", "logger", "get_logger", "set_log_level",
           "vlog", "StatRegistry", "stat_add", "stat_get", "stat_reset",
           "device_memory_stats"]
