"""Per-rank logging (parity: python/paddle/distributed/fleet/utils/
log_util.py — a logger whose records carry the trainer rank, so multi-
process logs interleave attributably; plus VLOG-style verbosity via the
framework flag system).
"""
from __future__ import annotations

import logging
import os
import sys

__all__ = ["logger", "get_logger", "set_log_level", "vlog"]


def _rank():
    return os.environ.get("PADDLE_TRAINER_ID", "0")


class _RankFilter(logging.Filter):
    def filter(self, record):
        record.rank = _rank()
        return True


def get_logger(name="paddle_tpu", level=None, fmt=None):
    log = logging.getLogger(name)
    if not log.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(
            fmt or "%(asctime)s [rank %(rank)s] %(levelname)s "
                   "%(name)s: %(message)s"))
        h.addFilter(_RankFilter())
        log.addHandler(h)
        log.propagate = False
    if level is not None:
        log.setLevel(level)
    elif log.level == logging.NOTSET:
        log.setLevel(os.environ.get("PADDLE_LOG_LEVEL", "INFO"))
    return log


logger = get_logger()


def set_log_level(level):
    logger.setLevel(level)


def vlog(verbosity, msg, *args):
    """glog VLOG(n) analog: emits when FLAGS_v >= verbosity (env
    GLOG_v / FLAGS_v, reference platform/init.cc InitGLOG)."""
    try:
        from ..core.flags import flag

        v = flag("v") or 0
    except Exception:
        v = 0
    v = max(int(v), int(os.environ.get("GLOG_v", "0")))
    if v >= verbosity:
        logger.info(msg, *args)
