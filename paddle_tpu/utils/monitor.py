"""Runtime counters (parity: paddle/fluid/platform/monitor.h:77
``StatRegistry`` + the STAT_ADD/STAT_GET macros, plus memory/stats.h's
per-stat peaks).

Host-side registry: device-side memory stats come from
jax.local_devices()[0].memory_stats() and are surfaced through the same
API (the reference's DEVICE_MEMORY_STAT_* reads the allocator; ours reads
PJRT's).
"""
from __future__ import annotations

import threading

__all__ = ["StatRegistry", "stat_add", "stat_get", "stat_reset",
           "device_memory_stats"]


class _Stat:
    __slots__ = ("value", "peak")

    def __init__(self):
        self.value = 0
        self.peak = 0


class StatRegistry:
    """Named integer counters with peaks (monitor.h:77)."""

    def __init__(self):
        self._stats: dict[str, _Stat] = {}
        self._lock = threading.Lock()

    def add(self, name, delta):
        with self._lock:
            s = self._stats.setdefault(name, _Stat())
            s.value += int(delta)
            s.peak = max(s.peak, s.value)
            return s.value

    def get(self, name):
        with self._lock:
            s = self._stats.get(name)
            return s.value if s else 0

    def peak(self, name):
        with self._lock:
            s = self._stats.get(name)
            return s.peak if s else 0

    def reset(self, name=None):
        with self._lock:
            if name is None:
                self._stats.clear()
            else:
                self._stats.pop(name, None)

    def stats(self):
        with self._lock:
            return {k: (s.value, s.peak) for k, s in self._stats.items()}


_default = StatRegistry()


def stat_add(name, delta=1):
    """STAT_ADD analog on the process-wide registry."""
    return _default.add(name, delta)


def stat_get(name):
    return _default.get(name)


def stat_reset(name=None):
    _default.reset(name)


def device_memory_stats(device=None):
    """PJRT memory stats for a device (allocator stats analog); {} when
    the backend does not report them."""
    import jax

    d = device if device is not None else jax.local_devices()[0]
    try:
        return d.memory_stats() or {}
    except Exception:
        return {}
