"""Runtime counters (parity: paddle/fluid/platform/monitor.h:77
``StatRegistry`` + the STAT_ADD/STAT_GET macros, plus memory/stats.h's
per-stat peaks).

Host-side registry: device-side memory stats come from
jax.local_devices()[0].memory_stats() and are surfaced through the same
API (the reference's DEVICE_MEMORY_STAT_* reads the allocator; ours reads
PJRT's).
"""
from __future__ import annotations

import threading

__all__ = ["StatRegistry", "stat_add", "stat_get", "stat_reset",
           "bridge_to_metrics", "device_memory_stats"]


class _Stat:
    __slots__ = ("value", "peak")

    def __init__(self):
        self.value = 0
        self.peak = 0


class StatRegistry:
    """Named integer counters with peaks (monitor.h:77)."""

    def __init__(self):
        self._stats: dict[str, _Stat] = {}      # guarded-by: self._lock
        self._lock = threading.Lock()

    def add(self, name, delta):
        with self._lock:
            s = self._stats.setdefault(name, _Stat())
            s.value += int(delta)
            s.peak = max(s.peak, s.value)
            return s.value

    def get(self, name):
        with self._lock:
            s = self._stats.get(name)
            return s.value if s else 0

    def peak(self, name):
        with self._lock:
            s = self._stats.get(name)
            return s.peak if s else 0

    def reset(self, name=None):
        with self._lock:
            if name is None:
                self._stats.clear()
            else:
                self._stats.pop(name, None)

    def stats(self):
        with self._lock:
            return {k: (s.value, s.peak) for k, s in self._stats.items()}


_default = StatRegistry()


def stat_add(name, delta=1):
    """STAT_ADD analog on the process-wide registry."""
    return _default.add(name, delta)


def stat_get(name):
    return _default.get(name)


def stat_reset(name=None):
    _default.reset(name)


def bridge_to_metrics(stat_registry=None, metrics_registry=None):
    """One-way bridge: surface a :class:`StatRegistry`'s counters/peaks
    in the observability :class:`MetricsRegistry` as the
    ``runtime_stat{name=...}`` gauge family.

    The sync runs *on scrape* (a registry collector fires at the top of
    every ``snapshot()``/``expose_prometheus()``), so legacy
    ``stat_add`` call sites keep their lock-cheap integer registry but
    their stats still appear on ``/metrics`` and in bench JSON instead
    of living in a parallel, invisible registry.  Peaks ride the gauge's
    own peak tracking (the peak is replayed before the current value,
    so ``runtime_stat_peak`` is never below the stat's true peak).

    Defaults bridge the process-wide pair; the default bridge is
    installed once at import of this module.  Returns the collector so
    callers wiring explicit registries can ``remove_collector`` it."""
    from ..observability.metrics import default_registry

    sr = stat_registry if stat_registry is not None else _default
    mr = metrics_registry if metrics_registry is not None \
        else default_registry()

    def _collect():
        stats = sr.stats()
        if not stats:
            return
        g = mr.gauge("runtime_stat",
                     "legacy StatRegistry counters (bridged on scrape)",
                     labelnames=("name",))
        for name, (value, peak) in stats.items():
            child = g.labels(name=name)
            child.set(peak)
            child.set(value)

    return mr.add_collector(_collect)


_BRIDGED = False


def _install_default_bridge():
    global _BRIDGED
    if not _BRIDGED:
        _BRIDGED = True
        bridge_to_metrics()


_install_default_bridge()


def device_memory_stats(device=None):
    """PJRT memory stats for a device (allocator stats analog); {} when
    the backend does not report them."""
    import jax

    d = device if device is not None else jax.local_devices()[0]
    try:
        return d.memory_stats() or {}
    except Exception:
        return {}
