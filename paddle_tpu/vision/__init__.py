"""paddle_tpu.vision (parity: python/paddle/vision)."""
from . import datasets, models, ops, transforms  # noqa: F401
