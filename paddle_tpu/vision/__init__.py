"""paddle_tpu.vision (parity: python/paddle/vision)."""
from . import datasets, detection_ops, models, ops, transforms  # noqa: F401
