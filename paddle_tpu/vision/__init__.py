"""paddle_tpu.vision (parity: python/paddle/vision)."""
from . import datasets, models, transforms  # noqa: F401
