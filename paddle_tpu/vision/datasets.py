"""Vision datasets (parity: python/paddle/vision/datasets).

Synthetic-capable: when download is unavailable (zero-egress TPU pods), each
dataset can generate deterministic fake data with the real shapes/dtypes so
training pipelines remain runnable end-to-end.
"""
from __future__ import annotations

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeImageNet", "ImageFolder"]


class _SyntheticImageDataset(Dataset):
    NUM_CLASSES = 10
    SHAPE = (1, 28, 28)
    SIZE = 1024

    def __init__(self, mode="train", transform=None, size=None, seed=0,
                 backend="cv2", download=True):
        self.mode = mode
        self.transform = transform
        self.size = size or self.SIZE
        rng = np.random.RandomState(seed if mode == "train" else seed + 1)
        self.images = rng.rand(self.size, *self.SHAPE).astype(np.float32)
        self.labels = rng.randint(0, self.NUM_CLASSES, self.size).astype(np.int64)

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.size


class MNIST(_SyntheticImageDataset):
    NUM_CLASSES = 10
    SHAPE = (1, 28, 28)


class FashionMNIST(_SyntheticImageDataset):
    NUM_CLASSES = 10
    SHAPE = (1, 28, 28)


class Cifar10(_SyntheticImageDataset):
    NUM_CLASSES = 10
    SHAPE = (3, 32, 32)


class Cifar100(_SyntheticImageDataset):
    NUM_CLASSES = 100
    SHAPE = (3, 32, 32)


class FakeImageNet(_SyntheticImageDataset):
    NUM_CLASSES = 1000
    SHAPE = (3, 224, 224)
    SIZE = 256


class ImageFolder(Dataset):
    def __init__(self, root, loader=None, transform=None):
        import os

        self.samples = []
        self.transform = transform
        self.loader = loader or _default_loader
        for dirpath, _, files in os.walk(root):
            for f in sorted(files):
                if f.lower().endswith((".png", ".jpg", ".jpeg", ".bmp", ".npy")):
                    self.samples.append(os.path.join(dirpath, f))

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return (img,)

    def __len__(self):
        return len(self.samples)


def _default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    from PIL import Image

    return np.asarray(Image.open(path), dtype=np.float32) / 255.0
