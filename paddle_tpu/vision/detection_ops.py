"""Detection operators — the op subset that expresses the PP-YOLOE /
SSD-class configs (VERDICT r4 item 7).

Reference parity:
  roi_align  — paddle/fluid/operators/detection/roi_align_op.cc (bilinear
               pooling over RoI bins, `aligned` half-pixel semantics)
  yolo_box   — paddle/fluid/operators/detection/yolo_box_op.cc (decode
               YOLO head predictions into boxes + scores)
  prior_box  — paddle/fluid/operators/detection/prior_box_op.cc (SSD
               anchor generation)
  box_coder  — paddle/fluid/operators/detection/box_coder_op.cc (SSD
               encode/decode between priors and targets)

TPU-first notes: every op is a static-shape vectorized jnp program (no
per-RoI Python loops — sampling grids are materialized as gathers the
XLA TPU backend tiles well).  ``roi_align``'s adaptive sampling
(sampling_ratio <= 0) is data-dependent in the reference (ceil of the
per-RoI bin size); under jit that is unshapeable, so it maps to the
fixed 2-sample grid the detection configs overwhelmingly use — pass an
explicit sampling_ratio to override.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["roi_align", "yolo_box", "prior_box", "box_coder"]


def _arr(x, dtype=jnp.float32):
    a = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    return a.astype(dtype) if dtype is not None else a


def _bilinear(feat, y, x):
    """Bilinear sample feat [C, H, W] at (y, x) grids [...]; out-of-range
    samples contribute 0 (reference roi_align boundary handling).  Bounds
    are inclusive at both ends (reference roi_align_op.cc zeroes only
    y < -1 or y > height): a sample exactly at the image edge (y == H) is
    clamped onto the last row and sampled, not dropped."""
    C, H, W = feat.shape
    valid = (y >= -1.0) & (y <= H) & (x >= -1.0) & (x <= W)
    y = jnp.clip(y, 0.0, H - 1)
    x = jnp.clip(x, 0.0, W - 1)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    ly, lx = y - y0, x - x0
    hy, hx = 1.0 - ly, 1.0 - lx
    # gather 4 corners: [C, ...grid]
    g = lambda yy, xx: feat[:, yy, xx]
    val = (g(y0, x0) * (hy * hx) + g(y0, x1) * (hy * lx)
           + g(y1, x0) * (ly * hx) + g(y1, x1) * (ly * lx))
    return val * valid.astype(feat.dtype)


def roi_align(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoI Align (reference roi_align_op.cc; torchvision semantics).

    x: [N, C, H, W]; boxes: [R, 4] (x1, y1, x2, y2) in input-image
    coords; boxes_num: [N] rois per image (defaults to all on image 0).
    Returns [R, C, output_size, output_size].
    """
    was_tensor = isinstance(boxes, Tensor)
    x = _arr(x)
    boxes = _arr(boxes)
    N, C, H, W = x.shape
    R = boxes.shape[0]
    if isinstance(output_size, int):
        ph = pw = output_size
    else:
        ph, pw = output_size
    s = int(sampling_ratio) if sampling_ratio and sampling_ratio > 0 else 2

    if boxes_num is None:
        batch_idx = jnp.zeros((R,), jnp.int32)
    else:
        bn = _arr(boxes_num, jnp.int32)
        batch_idx = jnp.repeat(jnp.arange(N, dtype=jnp.int32), bn,
                               total_repeat_length=R)

    offset = 0.5 if aligned else 0.0
    bx = boxes * spatial_scale - offset
    x1, y1, x2, y2 = bx[:, 0], bx[:, 1], bx[:, 2], bx[:, 3]
    rw = x2 - x1
    rh = y2 - y1
    if not aligned:                      # legacy: clamp to >= 1
        rw = jnp.maximum(rw, 1.0)
        rh = jnp.maximum(rh, 1.0)
    bin_h = rh / ph
    bin_w = rw / pw

    # sample grid per roi: [R, ph, s] x [R, pw, s]
    iy = (jnp.arange(ph)[None, :, None]
          + (jnp.arange(s)[None, None, :] + 0.5) / s)
    ix = (jnp.arange(pw)[None, :, None]
          + (jnp.arange(s)[None, None, :] + 0.5) / s)
    ys = y1[:, None, None] + iy * bin_h[:, None, None]   # [R, ph, s]
    xs = x1[:, None, None] + ix * bin_w[:, None, None]   # [R, pw, s]
    # full grid [R, ph, pw, s, s]
    yg = ys[:, :, None, :, None]
    xg = xs[:, None, :, None, :]
    yg = jnp.broadcast_to(yg, (R, ph, pw, s, s))
    xg = jnp.broadcast_to(xg, (R, ph, pw, s, s))

    def one_roi(b, yg_r, xg_r):
        feat = x[b]                                       # [C, H, W]
        v = _bilinear(feat, yg_r, xg_r)                   # [C, ph, pw, s, s]
        return v.mean(axis=(-1, -2))

    out = jax.vmap(one_roi)(batch_idx, yg, xg)            # [R, C, ph, pw]
    return Tensor(out) if was_tensor else out


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.005,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode a YOLO detection head (reference yolo_box_op.cc).

    x: [N, A*(5+class_num), H, W]; img_size: [N, 2] (h, w); anchors:
    flat list [a0w, a0h, a1w, ...].  Returns (boxes [N, A*H*W, 4] in
    (x1, y1, x2, y2), scores [N, A*H*W, class_num]); predictions with
    objectness below conf_thresh are zeroed (the op's LoD-free contract).
    """
    x = _arr(x)
    img_size = _arr(img_size)
    N, _, H, W = x.shape
    A = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(A, 2)   # (w, h)
    pred = x.reshape(N, A, 5 + class_num, H, W)

    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
    cx = (jax.nn.sigmoid(pred[:, :, 0]) * alpha + beta + gx) / W
    cy = (jax.nn.sigmoid(pred[:, :, 1]) * alpha + beta + gy) / H
    tw = jnp.exp(jnp.clip(pred[:, :, 2], -10.0, 10.0))
    th = jnp.exp(jnp.clip(pred[:, :, 3], -10.0, 10.0))
    input_h = downsample_ratio * H
    input_w = downsample_ratio * W
    bw = tw * an[None, :, 0, None, None] / input_w
    bh = th * an[None, :, 1, None, None] / input_h

    obj = jax.nn.sigmoid(pred[:, :, 4])
    cls = jax.nn.sigmoid(pred[:, :, 5:])                  # [N,A,cls,H,W]
    keep = (obj >= conf_thresh).astype(x.dtype)
    scores = (cls * (obj * keep)[:, :, None]).transpose(0, 1, 3, 4, 2)

    imh = img_size[:, 0][:, None, None, None]
    imw = img_size[:, 1][:, None, None, None]
    x1 = (cx - bw / 2) * imw
    y1 = (cy - bh / 2) * imh
    x2 = (cx + bw / 2) * imw
    y2 = (cy + bh / 2) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0.0, imw - 1)
        y1 = jnp.clip(y1, 0.0, imh - 1)
        x2 = jnp.clip(x2, 0.0, imw - 1)
        y2 = jnp.clip(y2, 0.0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], -1) * keep[..., None]
    return (boxes.reshape(N, A * H * W, 4),
            scores.reshape(N, A * H * W, class_num))


def prior_box(input_hw, image_hw, min_sizes, max_sizes=None,
              aspect_ratios=(1.0,), variances=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False):
    """SSD prior (anchor) boxes (reference prior_box_op.cc).

    input_hw: (H, W) of the feature map; image_hw: (h, w) of the image.
    Returns (boxes [H, W, P, 4] normalized (x1, y1, x2, y2),
    variances [H, W, P, 4]).
    """
    H, W = int(input_hw[0]), int(input_hw[1])
    img_h, img_w = float(image_hw[0]), float(image_hw[1])
    step_h = steps[0] or img_h / H
    step_w = steps[1] or img_w / W

    # expand aspect ratios like the reference (1.0 first, optional flip)
    ars = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - a) < 1e-6 for a in ars):
            continue
        ars.append(float(ar))
        if flip:
            ars.append(1.0 / float(ar))

    whs = []       # per-prior (half_w, half_h) in pixels
    for k, ms in enumerate(min_sizes):
        ms = float(ms)
        if min_max_aspect_ratios_order:
            whs.append((ms / 2, ms / 2))
            if max_sizes:
                big = np.sqrt(ms * float(max_sizes[k]))
                whs.append((big / 2, big / 2))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar) / 2, ms / np.sqrt(ar) / 2))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar) / 2, ms / np.sqrt(ar) / 2))
            if max_sizes:
                big = np.sqrt(ms * float(max_sizes[k]))
                whs.append((big / 2, big / 2))
    wh = jnp.asarray(whs, jnp.float32)                   # [P, 2]
    P = wh.shape[0]

    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cxg = jnp.broadcast_to(cx[None, :, None], (H, W, P))
    cyg = jnp.broadcast_to(cy[:, None, None], (H, W, P))
    hw_ = jnp.broadcast_to(wh[None, None, :, 0], (H, W, P))
    hh_ = jnp.broadcast_to(wh[None, None, :, 1], (H, W, P))
    boxes = jnp.stack([(cxg - hw_) / img_w, (cyg - hh_) / img_h,
                       (cxg + hw_) / img_w, (cyg + hh_) / img_h], -1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (H, W, P, 4))
    return boxes, var


def box_coder(prior_box_, target_box, prior_box_var=None,
              code_type="encode_center_size", box_normalized=True,
              axis=0):
    """SSD box encode/decode (reference box_coder_op.cc).

    encode: target [T, 4] against priors [P, 4] -> [T, P, 4] deltas.
    decode: deltas [T, P, 4] (or [T, 4] with axis semantics collapsed to
    per-row priors when shapes match) -> absolute boxes.
    prior_box_var: [P, 4] or a 4-vector; None = unit variance.
    """
    pb = _arr(prior_box_)
    tb = _arr(target_box)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2
    if prior_box_var is None:
        var = jnp.ones((pb.shape[0], 4), jnp.float32)
    else:
        v = _arr(prior_box_var)
        var = (jnp.broadcast_to(v, (pb.shape[0], 4)) if v.ndim == 1
               else v)

    if code_type in ("encode_center_size", "encode"):
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw / 2
        tcy = tb[:, 1] + th / 2
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        dh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out = jnp.stack([dx, dy, dw, dh], -1) / var[None]
        return out
    if code_type in ("decode_center_size", "decode"):
        if axis not in (0, 1):
            raise ValueError(f"box_coder: axis must be 0 or 1, got {axis}")
        if tb.ndim == 2:
            tb = tb[:, None, :]
        if axis == 0:
            # priors align with dim 0 of the deltas (reference
            # box_coder_op.cc axis semantics): run in the axis=1 layout
            # and transpose both ways
            tb = tb.transpose(1, 0, 2)
        d = tb * var[None]
        cx = d[..., 0] * pw[None, :] + pcx[None, :]
        cy = d[..., 1] * ph[None, :] + pcy[None, :]
        w = jnp.exp(d[..., 2]) * pw[None, :]
        h = jnp.exp(d[..., 3]) * ph[None, :]
        out = jnp.stack([cx - w / 2, cy - h / 2,
                         cx + w / 2 - norm, cy + h / 2 - norm], -1)
        return out.transpose(1, 0, 2) if axis == 0 else out
    raise ValueError(f"box_coder: unknown code_type {code_type!r}")
