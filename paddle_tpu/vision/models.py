"""Vision models (parity: python/paddle/vision/models — ResNet/VGG/LeNet/MobileNet)."""
from __future__ import annotations

from .. import nn

__all__ = ["LeNet", "ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "VGG", "vgg16", "MobileNetV1",
           "AlexNet", "alexnet", "SqueezeNet", "squeezenet1_1",
           "ShuffleNetV2", "shufflenet_v2_x1_0", "DenseNet", "densenet121"]


class LeNet(nn.Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        self.fc = nn.Sequential(
            nn.Linear(400, 120), nn.Linear(120, 84), nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        x = x.flatten(1)
        return self.fc(x)


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(planes)
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(inplanes, planes, 1, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(planes)
        self.conv2 = nn.Conv2D(planes, planes, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(planes)
        self.conv3 = nn.Conv2D(planes, planes * 4, 1, bias_attr=False)
        self.bn3 = nn.BatchNorm2D(planes * 4)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """ResNet-{18,34,50,101,152} (parity: python/paddle/vision/models/resnet.py)."""

    CFG = {18: (BasicBlock, [2, 2, 2, 2]), 34: (BasicBlock, [3, 4, 6, 3]),
           50: (BottleneckBlock, [3, 4, 6, 3]), 101: (BottleneckBlock, [3, 4, 23, 3]),
           152: (BottleneckBlock, [3, 8, 36, 3])}

    def __init__(self, depth=50, num_classes=1000, with_pool=True):
        super().__init__()
        block, layers = self.CFG[depth]
        self.inplanes = 64
        self.conv1 = nn.Conv2D(3, 64, 7, stride=2, padding=3, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(64)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        self.avgpool = nn.AdaptiveAvgPool2D((1, 1)) if with_pool else None
        self.fc = nn.Linear(512 * block.expansion, num_classes) if num_classes > 0 else None

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False),
                nn.BatchNorm2D(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.avgpool is not None:
            x = self.avgpool(x)
        if self.fc is not None:
            x = self.fc(x.flatten(1))
        return x


def resnet18(pretrained=False, **kwargs):
    return ResNet(18, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return ResNet(34, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return ResNet(50, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return ResNet(101, **kwargs)


class VGG(nn.Layer):
    def __init__(self, cfg=(64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
                 num_classes=1000):
        super().__init__()
        layers = []
        in_c = 3
        for v in cfg:
            if v == "M":
                layers.append(nn.MaxPool2D(2, 2))
            else:
                layers += [nn.Conv2D(in_c, v, 3, padding=1), nn.ReLU()]
                in_c = v
        self.features = nn.Sequential(*layers)
        self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        self.classifier = nn.Sequential(
            nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(0.5),
            nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(0.5),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(x.flatten(1))


def vgg16(pretrained=False, **kwargs):
    return VGG(**kwargs)


class MobileNetV1(nn.Layer):
    def __init__(self, num_classes=1000, scale=1.0):
        super().__init__()

        def dw_sep(inp, outp, stride):
            return nn.Sequential(
                nn.Conv2D(inp, inp, 3, stride=stride, padding=1, groups=inp,
                          bias_attr=False),
                nn.BatchNorm2D(inp), nn.ReLU(),
                nn.Conv2D(inp, outp, 1, bias_attr=False),
                nn.BatchNorm2D(outp), nn.ReLU())

        s = lambda c: int(c * scale)
        self.features = nn.Sequential(
            nn.Conv2D(3, s(32), 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(s(32)), nn.ReLU(),
            dw_sep(s(32), s(64), 1), dw_sep(s(64), s(128), 2),
            dw_sep(s(128), s(128), 1), dw_sep(s(128), s(256), 2),
            dw_sep(s(256), s(256), 1), dw_sep(s(256), s(512), 2),
            *[dw_sep(s(512), s(512), 1) for _ in range(5)],
            dw_sep(s(512), s(1024), 2), dw_sep(s(1024), s(1024), 1))
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.pool(self.features(x))
        return self.fc(x.flatten(1))


class AlexNet(nn.Layer):
    """Parity: vision/models/alexnet.py (the 2012 conv stack)."""

    def __init__(self, num_classes=1000, dropout=0.5):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2))
        self.pool = nn.AdaptiveAvgPool2D((6, 6))
        self.classifier = nn.Sequential(
            nn.Dropout(dropout), nn.Linear(256 * 36, 4096), nn.ReLU(),
            nn.Dropout(dropout), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.pool(self.features(x))
        return self.classifier(x.flatten(1))


def alexnet(pretrained=False, **kwargs):
    return AlexNet(**kwargs)


class _Fire(nn.Layer):
    """SqueezeNet fire module (squeeze 1x1 -> expand 1x1 + 3x3 concat)."""

    def __init__(self, inp, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(inp, squeeze, 1), nn.ReLU())
        self.expand1 = nn.Sequential(nn.Conv2D(squeeze, e1, 1), nn.ReLU())
        self.expand3 = nn.Sequential(
            nn.Conv2D(squeeze, e3, 3, padding=1), nn.ReLU())

    def forward(self, x):
        import paddle_tpu as paddle

        s = self.squeeze(x)
        return paddle.concat([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(nn.Layer):
    """Parity: vision/models/squeezenet.py (version 1.1 topology)."""

    def __init__(self, num_classes=1000, version="1.1"):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
            nn.MaxPool2D(3, 2),
            _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
            nn.MaxPool2D(3, 2),
            _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
            _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D((1, 1)))

    def forward(self, x):
        return self.classifier(self.features(x)).flatten(1)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet(**kwargs)


def _channel_shuffle(x, groups):
    """ShuffleNet channel shuffle: interleave group channels (the
    pointwise-group-conv information-mixing trick)."""
    B, C, H, W = x.shape
    return (x.reshape([B, groups, C // groups, H, W])
             .transpose([0, 2, 1, 3, 4]).reshape([B, C, H, W]))


class _ShuffleUnit(nn.Layer):
    def __init__(self, inp, outp, stride):
        super().__init__()
        self.stride = stride
        branch = outp // 2
        if stride == 2:
            self.branch1 = nn.Sequential(
                nn.Conv2D(inp, inp, 3, stride=2, padding=1, groups=inp,
                          bias_attr=False),
                nn.BatchNorm2D(inp),
                nn.Conv2D(inp, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), nn.ReLU())
            in2 = inp
        else:
            self.branch1 = None
            in2 = inp // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(in2, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), nn.ReLU(),
            nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                      groups=branch, bias_attr=False),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), nn.ReLU())

    def forward(self, x):
        import paddle_tpu as paddle

        if self.stride == 2:
            out = paddle.concat([self.branch1(x), self.branch2(x)], axis=1)
        else:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = paddle.concat([x1, self.branch2(x2)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    """Parity: vision/models/shufflenetv2.py (x1.0)."""

    def __init__(self, num_classes=1000, scale=1.0):
        super().__init__()
        stage_out = {0.5: [48, 96, 192, 1024], 1.0: [116, 232, 464, 1024],
                     1.5: [176, 352, 704, 1024]}[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, 24, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(24), nn.ReLU())
        self.maxpool = nn.MaxPool2D(3, 2, padding=1)
        inp = 24
        stages = []
        for outp, repeats in zip(stage_out[:3], (4, 8, 4)):
            units = [_ShuffleUnit(inp, outp, 2)]
            units += [_ShuffleUnit(outp, outp, 1) for _ in range(repeats - 1)]
            stages.append(nn.Sequential(*units))
            inp = outp
        self.stage2, self.stage3, self.stage4 = stages
        self.conv5 = nn.Sequential(
            nn.Conv2D(inp, stage_out[3], 1, bias_attr=False),
            nn.BatchNorm2D(stage_out[3]), nn.ReLU())
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc = nn.Linear(stage_out[3], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.stage4(self.stage3(self.stage2(x)))
        x = self.pool(self.conv5(x))
        return self.fc(x.flatten(1))


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


class _DenseLayer(nn.Layer):
    def __init__(self, inp, growth, bn_size):
        super().__init__()
        self.fn = nn.Sequential(
            nn.BatchNorm2D(inp), nn.ReLU(),
            nn.Conv2D(inp, bn_size * growth, 1, bias_attr=False),
            nn.BatchNorm2D(bn_size * growth), nn.ReLU(),
            nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                      bias_attr=False))

    def forward(self, x):
        import paddle_tpu as paddle

        return paddle.concat([x, self.fn(x)], axis=1)


class DenseNet(nn.Layer):
    """Parity: vision/models/densenet.py (DenseNet-121 by default)."""

    def __init__(self, num_classes=1000, growth_rate=32,
                 block_config=(6, 12, 24, 16), bn_size=4,
                 num_init_features=64):
        super().__init__()
        feats = [nn.Conv2D(3, num_init_features, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(num_init_features), nn.ReLU(),
                 nn.MaxPool2D(3, 2, padding=1)]
        ch = num_init_features
        for bi, n in enumerate(block_config):
            for _ in range(n):
                feats.append(_DenseLayer(ch, growth_rate, bn_size))
                ch += growth_rate
            if bi != len(block_config) - 1:
                feats += [nn.BatchNorm2D(ch), nn.ReLU(),
                          nn.Conv2D(ch, ch // 2, 1, bias_attr=False),
                          nn.AvgPool2D(2, 2)]
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.pool(self.features(x))
        return self.classifier(x.flatten(1))


def densenet121(pretrained=False, **kwargs):
    return DenseNet(**kwargs)
