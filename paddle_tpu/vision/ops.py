"""Detection ops (reference parity: paddle.vision.ops — box_iou,
nms, generate-anchor helpers over operators/detection/*).

TPU-native notes: NMS is the classic dynamic-shape offender; the
suppression decision here is the O(N^2) masked formulation — one [N, N]
IoU matrix + a fixed-length lax.scan producing a static-shape KEEP MASK
(operators/detection/nms_op.cc walks a sorted list with data-dependent
erases instead).  The final mask→indices compaction is inherently
dynamic-shape and happens at the host boundary; jit callers should
consume the mask form.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["box_iou", "nms", "box_area"]


def _arr(x):
    return x.data if isinstance(x, Tensor) else jnp.asarray(x)


def box_area(boxes):
    b = _arr(boxes)
    area = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return Tensor(area) if isinstance(boxes, Tensor) else area


def box_iou(boxes1, boxes2):
    """Pairwise IoU of [N,4] x [M,4] xyxy boxes -> [N, M]."""
    a, b = _arr(boxes1), _arr(boxes2)
    area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    iou = inter / jnp.maximum(area1[:, None] + area2[None] - inter, 1e-10)
    return Tensor(iou) if isinstance(boxes1, Tensor) else iou


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS (reference vision/ops.py nms): keeps the highest-score
    box, suppresses overlaps above ``iou_threshold``, repeats.

    Static-shape formulation: boxes are processed in score order under a
    lax.scan over N steps; a keep mask accumulates.  Category-aware when
    category_idxs given (boxes of different categories never suppress
    each other).  Returns kept indices sorted by score (Tensor[int64]),
    truncated to top_k when given.
    """
    b = _arr(boxes).astype(jnp.float32)
    n = b.shape[0]
    s = (_arr(scores).astype(jnp.float32) if scores is not None
         else jnp.arange(n, 0, -1, dtype=jnp.float32))
    order = jnp.argsort(-s)
    iou = box_iou(b, b)
    iou = iou if not isinstance(iou, Tensor) else iou.data
    if category_idxs is not None:
        cats = _arr(category_idxs)
        same = cats[:, None] == cats[None, :]
        iou = jnp.where(same, iou, 0.0)

    def step(keep, i):
        idx = order[i]
        # suppressed if any higher-scored KEPT box overlaps too much
        earlier = order[:n]
        rank = jnp.arange(n)
        higher = rank < i
        overlap = iou[idx, earlier] > iou_threshold
        kept_earlier = keep[earlier]
        suppressed = jnp.any(higher & overlap & kept_earlier)
        keep = keep.at[idx].set(~suppressed)
        return keep, None

    keep, _ = jax.lax.scan(step, jnp.zeros((n,), bool), jnp.arange(n))
    kept_sorted = order[keep[order]]          # score order, kept only
    if top_k is not None:
        kept_sorted = kept_sorted[:top_k]
    out = kept_sorted.astype(jnp.int64)
    return Tensor(out) if isinstance(boxes, Tensor) else out
