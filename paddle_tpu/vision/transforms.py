"""Vision transforms (parity: python/paddle/vision/transforms) — numpy-based."""
from __future__ import annotations

import numpy as np

__all__ = ["Compose", "Normalize", "ToTensor", "Resize", "RandomCrop",
           "RandomHorizontalFlip", "CenterCrop", "Transpose"]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean, std, data_format="CHW"):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, dtype=np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, dtype=np.float32)
        if img.ndim == 2:
            img = img[None]
        elif img.ndim == 3 and self.data_format == "CHW" and img.shape[-1] in (1, 3, 4):
            img = np.transpose(img, (2, 0, 1))
        return img


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        import jax

        img = np.asarray(img, dtype=np.float32)
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        if chw:
            c = img.shape[0]
            out = jax.image.resize(img, (c, *self.size), method="bilinear")
        else:
            out = jax.image.resize(img, (*self.size, *img.shape[2:]), method="bilinear")
        return np.asarray(out)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = np.asarray(img)
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        h, w = (img.shape[1], img.shape[2]) if chw else (img.shape[0], img.shape[1])
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        return img[:, i:i + th, j:j + tw] if chw else img[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        img = np.asarray(img)
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        if self.padding:
            p = self.padding
            cfg = [(0, 0), (p, p), (p, p)] if chw else [(p, p), (p, p)] + [(0, 0)] * (img.ndim - 2)
            img = np.pad(img, cfg)
        h, w = (img.shape[1], img.shape[2]) if chw else (img.shape[0], img.shape[1])
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[:, i:i + th, j:j + tw] if chw else img[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            img = np.asarray(img)
            return img[..., ::-1].copy() if img.ndim == 3 else img[:, ::-1].copy()
        return img


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.transpose(np.asarray(img), self.order)
