"""Test config: force an 8-device virtual CPU platform BEFORE jax initializes.

SURVEY.md §4: the reference conformance-tests device backends by re-targeting
one harness per place; here the CPU platform with
--xla_force_host_platform_device_count=8 is the fake multi-chip fixture that
exercises the same shard_map/pjit code paths as a real TPU slice.
"""
import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

# the axon TPU plugin ignores JAX_PLATFORMS env; the config knob wins
jax.config.update("jax_platforms", "cpu")

# jax compat shim (jax.shard_map on experimental-only builds) — must be
# in place before test modules do `from jax import shard_map` at import
# time, which can precede their paddle_tpu import
import paddle_tpu  # noqa: E402,F401

# persistent compilation cache: repeat suite runs skip XLA recompiles
# (reference quarantines slow tests via tools/parallel_UT_rule.py; our
# equivalent is @pytest.mark.slow + this cache)
_CACHE_DIR = os.path.join(os.path.dirname(__file__), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
# persist ONLY the genuinely expensive jitted step programs (hapi
# train/eval steps, the serving unified step) — the entries whose
# mid-process deserialization has years of green runs behind it.
# Eager primitives (most of all the per-call lax.scan of an eager
# gpt_forward: each call builds a fresh body closure -> fresh jaxpr ->
# in-memory cache miss -> disk read) must NOT be persisted: XLA:CPU's
# deserialize_executable reproducibly segfaults on those reads late in
# a long suite in this environment (same machine-feature problem
# family as the AOT-blob note below).  Recompiling them costs
# milliseconds per test; deserializing them kills the whole run.
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

# best-effort: jax._src.compilation_cache.put_executable_and_time is a
# PRIVATE symbol and the "jit_step"/"jit__step" module naming is a jit
# convention — both can move under a jax upgrade.  If either is gone,
# fall back to stock persistent caching (slower repeat runs, nothing
# broken) instead of failing collection.
try:
    from jax._src import compilation_cache as _cc  # noqa: E402

    _orig_put = _cc.put_executable_and_time
except (ImportError, AttributeError):
    _cc = None

if _cc is not None:

    def _selective_put(*args, **kwargs):
        module_name = kwargs.get(
            "module_name", args[1] if len(args) > 1 else None)
        if isinstance(module_name, str) and not module_name.startswith(
                ("jit_step", "jit__step")):
            return None   # eager primitive: never persist (see above)
        # step program — or an unrecognized signature, where the stock
        # behavior is the safe degradation
        return _orig_put(*args, **kwargs)

    _cc.put_executable_and_time = _selective_put
# keep XLA:CPU AOT blobs out of the cache: reloading them trips a
# machine-feature check (prefer-no-scatter/-gather) and spams stderr
jax.config.update("jax_persistent_cache_enable_xla_caches", "none")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed_everything():
    np.random.seed(1234)
    import paddle_tpu

    paddle_tpu.seed(1234)
    yield
