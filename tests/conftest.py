"""Test config: force an 8-device virtual CPU platform BEFORE jax initializes.

SURVEY.md §4: the reference conformance-tests device backends by re-targeting
one harness per place; here the CPU platform with
--xla_force_host_platform_device_count=8 is the fake multi-chip fixture that
exercises the same shard_map/pjit code paths as a real TPU slice.
"""
import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

# the axon TPU plugin ignores JAX_PLATFORMS env; the config knob wins
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed_everything():
    np.random.seed(1234)
    import paddle_tpu

    paddle_tpu.seed(1234)
    yield
