"""1F1B pipeline schedule tests (VERDICT r4 item 2).

Reference parity target: forward_backward_pipeline
(python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:81) —
the memory-bounded schedule whose live activations are O(pp), not
O(num_microbatches).

Covers: the static schedule's invariants (incl. the single-slot mailbox
property the device code depends on — at pp>=3 stages go idle mid-stream
and a naive mailbox gets clobbered with zeros), loss parity 1f1b-vs-gpipe
at pp>=3 where the mailbox actually matters, hybrid parity, and the
activation-memory bound as num_microbatches doubles.
"""
import jax
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from paddle_tpu.distributed.engine import (EngineConfig, HybridEngine,
                                           _1f1b_schedule)
from paddle_tpu.models.gpt import GPTConfig

CFG = GPTConfig(vocab_size=256, max_seq_len=64, hidden=64, num_layers=4,
                num_heads=4, ffn_hidden=128, dtype="float32",
                use_flash=False, remat="nothing")


def _batch(bs=8, seq=32, seed=0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, CFG.vocab_size, (bs, seq)).astype(np.int32)
    labels = np.concatenate([tokens[:, 1:], np.full((bs, 1), -100)],
                            axis=1).astype(np.int32)
    return tokens, labels


def _run(engine, n=3, bs=8):
    params, opt = engine.init(seed=0)
    tokens, labels = _batch(bs)
    losses = []
    for _ in range(n):
        params, opt, loss = engine.step(params, opt, tokens, labels,
                                        lr=1e-3)
        losses.append(float(loss))
    return losses, engine.gather_params(params)


class TestSchedule:
    @pytest.mark.parametrize("pp,M", [(2, 2), (2, 4), (2, 8), (3, 3),
                                      (3, 6), (4, 4), (4, 8), (4, 16),
                                      (8, 8), (8, 32)])
    def test_invariants(self, pp, M):
        f, b = _1f1b_schedule(pp, M)   # raises on mailbox overflow
        T = f.shape[0]
        for i in range(pp):
            assert sorted(m for m in f[:, i] if m >= 0) == list(range(M))
            assert sorted(m for m in b[:, i] if m >= 0) == list(range(M))
        # 1F1B memory bound: stage i holds <= pp - i in flight
        for i in range(pp):
            infl = peak = 0
            for t in range(T):
                infl += int(f[t, i] >= 0) - int(b[t, i] >= 0)
                peak = max(peak, infl)
            assert peak <= pp - i
        # dependencies ride one-tick ppermutes
        tick = lambda a, i, m: int(np.where(a[:, i] == m)[0][0])
        for m in range(M):
            for i in range(1, pp):
                assert tick(f, i, m) > tick(f, i - 1, m)
            for i in range(pp - 1):
                assert tick(b, i, m) > tick(b, i + 1, m)
            # last stage pairs bwd with its own same-tick fwd
            assert tick(b, pp - 1, m) == tick(f, pp - 1, m)

    def test_stages_go_idle_at_pp3(self):
        """The case that distinguishes a sticky mailbox from a naive one:
        at pp>=3 a stage is fwd-idle mid-stream while its successor has
        not yet consumed the last activation."""
        f, _ = _1f1b_schedule(3, 6)
        sent = {int(np.where(f[:, 0] == m)[0][0]): m for m in range(6)}
        consumed = {m: int(np.where(f[:, 1] == m)[0][0]) for m in range(6)}
        assert any(consumed[m] > t + 1 for t, m in sent.items()), \
            "expected a >1-tick mailbox dwell at pp=3"


class TestParity:
    @pytest.fixture(scope="class")
    def baseline(self):
        eng = HybridEngine(CFG, devices=jax.devices()[:1])
        return _run(eng)

    def test_pp4_matches_single_device(self, baseline):
        """pp=4 exercises mid-stream idle ticks (the pp>=3 mailbox case
        pp=2 coincidentally never hits)."""
        eng = HybridEngine(CFG, pp=4, devices=jax.devices()[:4],
                           engine_cfg=EngineConfig(num_microbatches=8))
        losses, params = _run(eng)
        np.testing.assert_allclose(losses, baseline[0], atol=2e-4,
                                   rtol=1e-4)
        for a, b in zip(jax.tree_util.tree_leaves(baseline[1]),
                        jax.tree_util.tree_leaves(params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4)

    def test_pp4_matches_gpipe(self):
        tokens, labels = _batch()

        def run(schedule):
            eng = HybridEngine(CFG, pp=4, devices=jax.devices()[:4],
                               engine_cfg=EngineConfig(
                                   num_microbatches=8,
                                   pipeline_schedule=schedule))
            p, o = eng.init(seed=0)
            out = []
            for _ in range(3):
                p, o, loss = eng.step(p, o, tokens, labels, lr=1e-3)
                out.append(float(loss))
            return out

        np.testing.assert_allclose(run("1f1b"), run("gpipe"), atol=2e-4,
                                   rtol=1e-4)

    def test_pp2_mp2_sharding2_matches(self, baseline):
        eng = HybridEngine(CFG, pp=2, mp=2, sharding=2,
                           engine_cfg=EngineConfig(num_microbatches=4))
        losses, _ = _run(eng)
        np.testing.assert_allclose(losses, baseline[0], atol=2e-4,
                                   rtol=1e-4)

    def test_pp2_zero3_matches(self, baseline):
        eng = HybridEngine(CFG, pp=2, sharding=2, dp=2,
                           engine_cfg=EngineConfig(num_microbatches=2,
                                                   zero_stage=3))
        losses, _ = _run(eng)
        np.testing.assert_allclose(losses, baseline[0], atol=2e-4,
                                   rtol=1e-4)


class TestMemoryBound:
    def _temp_bytes(self, schedule, num_micro, micro_bs=2):
        """Compiled temp bytes for a fixed PER-MICROBATCH size — the
        memory question 1F1B answers is 'can I add microbatches to
        amortize the bubble without growing live activations'."""
        eng = HybridEngine(CFG, pp=2, devices=jax.devices()[:2],
                           engine_cfg=EngineConfig(
                               num_microbatches=num_micro,
                               pipeline_schedule=schedule))
        params, opt = eng.init(seed=0)
        tokens, labels = _batch(micro_bs * num_micro)
        import jax.numpy as jnp

        fn = eng.build_step()
        lowered = fn.lower(params, opt, jnp.asarray(tokens),
                           jnp.asarray(labels),
                           jnp.asarray(1e-3, jnp.float32),
                           jnp.asarray(0, jnp.uint32))
        mem = lowered.compile().memory_analysis()
        # per-device temp bytes (CPU backend reports one analysis)
        return mem.temp_size_in_bytes

    def test_activation_memory_flat_in_num_micro(self):
        """4x the microbatch count (at fixed microbatch size) must NOT
        4x 1F1B's live activations (VERDICT r4 item 2's done-criterion:
        activation memory flat as num_micro doubles).  GPipe's grow
        ~linearly by construction."""
        t4 = self._temp_bytes("1f1b", 4)
        t16 = self._temp_bytes("1f1b", 16)
        g4 = self._temp_bytes("gpipe", 4)
        g16 = self._temp_bytes("gpipe", 16)
        # gpipe grows with microbatches (sanity: the measurement sees
        # the live activations at all)
        assert g16 > 2.0 * g4, (g4, g16)
        # 1f1b stays bounded (small slack for per-micro bookkeeping)
        assert t16 < 1.5 * t4, (t4, t16)
        assert t16 < 0.5 * g16, (t16, g16)
