"""GradScaler dynamic loss-scaling tests: inf/NaN grads must skip the
optimizer step and decay the scale, clean steps must recover scale
growth — the state machine that keeps fp16 training alive had no tier-1
coverage (test_collective_amp.py only checks defaults and the jit
guard, and does not collect on jax builds without ``jax.shard_map``)."""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.amp.grad_scaler import GradScaler
from paddle_tpu.core.tensor import Tensor


def _setup(lr=0.1, **scaler_kw):
    paddle.seed(3)
    lin = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=lr,
                               parameters=lin.parameters())
    return lin, opt, GradScaler(**scaler_kw)


def _params_bytes(lin):
    return [np.asarray(p.data).tobytes() for p in lin.parameters()]


def _set_grads(lin, value):
    for p in lin.parameters():
        p.grad = Tensor(jnp.full(p.data.shape, value, p.data.dtype))


class TestSkipOnNonFinite:
    @pytest.mark.parametrize("bad", [np.inf, -np.inf, np.nan])
    def test_bad_grads_skip_the_optimizer_step(self, bad):
        lin, opt, s = _setup(init_loss_scaling=1024.0,
                             decr_every_n_nan_or_inf=2)
        before = _params_bytes(lin)
        _set_grads(lin, bad)
        s.step(opt)
        # the step was skipped: params are bitwise untouched, and the
        # first bad step alone does not yet decay the scale
        assert _params_bytes(lin) == before
        assert s.get_loss_scaling() == 1024.0

    def test_scale_halves_after_decr_every_bad_steps(self):
        lin, opt, s = _setup(init_loss_scaling=1024.0,
                             decr_every_n_nan_or_inf=2)
        for _ in range(2):
            _set_grads(lin, np.inf)
            s.step(opt)
        assert s.get_loss_scaling() == 512.0
        # the bad-step counter reset: one more bad step doesn't halve
        _set_grads(lin, np.inf)
        s.step(opt)
        assert s.get_loss_scaling() == 512.0

    def test_scale_floors_at_one(self):
        lin, opt, s = _setup(init_loss_scaling=2.0,
                             decr_every_n_nan_or_inf=1)
        for _ in range(4):
            _set_grads(lin, np.nan)
            s.step(opt)
        assert s.get_loss_scaling() == 1.0
        # the finite check still runs at the floor (dynamic scaling on):
        # a clean step applies normally
        before = _params_bytes(lin)
        _set_grads(lin, 0.5)
        s.step(opt)
        assert _params_bytes(lin) != before

    def test_real_overflow_through_minimize(self):
        """End to end through scale()/backward: an inf input poisons
        the grads and minimize() must leave the params untouched."""
        lin, opt, s = _setup(init_loss_scaling=256.0)
        before = _params_bytes(lin)
        loss = s.scale(lin(Tensor(jnp.full((2, 4), jnp.inf))).sum())
        s.minimize(opt, loss)
        assert _params_bytes(lin) == before
        # clean batch afterwards trains normally
        loss = s.scale(lin(Tensor(jnp.ones((2, 4)))).sum())
        s.minimize(opt, loss)
        assert _params_bytes(lin) != before


class TestRecovery:
    def test_scale_regrows_after_incr_every_clean_steps(self):
        lin, opt, s = _setup(init_loss_scaling=1024.0,
                             decr_every_n_nan_or_inf=1,
                             incr_every_n_steps=3, incr_ratio=2.0)
        _set_grads(lin, np.inf)
        s.step(opt)
        assert s.get_loss_scaling() == 512.0
        for i in range(3):
            _set_grads(lin, 0.1)
            s.step(opt)
            # growth happens exactly AT the Nth clean step, not before
            assert s.get_loss_scaling() == (1024.0 if i == 2 else 512.0)

    def test_bad_step_resets_the_clean_streak(self):
        lin, opt, s = _setup(init_loss_scaling=512.0,
                             decr_every_n_nan_or_inf=2,
                             incr_every_n_steps=2, incr_ratio=2.0)
        _set_grads(lin, 0.1)
        s.step(opt)
        _set_grads(lin, np.nan)
        s.step(opt)                 # streak broken (scale not yet cut)
        _set_grads(lin, 0.1)
        s.step(opt)
        assert s.get_loss_scaling() == 512.0    # 1 clean, not 2
        _set_grads(lin, 0.1)
        s.step(opt)
        assert s.get_loss_scaling() == 1024.0


class TestUnscaleFlow:
    def test_unscale_divides_grads_by_the_scale(self):
        lin, opt, s = _setup(init_loss_scaling=64.0)
        loss = s.scale(lin(Tensor(jnp.ones((2, 4)))).sum())
        loss.backward()
        scaled = [np.asarray(p.grad.data).copy()
                  for p in opt._parameter_list]
        s.unscale_(opt)
        for p, g_scaled in zip(opt._parameter_list, scaled):
            np.testing.assert_allclose(np.asarray(p.grad.data),
                                       g_scaled / 64.0, rtol=1e-6)

    def test_double_unscale_raises(self):
        lin, opt, s = _setup(init_loss_scaling=64.0)
        _set_grads(lin, 0.1)
        s.unscale_(opt)
        with pytest.raises(RuntimeError, match="already been called"):
            s.unscale_(opt)
        # step() clears the latch for the next iteration
        s.step(opt)
        _set_grads(lin, 0.1)
        s.unscale_(opt)

    def test_matches_unscaled_reference_run(self):
        """A scaled clean step must land within float tolerance of an
        unscaled run from the same init — scaling is numerically
        transparent when nothing overflows."""
        def run(scaler):
            lin, opt, s = _setup(init_loss_scaling=scaler)
            for _ in range(3):
                loss = s.scale(lin(Tensor(jnp.ones((2, 4)))).sum())
                s.minimize(opt, loss)
            return [np.asarray(p.data) for p in lin.parameters()]

        for a, b in zip(run(1.0), run(4096.0)):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


class TestState:
    def test_state_dict_roundtrip(self):
        _, opt, s = _setup(init_loss_scaling=1024.0,
                           decr_every_n_nan_or_inf=2)
        lin2, opt2, s2 = _setup(init_loss_scaling=1024.0,
                                decr_every_n_nan_or_inf=2)
        _set_grads(lin2, np.inf)
        s2.step(opt2)
        state = s2.state_dict()
        assert state["bad_steps"] == 1
        s.load_state_dict(state)
        # the restored scaler continues the decay exactly where the
        # saved one stopped: one more bad step halves
        lin, opt, _ = _setup()
        s._unscaled = False
        _set_grads(lin, np.inf)
        s.step(opt)
        assert s.get_loss_scaling() == 512.0

    def test_disabled_scaler_passes_through(self):
        lin, opt, s = _setup(enable=False)
        before = _params_bytes(lin)
        _set_grads(lin, 0.1)
        s.step(opt)                     # plain optimizer.step()
        assert _params_bytes(lin) != before
        assert s.scale(2.0) == 2.0
