"""Semi-auto SPMD: sharding propagation + runtime reshard.

Reference parity: the auto_parallel planning tests
(unittests/test_auto_parallel_completion.py — Completer emits dist_attr for
every tensor of a toy MLP from sparse annotations;
test_auto_parallel_reshard.py — Resharder moves tensors between meshes).
Here: ShardingPropagator completes PartitionSpec trees over the traced
jaxpr, parity is sharded-vs-single-device loss equality, and reshard is
device_put between NamedShardings.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.distributed.auto_parallel import (
    ShardingPropagator, complete, parallelize, reshard, shard_tensor)
from paddle_tpu.models.gpt import GPT_CONFIGS, gpt_forward, gpt_init


def mesh_2x4():
    return Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "mp"))


def mlp_loss(params, x):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    h = h @ params["w2"] + params["b2"]
    return (h.astype(jnp.float32) ** 2).mean()


def mlp_params(key=0):
    ks = jax.random.split(jax.random.key(key), 4)
    return {
        "w1": jax.random.normal(ks[0], (16, 32), jnp.float32) * 0.3,
        "b1": jax.random.normal(ks[1], (32,), jnp.float32) * 0.1,
        "w2": jax.random.normal(ks[2], (32, 16), jnp.float32) * 0.3,
        "b2": jax.random.normal(ks[3], (16,), jnp.float32) * 0.1,
    }


class TestCompletion:
    def test_mlp_megatron_from_two_annotations(self):
        """Annotating the input batch dim + the first weight's output dim
        must complete the classic column→row layout (completion.py's MLP
        fixture)."""
        mesh = mesh_2x4()
        params = mlp_params()
        x = jnp.ones((8, 16))
        specs = complete(mlp_loss, (params, x),
                         {"*w1": P(None, "mp"), "1": P("dp")}, mesh)
        pspecs, xspec = specs
        assert xspec == P("dp")
        assert pspecs["w1"] == P(None, "mp")
        assert pspecs["b1"] == P("mp")          # column bias follows
        assert pspecs["w2"] == P("mp")          # row-parallel inferred
        assert pspecs["b2"] == P()              # replicated output bias

    def test_gpt_full_layout_from_three_annotations(self):
        """tokens→dp + qkv_w/up_w→column must complete the whole Megatron
        block layout (row proj/down, mp biases) through scan + remat +
        attention."""
        mesh = mesh_2x4()
        cfg = dataclasses.replace(GPT_CONFIGS["tiny"], use_flash=False)
        params = gpt_init(cfg)
        toks = jnp.zeros((4, 32), jnp.int32)

        def loss(params, tokens):
            logits = gpt_forward(cfg, params, tokens)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            tgt = jnp.roll(tokens, -1, 1)
            return -jnp.take_along_axis(logp, tgt[..., None], -1).mean()

        specs, _ = complete(
            loss, (params, toks),
            {"0/blocks/qkv_w": P(None, None, "mp"),
             "0/blocks/up_w": P(None, None, "mp"),
             "1": P("dp")}, mesh)
        b = specs["blocks"]
        assert b["qkv_w"] == P(None, None, "mp")
        assert b["qkv_b"] == P(None, "mp")
        assert b["proj_w"] == P(None, "mp")     # row-parallel inferred
        assert b["up_b"] == P(None, "mp")
        assert b["down_w"] == P(None, "mp")     # row-parallel inferred
        for name in ("ln1_g", "ln1_b", "ln2_g", "ln2_b"):
            assert b[name] == P()

    def test_indivisible_dim_stays_replicated(self):
        """A propagated axis whose size doesn't divide the dim must drop to
        replicated, not error (GSPMD couldn't honor it)."""
        mesh = mesh_2x4()
        params = {"w1": jnp.ones((16, 32)), "b1": jnp.zeros((32,)),
                  "g": jnp.ones((2, 16))}
        x = jnp.ones((8, 16))

        def loss(params, x):
            h = jnp.tanh(x @ params["w1"] + params["b1"])
            # reshape splits the mp-sharded 32-dim into (2, 16): mp(4)
            # propagates onto the size-2 major factor, which 4 can't divide
            z = h.reshape(8, 2, 16) * params["g"]
            return (z.astype(jnp.float32) ** 2).mean()

        specs = complete(loss, (params, x), {"*w1": P(None, "mp")}, mesh)
        assert specs[0]["w1"] == P(None, "mp")
        assert specs[0]["g"] == P()     # 2 % 4 != 0 → dropped, not error

    def test_annotation_errors(self):
        mesh = mesh_2x4()
        params = mlp_params()
        x = jnp.ones((8, 16))
        with pytest.raises(ValueError, match="matches no input"):
            complete(mlp_loss, (params, x), {"*nope": P("mp")}, mesh)
        with pytest.raises(ValueError, match="unknown mesh axis"):
            complete(mlp_loss, (params, x), {"*w1": P(None, "tp")}, mesh)
        with pytest.raises(ValueError, match="not divisible"):
            # 16 % 3 — no axis of size 3; use dp(2) on the 15-col weight
            complete(mlp_loss,
                     ({"w1": jnp.ones((16, 33)), "b1": jnp.zeros((33,)),
                       "w2": jnp.ones((33, 16)), "b2": jnp.zeros((16,))},
                      x),
                     {"*w1": P(None, "mp")}, mesh)
        with pytest.raises(ValueError, match="conflicting"):
            complete(mlp_loss, (params, x),
                     {"*w1": P(None, "mp"), "*b1": P("dp")}, mesh)


class TestParity:
    """Sharded-by-completed-specs training == single-device training."""

    def _sgd_step(self, loss_fn, lr=0.1):
        def step(params, x):
            l, g = jax.value_and_grad(loss_fn)(params, x)
            return jax.tree_util.tree_map(lambda p, g: p - lr * g,
                                          params, g), l
        return step

    def test_mlp_train_parity(self):
        mesh = mesh_2x4()
        params = mlp_params()
        x = np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32)
        step = self._sgd_step(mlp_loss)

        ref_p = jax.tree_util.tree_map(jnp.copy, params)
        ref_step = jax.jit(step)

        jstep, specs = parallelize(step, mesh, (params, jnp.asarray(x)),
                                   {"*w1": P(None, "mp"), "1": P("dp")},
                                   return_specs=True)
        sp = reshard(params, specs[0], mesh)

        for i in range(5):
            xb = jnp.asarray(x + i)
            ref_p, ref_l = ref_step(ref_p, xb)
            sp, l = jstep(sp, xb)
            np.testing.assert_allclose(np.asarray(l), np.asarray(ref_l),
                                       rtol=2e-5, atol=2e-6)
        for a, b in zip(jax.tree_util.tree_leaves(sp),
                        jax.tree_util.tree_leaves(ref_p)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)

    @pytest.mark.slow
    def test_gpt_train_parity_three_annotations(self):
        """The VERDICT acceptance bar: a GPT train step reaches parity loss
        with ≤3 user annotations on the 8-device mesh."""
        mesh = mesh_2x4()
        cfg = dataclasses.replace(GPT_CONFIGS["tiny"], use_flash=False,
                                  dtype="float32")
        params = gpt_init(cfg, dtype=jnp.float32)
        rng = np.random.default_rng(1)

        def loss(params, tokens):
            logits = gpt_forward(cfg, params, tokens)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            tgt = jnp.roll(tokens, -1, 1)
            return -jnp.take_along_axis(logp, tgt[..., None], -1).mean()

        step = self._sgd_step(loss, lr=0.01)
        toks0 = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 32)),
                            jnp.int32)

        jstep, specs = parallelize(
            step, mesh, (params, toks0),
            {"0/blocks/qkv_w": P(None, None, "mp"),
             "0/blocks/up_w": P(None, None, "mp"),
             "1": P("dp")}, return_specs=True)

        ref_step = jax.jit(step)
        ref_p = jax.tree_util.tree_map(jnp.copy, params)
        sp = reshard(params, specs[0], mesh)
        for _ in range(3):
            toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 32)),
                               jnp.int32)
            ref_p, ref_l = ref_step(ref_p, toks)
            sp, l = jstep(sp, toks)
            np.testing.assert_allclose(np.asarray(l), np.asarray(ref_l),
                                       rtol=1e-4, atol=1e-5)


class TestReshard:
    def test_shard_tensor_roundtrip(self):
        mesh = mesh_2x4()
        x = np.arange(64, dtype=np.float32).reshape(8, 8)
        sx = shard_tensor(jnp.asarray(x), mesh, P("dp", "mp"))
        assert sx.sharding == NamedSharding(mesh, P("dp", "mp"))
        np.testing.assert_array_equal(np.asarray(sx), x)

    def test_reshard_between_layouts_and_meshes(self):
        """Resharder analog: values survive arbitrary layout moves,
        including onto a differently-factored mesh (reshard.py:603's
        cross-mesh case)."""
        mesh_a = mesh_2x4()
        mesh_b = Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                      ("x", "y"))
        tree = {"w": jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                "b": jnp.arange(8, dtype=jnp.float32)}
        on_a = reshard(tree, {"w": P("dp", "mp"), "b": P("mp")}, mesh_a)
        on_b = reshard(on_a, {"w": P("y", "x"), "b": P(None)}, mesh_b)
        assert on_b["w"].sharding == NamedSharding(mesh_b, P("y", "x"))
        np.testing.assert_array_equal(np.asarray(on_b["w"]),
                                      np.asarray(tree["w"]))
        np.testing.assert_array_equal(np.asarray(on_b["b"]),
                                      np.asarray(tree["b"]))

    def test_reshard_single_spec_broadcast(self):
        mesh = mesh_2x4()
        tree = [jnp.ones((8, 4)), jnp.ones((16, 8))]
        out = reshard(tree, P("dp"), mesh)
        for leaf in out:
            assert leaf.sharding == NamedSharding(mesh, P("dp"))
