"""Autograd tape tests (parity: eager backward semantics, backward.cc:522)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import ops


def test_simple_chain():
    x = paddle.to_tensor(np.array([2.0, 3.0], np.float32), stop_gradient=False)
    y = x * x + x
    loss = ops.sum(y)
    loss.backward()
    np.testing.assert_allclose(np.asarray(x.grad.data), [5.0, 7.0])


def test_grad_accumulation():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad.data), [5.0, 5.0, 5.0])


def test_stop_gradient():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=True)
    (x * y).sum().backward()
    assert x.grad is not None
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = (x * 2).detach()
    z = y * x
    z.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad.data), [2.0, 2.0, 2.0])


def test_no_grad():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y._node is None
    assert y.stop_gradient


def test_multi_output_op():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                         stop_gradient=False)
    a, b = ops.split(x, 2, axis=0)
    (a.sum() * 2 + b.sum() * 3).backward()
    expected = np.array([[2, 2, 2], [3, 3, 3]], np.float32)
    np.testing.assert_allclose(np.asarray(x.grad.data), expected)


def test_diamond_graph():
    x = paddle.to_tensor(np.array([1.0], np.float32), stop_gradient=False)
    a = x * 2
    b = x * 3
    ((a + b) * (a - b)).sum().backward()  # (2x)(3x) pattern: 4x^2 - 9x^2
    np.testing.assert_allclose(np.asarray(x.grad.data), [-10.0])


def test_shared_subexpression():
    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    y = x * x        # x^2
    z = y * y        # x^4 → dz/dx = 4 x^3 = 32
    z.backward()
    np.testing.assert_allclose(np.asarray(x.grad.data), [32.0])


def test_grad_api():
    x = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
    y = x * x
    (g,) = paddle.grad(y, [x])
    np.testing.assert_allclose(np.asarray(g.data), [6.0])


def test_backward_non_scalar_with_grad():
    x = paddle.to_tensor(np.ones((2, 2), np.float32), stop_gradient=False)
    y = x * 4
    y.backward(paddle.to_tensor(np.full((2, 2), 0.5, np.float32)))
    np.testing.assert_allclose(np.asarray(x.grad.data), np.full((2, 2), 2.0))


def test_retain_grads_intermediate():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    y = x * 2
    y.retain_grads()
    (y * 3).sum().backward()
    np.testing.assert_allclose(np.asarray(y.grad.data), [3.0, 3.0])
