"""Hooks + PyLayer + double-grad tests (VERDICT r3 item 7).

Reference strategy: the eager hook/double-grad tests compare against
numeric or closed-form references (grad_node_info.h:90 hooks,
py_layer.py PyLayer, partial_grad_engine.cc grad-of-grad)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.autograd import PyLayer


def _t(arr, requires_grad=True):
    t = paddle.to_tensor(np.asarray(arr, np.float32))
    t.stop_gradient = not requires_grad
    return t


class TestHooks:
    def test_hook_sees_final_accumulated_grad(self):
        x = _t([1.0, 2.0])
        seen = []
        x.register_hook(lambda g: seen.append(np.asarray(g.data)))
        y = (x * x).sum() + (3.0 * x).sum()   # two consumers of x
        y.backward()
        assert len(seen) == 1                  # fired once, after accum
        np.testing.assert_allclose(seen[0], [5.0, 7.0])
        np.testing.assert_allclose(np.asarray(x.grad.data), [5.0, 7.0])

    def test_hook_modifies_propagated_grad(self):
        x = _t([1.0, 2.0])
        h = _t([0.0, 0.0])   # intermediate
        y = x * 2.0
        y.register_hook(lambda g: g * 10.0)
        (y.sum()).backward()
        # d/dx = 2, hook scales the cotangent at y by 10 before it
        # propagates to x
        np.testing.assert_allclose(np.asarray(x.grad.data), [20.0, 20.0])

    def test_hook_remove(self):
        x = _t([1.0])
        calls = []
        handle = x.register_hook(lambda g: calls.append(1))
        handle.remove()
        (x * 2.0).sum().backward()
        assert calls == []

    def test_intermediate_hook_affects_retained_grad(self):
        x = _t([3.0])
        y = x * 2.0
        y.retain_grads()
        y.register_hook(lambda g: g * 5.0)
        (y * 1.0).sum().backward()
        np.testing.assert_allclose(np.asarray(y.grad.data), [5.0])
        np.testing.assert_allclose(np.asarray(x.grad.data), [10.0])


class TestFunctionalGrad:
    def test_grad_outputs_length_mismatch_raises(self):
        x = _t([1.0, 1.0, 1.0])
        y1, y2 = (x * 2.0).sum(), (x * 3.0).sum()
        with pytest.raises(ValueError, match="lengths must match"):
            paddle.grad([y1, y2], [x],
                        grad_outputs=[_t(1.0, requires_grad=False)])

    def test_hook_on_output_that_feeds_another_output(self):
        # grad([y, z]) with z = f(y): the hook on y must see the FULL
        # dL/dy (seed + z's contribution), and the result propagates
        x = _t([1.0, 1.0, 1.0])
        y = x * 2.0
        z = (y * 3.0).sum()
        seen = []

        def hook(g):
            seen.append(np.asarray(g.data).copy())
            return g * 2.0

        y.register_hook(hook)
        gx = paddle.grad([y.sum(), z], [x])[0]
        np.testing.assert_allclose(seen[0], [4.0, 4.0, 4.0])  # 1 + 3
        np.testing.assert_allclose(np.asarray(gx.data), [16.0] * 3)

    def test_grad_basic(self):
        x = _t([2.0, 3.0])
        y = (x ** 3).sum()
        (gx,) = paddle.grad(y, [x])
        np.testing.assert_allclose(np.asarray(gx.data), [12.0, 27.0])
        assert x.grad is None     # grad() must not write .grad

    def test_grad_allow_unused(self):
        x, z = _t([1.0]), _t([1.0])
        y = (x * 2.0).sum()
        with pytest.raises(RuntimeError, match="allow_unused"):
            paddle.grad(y, [x, z])
        gx, gz = paddle.grad((x * 2.0).sum(), [x, z], allow_unused=True)
        assert gz is None
        np.testing.assert_allclose(np.asarray(gx.data), [2.0])

    def test_double_grad_closed_form(self):
        # y = x^3: dy/dx = 3x^2, d/dx(dy/dx · 1) = 6x
        x = _t([2.0])
        y = (x ** 3).sum()
        (gx,) = paddle.grad(y, [x], create_graph=True)
        (ggx,) = paddle.grad(gx.sum(), [x])
        np.testing.assert_allclose(np.asarray(ggx.data), [12.0])

    def test_gradient_penalty_matches_numeric(self):
        # loss = f(x) + ||∇x f||²  — the VERDICT's acceptance test
        paddle.seed(7)
        net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))

        def full_loss_np(x_np):
            x = _t(x_np)
            f = net(x).sum()
            (gx,) = paddle.grad(f, [x], create_graph=True)
            return f + (gx ** 2).sum()

        x0 = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        x = _t(x0)
        f = net(x).sum()
        (gx,) = paddle.grad(f, [x], create_graph=True)
        loss = f + (gx ** 2).sum()
        loss.backward()
        analytic = np.asarray(x.grad.data)

        # central differences on the full (penalized) loss
        eps = 1e-3
        numeric = np.zeros_like(x0)
        for i in np.ndindex(*x0.shape):
            xp, xm = x0.copy(), x0.copy()
            xp[i] += eps
            xm[i] -= eps
            lp = float(full_loss_np(xp).data)
            lm = float(full_loss_np(xm).data)
            numeric[i] = (lp - lm) / (2 * eps)
        np.testing.assert_allclose(analytic, numeric, rtol=2e-2,
                                   atol=2e-3)

    def test_double_grad_through_params(self):
        # second-order wrt x must include curvature through shared use
        x = _t([1.5])
        w = _t([2.0])
        y = (w * x ** 2).sum()           # dy/dx = 2wx; d(dy/dx)/dw = 2x
        (gx,) = paddle.grad(y, [x], create_graph=True)
        (gw,) = paddle.grad(gx.sum(), [w])
        np.testing.assert_allclose(np.asarray(gw.data), [3.0])


class TestPyLayer:
    def test_forward_backward_round_trip(self):
        class Square(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor()
                return dy * 2.0 * x

        x = _t([3.0, 4.0])
        y = Square.apply(x)
        y.sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad.data), [6.0, 8.0])

    def test_multiple_inputs_and_outputs(self):
        class MulAdd(PyLayer):
            @staticmethod
            def forward(ctx, a, b):
                ctx.save_for_backward(a, b)
                return a * b, a + b

            @staticmethod
            def backward(ctx, d_mul, d_add):
                a, b = ctx.saved_tensor()
                return d_mul * b + d_add, d_mul * a + d_add

        a, b = _t([2.0]), _t([5.0])
        p, s = MulAdd.apply(a, b)
        (p + 2.0 * s).sum().backward()
        np.testing.assert_allclose(np.asarray(a.grad.data), [7.0])
        np.testing.assert_allclose(np.asarray(b.grad.data), [4.0])

    def test_wrong_grad_count_is_loud(self):
        class Bad(PyLayer):
            @staticmethod
            def forward(ctx, a, b):
                return a + b

            @staticmethod
            def backward(ctx, dy):
                return dy        # two inputs, one grad

        a, b = _t([1.0]), _t([1.0])
        with pytest.raises(RuntimeError, match="gradient"):
            Bad.apply(a, b).sum().backward()

    def test_no_track_when_inputs_stopped(self):
        class Ident(PyLayer):
            @staticmethod
            def forward(ctx, x):
                return x * 1.0

            @staticmethod
            def backward(ctx, dy):
                return dy

        x = _t([1.0], requires_grad=False)
        y = Ident.apply(x)
        assert y.stop_gradient

    def test_double_grad_through_pylayer(self):
        class Cube(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x * x

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor()
                return dy * 3.0 * x * x     # differentiable ops only

        x = _t([2.0])
        y = Cube.apply(x).sum()
        (gx,) = paddle.grad(y, [x], create_graph=True)
        (ggx,) = paddle.grad(gx.sum(), [x])
        np.testing.assert_allclose(np.asarray(ggx.data), [12.0])


class TestMultiRootBackward:
    def test_shared_subgraph_joint_walk(self):
        """backward([r1, r2]) with a shared intermediate must run ONE
        joint walk — a per-root loop frees h's node after the first
        root and errors on the second (regression: code-review r4)."""
        from paddle_tpu.autograd import backward

        x = _t([1.0, 2.0])
        h = x * 2.0
        r1 = (h * h).sum()
        r2 = (h * 3.0).sum()
        backward([r1, r2])
        # d r1/dx = 8x ; d r2/dx = 6
        np.testing.assert_allclose(np.asarray(x.grad.data), [14.0, 22.0])

    def test_length_mismatch_raises(self):
        from paddle_tpu.autograd import backward

        x = _t([1.0])
        with pytest.raises(ValueError, match="lengths must match"):
            backward([(x * 2.0).sum(), (x * 3.0).sum()],
                     grad_tensors=[_t([1.0], False)])

    def test_duplicate_roots_accumulate(self):
        from paddle_tpu.autograd import backward

        x = _t([2.0])
        y = (x * x).sum()
        backward([y, y])
        np.testing.assert_allclose(np.asarray(x.grad.data), [8.0])


class TestPartialGradPruning:
    def test_side_branch_not_differentiated(self):
        """grad(out, [mid]) must prune to the outputs→inputs subgraph
        (PartialGradEngine parity): the deep branch below mid is not
        walked, so its nodes survive for a later backward even with
        retain_graph=False."""
        from paddle_tpu.core.autograd import grad as fgrad

        x = _t([1.0, 2.0])
        mid = x * 3.0
        out = (mid * mid).sum()
        (g,) = fgrad(out, [mid])                 # retain_graph=False
        np.testing.assert_allclose(np.asarray(g.data), [6.0, 12.0])
        # the x*3 node was off the out→mid path: still differentiable
        mid2 = mid.sum()
        mid2.backward()
        np.testing.assert_allclose(np.asarray(x.grad.data), [3.0, 3.0])

    def test_pruned_grad_still_exact_with_fanout(self):
        """A consumer feeding a needed producer is itself needed: both
        consumers of h contribute to grad wrt x."""
        from paddle_tpu.core.autograd import grad as fgrad

        x = _t([1.0, 3.0])
        h = x * x
        a = (h * 2.0).sum()
        b = (h * 5.0).sum()
        out = a + b
        (g,) = fgrad(out, [x])
        np.testing.assert_allclose(np.asarray(g.data),
                                   14.0 * np.array([1.0, 3.0]))

    def test_hook_on_pruned_producer_target_still_fires(self):
        """Hooks on a grad() target whose producer node is off the
        outputs->inputs path must still see the finalized cotangent
        (regression: pruning skipped the producer that used to fire
        them)."""
        from paddle_tpu.core.autograd import grad as fgrad

        x = _t([1.0, 2.0])
        mid = x * 3.0
        mid.register_hook(lambda g: g * 10.0)
        out = (mid * mid).sum()
        (g,) = fgrad(out, [mid])
        np.testing.assert_allclose(np.asarray(g.data), [60.0, 120.0])

    def test_hook_on_nontarget_leaf_with_pruned_consumer_stays_silent(self):
        """Same partial-cotangent hazard for LEAVES: a hooked non-target
        leaf whose other consumer was pruned must not fire."""
        import paddle_tpu as paddle
        from paddle_tpu.core.autograd import grad as fgrad

        x = _t([1.0, 2.0])
        h = _t([3.0, 4.0])
        fired = []
        h.register_hook(lambda g: fired.append(np.asarray(g.data)))
        out = (x * h).sum() + (h * h).sum()
        (g,) = fgrad(out, [x])
        np.testing.assert_allclose(np.asarray(g.data), [3.0, 4.0])
        assert fired == []
