"""Elastic-fleet autoscaler unit matrix.

Stub engines + a manual clock make every decision path deterministic:
hysteresis-band edges (load exactly on a boundary → zero events),
burst → scale-up → quiet → cooldown-delayed scale-down, warming
replicas excluded from capacity, cache-warmth-aware victim selection
(in-process and over the gossip/store path), the bounded spawn-retry
budget at the ``autoscaler.scale_up`` fault site, and dead-fleet
revival.  One real-engine test pins the ``Engine.warmup()`` EWMA-reset
contract the warming logic depends on (the drain-floor regression).
"""
import dataclasses
import json

import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models.gpt import GPT_CONFIGS, gpt_init
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.resilience import FaultSpec, injected_faults
from paddle_tpu.serving import (Autoscaler, Engine, FleetRouter,
                                PrefixSummaryPublisher, ReplicaServer,
                                ReplicaState, RequestState,
                                SamplingParams,
                                collect_prefix_summaries)


class _ManualClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


class _StubReq:
    def __init__(self, prompt, sampling):
        self.prompt = list(prompt)
        self.sampling = sampling
        self.state = RequestState.QUEUED
        self.tokens = list(prompt)
        self.finish_reason = None
        self.retry_after_s = None

    @property
    def output(self):
        return self.tokens[len(self.prompt):]


class _StubEngine:
    """Engine-shaped stub with hand-set router signals: ``drain`` is
    the advertised estimate, ``rate=None`` means warming (no decode
    EWMA sample yet), ``summary`` is the gossiped radix payload."""

    def __init__(self, rate=120.0, drain=0.0, summary=None):
        self.rate = rate
        self.drain = drain
        self.summary = summary if summary is not None else {
            "page_size": 8, "enabled": True, "entries": {}, "stats": {}}
        self.reqs = []
        self.warmed = 0

    def health(self):
        return {"healthy": True, "queue_depth": 0,
                "running": len(self.reqs), "page_occupancy": 0.0,
                "estimated_drain_s": self.drain,
                "decode_rate_tok_s": self.rate,
                "prefix_cache": {"enabled": True}}

    def add_request(self, prompt, sampling, trace_context=None):
        req = _StubReq(prompt, sampling)
        self.reqs.append(req)
        return req

    def has_work(self):
        return bool(self.reqs)

    def step(self):
        for req in self.reqs:
            req.tokens.append(1)
            if len(req.output) >= req.sampling.max_new_tokens:
                req.state = RequestState.FINISHED
                req.finish_reason = "length"
        self.reqs = [r for r in self.reqs
                     if r.state != RequestState.FINISHED]

    def evacuate(self):
        for req in self.reqs:
            req.state = RequestState.EVACUATED
        self.reqs = []

    def prefix_summary(self, max_entries=32):
        return self.summary

    def warmup(self):
        self.warmed += 1
        return self


def _stub_factory(**kw):
    return lambda: _StubEngine(**kw)


def _fleet(engines, clock, *, factory=None, scaler_kw=None, **router_kw):
    registry = router_kw.pop("registry", None) or MetricsRegistry()
    router = FleetRouter(engines, clock=clock, registry=registry,
                         **router_kw)
    kw = dict(min_replicas=1, max_replicas=4, up_pressure_s=2.0,
              down_pressure_s=0.25, up_pending_depth=6,
              scale_up_cooldown_s=5.0, scale_down_cooldown_s=10.0,
              spawn_backoff_base_s=0.001, spawn_backoff_cap_s=0.002)
    kw.update(scaler_kw or {})
    scaler = Autoscaler(router, factory or _stub_factory(),
                        clock=clock, registry=registry, **kw)
    return router, scaler


def _events(scaler):
    return scaler.status()["scale_events"]


# ----------------------------------------------------- hysteresis edges


class TestHysteresis:
    def test_boundary_oscillation_zero_events(self):
        """Load oscillating EXACTLY between the two band edges must
        produce zero scale events: both comparisons are strict."""
        clock = _ManualClock()
        stubs = [_StubEngine(drain=0.0), _StubEngine(drain=0.0)]
        router, scaler = _fleet(stubs, clock)
        for i in range(40):
            drain = (scaler.up_pressure_s if i % 2 == 0
                     else scaler.down_pressure_s)
            for stub in stubs:
                stub.drain = drain
            clock.advance(30.0)       # every cooldown long expired
            assert scaler.tick() is None
        assert _events(scaler) == {"up": 0, "down": 0}
        assert len(router.replicas) == 2
        snap = scaler.metrics.snapshot()
        assert snap["scale_events"] == {}
        # the band edges themselves were really exercised
        assert scaler.status()["last_signals"]["pressure_s"] in (
            scaler.up_pressure_s, scaler.down_pressure_s)

    def test_above_band_scales_up_below_scales_down(self):
        clock = _ManualClock()
        stub = _StubEngine(drain=0.0)
        router, scaler = _fleet([stub], clock)
        stub.drain = scaler.up_pressure_s + 0.01
        assert scaler.tick() == ("up", "pressure")
        assert len(router.replicas) == 2
        stub.drain = scaler.down_pressure_s - 0.01
        # the new replica is warming (factory stub has no EWMA state
        # here: give it one so it counts as ready capacity)
        router.replicas[1].engine.rate = 100.0
        clock.advance(scaler.scale_down_cooldown_s + 0.1)
        assert scaler.tick() == ("down", "idle")

    def test_burst_up_quiet_then_cooldown_delayed_down(self):
        """Burst → immediate up; quiet → the down waits out the
        cooldown measured from the UP event (an up is never undone
        in the same breath), then fires."""
        clock = _ManualClock()
        stub = _StubEngine(drain=0.0)
        router, scaler = _fleet(
            [stub], clock,
            factory=_stub_factory(rate=100.0),
            scaler_kw={"scale_down_cooldown_s": 10.0})
        stub.drain = 5.0                       # burst
        assert scaler.tick() == ("up", "pressure")
        up_t = clock.t
        stub.drain = 0.0                       # quiet again
        for _ in range(9):                     # 9 s: inside the window
            clock.advance(1.0)
            assert scaler.tick() is None
        assert _events(scaler) == {"up": 1, "down": 0}
        clock.advance(1.5)                     # past the window
        assert scaler.tick() == ("down", "idle")
        assert clock.t - up_t >= scaler.scale_down_cooldown_s
        assert _events(scaler) == {"up": 1, "down": 1}
        # drained victim left rotation without a restart
        states = [rep.state for rep in router.replicas]
        assert states.count(ReplicaState.HEALTHY) == 1


# ------------------------------------------------- warming ≠ capacity


class TestWarmingCapacity:
    def test_warming_replica_excluded_from_pressure_and_ready(self):
        clock = _ManualClock()
        busy = _StubEngine(drain=3.0)
        router, scaler = _fleet(
            [busy], clock, factory=_stub_factory(rate=None, drain=0.5),
            scaler_kw={"max_replicas": 2})
        assert scaler.tick() == ("up", "pressure")
        clock.advance(1.0)
        scaler.tick()
        sig = scaler.status()["last_signals"]
        # the fresh replica advertises its 0.5 s drain floor but has
        # no decode sample: it is warming, not capacity — pressure
        # stays the ready replica's full 3.0 s, not (3.0 + 0.5) / 2
        assert sig["healthy"] == 2
        assert sig["ready"] == 1
        assert sig["warming"] == [1]
        assert sig["pressure_s"] == pytest.approx(3.0)
        # first real decode sample → same replica now counts
        router.replicas[1].engine.rate = 80.0
        clock.advance(1.0)
        scaler.tick()
        sig = scaler.status()["last_signals"]
        assert sig["ready"] == 2 and sig["warming"] == []
        assert sig["pressure_s"] == pytest.approx((3.0 + 0.5) / 2)

    def test_spawned_engine_gets_router_warmup(self):
        clock = _ManualClock()
        warmed = []
        router, scaler = _fleet(
            [_StubEngine(drain=5.0)], clock,
            factory=_stub_factory(rate=None),
            warmup=lambda eng: warmed.append(eng.warmup()))
        assert scaler.tick() == ("up", "pressure")
        # warmup ran on the spawned engine BEFORE rotation entry
        assert len(warmed) == 1
        assert router.replicas[1].engine is warmed[0]
        assert warmed[0].warmed == 1


class TestWarmupEwmaReset:
    def test_drain_floor_survives_warmup(self):
        """Regression (the satellite fix): ``Engine.warmup()`` compiles
        the unified step via a real tiny request but must RESET the
        decode EWMA — a freshly scaled-up replica keeps advertising
        ``drain_floor_s`` (and ``decode_rate_tok_s: None``) until a
        real decode step samples the true rate."""
        cfg = dataclasses.replace(GPT_CONFIGS["tiny"], dtype="float32")
        params = gpt_init(cfg, jax.random.key(0), dtype=jnp.float32)
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=2, chunk_len=8)
        eng.warmup()
        assert not eng.has_work()
        assert eng._decode_rate_ewma is None
        assert eng.health()["decode_rate_tok_s"] is None
        assert eng.estimated_drain_s() >= eng.drain_floor_s
        # the first real decode replaces the floor with measurement
        eng.generate([[5, 6, 7]], SamplingParams(max_new_tokens=3))
        assert eng._decode_rate_ewma is not None
        assert eng.estimated_drain_s() == 0.0     # idle, measured


# ------------------------------------------------ victim selection


class TestVictimSelection:
    def _summary(self, entries):
        return {"page_size": 8, "enabled": True, "entries": entries,
                "stats": {"cached_pages": len(entries)}}

    def test_coldest_replica_drains_first(self):
        clock = _ManualClock()
        warm = _StubEngine(summary=self._summary({"a": 64, "b": 32}))
        cold = _StubEngine(summary=self._summary({}))
        tepid = _StubEngine(summary=self._summary({"c": 16}))
        router, scaler = _fleet([warm, cold, tepid], clock,
                                scaler_kw={"min_replicas": 1})
        clock.advance(60.0)
        assert scaler.tick() == ("down", "idle")
        assert router.replicas[1].state != ReplicaState.HEALTHY
        assert router.replicas[0].state == ReplicaState.HEALTHY
        assert router.replicas[2].state == ReplicaState.HEALTHY
        event = scaler.status()["events"][-1]
        assert event["replica"] == 1
        assert event["victim_warm_tokens"] == 0

    def test_warmth_tie_breaks_to_youngest(self):
        clock = _ManualClock()
        stubs = [_StubEngine(summary=self._summary({})),
                 _StubEngine(summary=self._summary({})),
                 _StubEngine(summary=self._summary({}))]
        router, scaler = _fleet(stubs, clock)
        clock.advance(60.0)
        assert scaler.tick() == ("down", "idle")
        # all equally cold, nothing in flight → the youngest (most
        # recently added capacity) goes first
        assert router.replicas[2].state != ReplicaState.HEALTHY

    def test_warmth_scores_over_store_gossip(self):
        """Cross-process path: replicas publish radix summaries over
        the store plane; the autoscaler's victim selection reads the
        collected summaries, not in-process engine state."""

        class _FakeStore:
            def __init__(self):
                self.kv = {}

            def set(self, key, value):
                self.kv[key] = value

            def mget(self, keys):
                return [self.kv.get(k) for k in keys]

        store = _FakeStore()
        clock = _ManualClock()
        engines = [_StubEngine(summary=self._summary({"a": 64})),
                   _StubEngine(summary=self._summary({})),
                   _StubEngine(summary=self._summary({"b": 128}))]
        for rid, eng in enumerate(engines):
            PrefixSummaryPublisher(eng, rid, store).publish()
        router, scaler = _fleet(
            engines, clock,
            prefix_summary_source=lambda: collect_prefix_summaries(
                store, range(3)))
        clock.advance(60.0)
        assert scaler.tick() == ("down", "idle")
        assert router.replicas[1].state != ReplicaState.HEALTHY
        assert router.replicas[0].state == ReplicaState.HEALTHY
        assert router.replicas[2].state == ReplicaState.HEALTHY

    def test_replica_server_hosts_gossip_publisher(self):
        """The per-replica serve loop owns its publisher: the store
        key appears while serving and carries the engine's summary."""

        class _FakeStore:
            def __init__(self):
                self.kv = {}

            def set(self, key, value):
                self.kv[key] = value

            def mget(self, keys):
                return [self.kv.get(k) for k in keys]

        store = _FakeStore()
        eng = _StubEngine(summary=self._summary({"x": 24}))
        eng.add_request([1, 2, 3], SamplingParams(max_new_tokens=2))
        srv = ReplicaServer(eng, 7, store=store,
                            gossip_interval_s=0.01)
        served = srv.serve(should_stop=lambda: not eng.has_work())
        assert served == 2 and not eng.has_work()
        raw = store.kv.get("prefix/replica_7")
        assert raw is not None
        payload = json.loads(raw)
        assert payload["replica"] == 7
        assert payload["summary"]["entries"] == {"x": 24}
        collected = collect_prefix_summaries(store, [7])
        assert collected[7]["entries"] == {"x": 24}
        assert srv.publisher.running is False


# ---------------------------------------------- spawn discipline


@pytest.mark.faultinject
class TestSpawnDiscipline:
    def test_spawn_io_error_retried_within_budget(self):
        clock = _ManualClock()
        stub = _StubEngine(drain=5.0)
        router, scaler = _fleet([stub], clock,
                                factory=_stub_factory(rate=100.0),
                                scaler_kw={"spawn_max_retries": 2})
        with injected_faults(FaultSpec("autoscaler.scale_up",
                                       "io_error", occurrence=1)):
            assert scaler.tick() == ("up", "pressure")
        assert len(router.replicas) == 2
        status = scaler.status()
        assert status["spawn_failures"] == 0
        assert status["scale_events"] == {"up": 1, "down": 0}

    def test_spawn_budget_exhaustion_counted_not_raised(self):
        clock = _ManualClock()
        stub = _StubEngine(drain=5.0)
        router, scaler = _fleet([stub], clock,
                                scaler_kw={"spawn_max_retries": 1})
        specs = [FaultSpec("autoscaler.scale_up", "io_error",
                           occurrence=i) for i in (1, 2)]
        with injected_faults(*specs):
            assert scaler.tick() is None       # budget exhausted
        assert len(router.replicas) == 1
        status = scaler.status()
        assert status["spawn_failures"] == 1
        assert status["scale_events"] == {"up": 0, "down": 0}
        assert scaler.metrics.snapshot()["spawn_failures"] == 1

    def test_dead_fleet_revives_through_restart_first(self):
        """Scale-up prefers reviving a DEAD restartable replica over
        spawning fresh — and a fully dead fleet bypasses the up
        cooldown (recovery, not flap)."""
        clock = _ManualClock()
        router, scaler = _fleet([_stub_factory(rate=50.0)], clock)
        router.kill_replica(0)
        router.step()                          # probe miss 1
        router.step()                          # probe miss 2 → DEAD
        assert router.replicas[0].state == ReplicaState.DEAD
        clock.advance(0.1)
        assert scaler.tick() == ("up", "no_capacity")
        assert len(router.replicas) == 1       # revived, not appended
        assert router.replicas[0].state == ReplicaState.HEALTHY


# ------------------------------------------- cascade-breaker coordination


class TestCascadeBreakerGate:
    """Autoscaler × cascade-breaker interplay: while the router's
    breaker is open (a poison storm churning replicas), every scale-up
    trigger is vetoed — the backlog is failure churn, not demand — with
    exactly one exception: zero healthy replicas is recovery, and a
    starved fleet cannot even run canary trials."""

    def _storm(self, clock, n=3, **router_kw):
        router_kw.setdefault("cascade_threshold", 2)
        router_kw.setdefault("cascade_window_s", 50.0)
        router, scaler = _fleet([_stub_factory() for _ in range(n)],
                                clock, factory=_stub_factory(),
                                **router_kw)
        # two uncontrolled replica deaths inside the window: the
        # probe-miss detection path counts each as a failure event
        router.kill_replica(0)
        router.kill_replica(1)
        for _ in range(2):                 # probe misses → DEAD
            router.step()
        assert router.cascade_open()
        return router, scaler

    def test_open_breaker_vetoes_every_scale_up_trigger(self):
        clock = _ManualClock()
        router, scaler = self._storm(clock)
        survivor = router.replicas[2]
        assert survivor.state == ReplicaState.HEALTHY
        # a screaming scale-up signal: pressure far above the band,
        # with both dead replicas revivable and no cooldown pending
        survivor.engine.drain = scaler.up_pressure_s * 10
        for _ in range(6):
            clock.advance(5.0)             # < window: breaker stays open
            router.step()
            assert scaler.tick() is None
        assert _events(scaler) == {"up": 0, "down": 0}
        assert scaler.status()["last_signals"]["cascade_open"] is True
        assert len(router.replicas) == 3   # nothing spawned either
        assert router.cascade_open()

    def test_burst_during_open_breaker_scales_once_it_closes(self):
        clock = _ManualClock()
        router, scaler = self._storm(clock)
        # the burst arrives MID-storm ...
        survivor = router.replicas[2]
        survivor.engine.drain = scaler.up_pressure_s * 10
        clock.advance(1.0)
        router.step()
        assert scaler.tick() is None       # vetoed while open
        # ... the storm window drains: breaker closes, the still-
        # present burst scales on the next tick (revive-first)
        clock.advance(60.0)
        router.step()
        assert not router.cascade_open()
        assert scaler.tick() == ("up", "pressure")
        assert _events(scaler)["up"] == 1
        assert any(rep.state == ReplicaState.HEALTHY
                   and rep.replica_id in (0, 1)
                   for rep in router.replicas)  # revived, not appended
        assert len(router.replicas) == 3

    def test_zero_healthy_recovery_bypasses_the_veto(self):
        clock = _ManualClock()
        router, scaler = _fleet([_stub_factory(), _stub_factory()],
                                clock, factory=_stub_factory(),
                                cascade_threshold=2,
                                cascade_window_s=50.0)
        router.kill_replica(0)
        router.kill_replica(1)
        for _ in range(2):
            router.step()
        assert router.cascade_open()
        assert all(rep.state == ReplicaState.DEAD
                   for rep in router.replicas)
        clock.advance(0.1)
        # breaker open AND zero healthy: recovery wins — one replica
        # comes back so canary trials (and innocents) can run at all
        assert scaler.tick() == ("up", "no_capacity")
        assert router.cascade_open()       # the breaker itself stays open
        assert sum(1 for rep in router.replicas
                   if rep.state == ReplicaState.HEALTHY) == 1


# ---------------------------------------------------- status surface


class TestStatusSurface:
    def test_fleet_status_folds_autoscaler_block(self):
        clock = _ManualClock()
        stub = _StubEngine(drain=5.0)
        router, scaler = _fleet([stub], clock,
                                factory=_stub_factory(rate=100.0))
        scaler.tick()
        status = router.fleet_status()
        block = status["autoscaler"]
        assert block["scale_events"] == {"up": 1, "down": 0}
        assert block["target_replicas"] == 2
        assert block["bands"]["up_pressure_s"] == scaler.up_pressure_s
        assert block["cooldown_remaining_s"]["up"] > 0
        assert block["events"][-1]["direction"] == "up"
        assert block["events"][-1]["reason"] == "pressure"
        # the autoscaler::scale span landed in the tracer
        names = [t["name"] for t in scaler.tracer.traces()]
        assert "autoscaler::scale" in names
