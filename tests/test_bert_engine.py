"""A second architecture through HybridEngine.step with no engine edits
(VERDICT r4 item 3): BERT-style bidirectional encoder + MLM head via
distributed.model_adapter.BertAdapter.

Reference role: fleet.distributed_model wraps ANY Layer
(fleet_base.py:937,1043-1069) — here the engine's stage protocol carries
a model family with different attention (bidirectional), a different
embedding (token types + embedding LN) and a different head (MLM
transform), under the same dp x mp x pp hybrid meshes, both pipeline
schedules, ZeRO and the optimizer."""
import jax
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from paddle_tpu.distributed.engine import EngineConfig, HybridEngine
from paddle_tpu.distributed.model_adapter import BertAdapter
from paddle_tpu.models.bert import BertConfig, bert_loss

CFG = BertConfig(vocab_size=256, max_seq_len=64, type_vocab_size=2,
                 hidden=64, num_layers=4, num_heads=4, ffn_hidden=128,
                 dtype="float32", use_flash=False, remat="nothing")


def _mlm_batch(bs=8, seq=32, seed=0, mask_rate=0.2):
    """MLM corruption: labels carry the original ids at masked positions,
    -100 elsewhere; masked inputs are replaced by a [MASK]-like id."""
    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, CFG.vocab_size, (bs, seq)).astype(np.int32)
    mask = rng.rand(bs, seq) < mask_rate
    labels = np.where(mask, tokens, -100).astype(np.int32)
    corrupted = np.where(mask, CFG.vocab_size - 1, tokens).astype(np.int32)
    return corrupted, labels


def _run(engine, n=3, bs=8):
    params, opt = engine.init(seed=0)
    tokens, labels = _mlm_batch(bs)
    losses = []
    for _ in range(n):
        params, opt, loss = engine.step(params, opt, tokens, labels,
                                        lr=1e-3)
        losses.append(float(loss))
    return losses, engine.gather_params(params)


@pytest.fixture(scope="module")
def baseline():
    eng = HybridEngine(BertAdapter(CFG), devices=jax.devices()[:1])
    return _run(eng)


def test_single_device_loss_matches_functional(baseline):
    """Engine pp=1 path == the functional bert_loss oracle at init."""
    eng = HybridEngine(BertAdapter(CFG), devices=jax.devices()[:1])
    params, _ = eng.init(seed=0)
    tokens, labels = _mlm_batch()
    host = eng.gather_params(params)
    ref = float(bert_loss(CFG, host, tokens, labels))
    assert abs(baseline[0][0] - ref) < 2e-4, (baseline[0][0], ref)
    # MLM CE near log(vocab) at init
    assert abs(ref - np.log(CFG.vocab_size)) < 1.0


def test_dp_mp_matches(baseline):
    eng = HybridEngine(BertAdapter(CFG), dp=2, mp=2,
                       devices=jax.devices()[:4])
    losses, _ = _run(eng)
    np.testing.assert_allclose(losses, baseline[0], atol=2e-4, rtol=1e-4)


def test_pp_1f1b_matches(baseline):
    eng = HybridEngine(BertAdapter(CFG), pp=2, devices=jax.devices()[:2],
                       engine_cfg=EngineConfig(num_microbatches=4,
                                               pipeline_schedule="1f1b"))
    losses, _ = _run(eng)
    np.testing.assert_allclose(losses, baseline[0], atol=2e-4, rtol=1e-4)


def test_hybrid_dp_mp_pp_matches(baseline):
    eng = HybridEngine(BertAdapter(CFG), dp=2, mp=2, pp=2,
                       engine_cfg=EngineConfig(num_microbatches=2))
    losses, params = _run(eng)
    np.testing.assert_allclose(losses, baseline[0], atol=2e-4, rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(baseline[1]),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4)


def test_zero3_matches(baseline):
    eng = HybridEngine(BertAdapter(CFG), sharding=4,
                       devices=jax.devices()[:4],
                       engine_cfg=EngineConfig(zero_stage=3))
    losses, _ = _run(eng)
    np.testing.assert_allclose(losses, baseline[0], atol=2e-4, rtol=1e-4)
