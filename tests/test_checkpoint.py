"""Sharded checkpoint + cross-topology restore tests.

Reference scenario: auto_parallel/dist_saver.py + converter.py — train
under one (dp, mp, pp, sharding) layout, save per-shard, restore under a
DIFFERENT layout, and training must continue as if never interrupted.
"""
import jax
import numpy as np
import pytest

from paddle_tpu.distributed.checkpoint import (load_engine_state,
                                               load_sharded,
                                               save_engine_state,
                                               save_sharded)
from paddle_tpu.distributed.engine import EngineConfig, HybridEngine
from paddle_tpu.models.gpt import GPTConfig

CFG = GPTConfig(vocab_size=256, max_seq_len=64, hidden=64, num_layers=4,
                num_heads=4, ffn_hidden=128, dtype="float32",
                use_flash=False, remat="nothing")


def _batch(bs=8, seq=32, seed=0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, CFG.vocab_size, (bs, seq)).astype(np.int32)
    labels = np.concatenate([tokens[:, 1:], np.full((bs, 1), -100)],
                            axis=1).astype(np.int32)
    return tokens, labels


def _train(engine, params, opt, n, lr=1e-3):
    tokens, labels = _batch()
    losses = []
    for _ in range(n):
        params, opt, loss = engine.step(params, opt, tokens, labels, lr=lr)
        losses.append(float(loss))
    return params, opt, losses


class TestShardedRoundtrip:
    def test_plain_tree_roundtrip(self, tmp_path):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("a", "b"))
        x = jax.device_put(np.arange(64.0).reshape(8, 8),
                           NamedSharding(mesh, P("a", "b")))
        y = jax.device_put(np.arange(6.0), NamedSharding(mesh, P()))
        save_sharded(str(tmp_path / "ck"), {"x": x, "y": y}, step=5)
        host, manifest = load_sharded(str(tmp_path / "ck"))
        assert manifest["step"] == 5
        np.testing.assert_array_equal(host["x"], np.asarray(x))
        np.testing.assert_array_equal(host["y"], np.asarray(y))

    def test_resharded_load(self, tmp_path):
        """Saved under one sharding, loaded under a different one."""
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("a", "b"))
        x = jax.device_put(np.arange(64.0).reshape(8, 8),
                           NamedSharding(mesh, P("a", "b")))
        save_sharded(str(tmp_path / "ck"), {"x": x})
        # target: transposed sharding on a differently-shaped mesh
        mesh2 = Mesh(np.array(jax.devices()[:8]), ("c",))
        like = {"x": jax.device_put(np.zeros((8, 8)),
                                    NamedSharding(mesh2, P(None, "c")))}
        tree, _ = load_sharded(str(tmp_path / "ck"), like_tree=like)
        np.testing.assert_array_equal(np.asarray(tree["x"]), np.asarray(x))


@pytest.mark.slow
class TestEngineCheckpoint:
    def _uninterrupted(self):
        eng = HybridEngine(CFG, dp=2, mp=2, sharding=2)
        params, opt = eng.init(seed=0)
        _, _, losses = _train(eng, params, opt, 4)
        return losses

    def test_same_topology_resume(self, tmp_path):
        ref_losses = self._uninterrupted()
        eng = HybridEngine(CFG, dp=2, mp=2, sharding=2)
        params, opt = eng.init(seed=0)
        params, opt, l01 = _train(eng, params, opt, 2)
        save_engine_state(str(tmp_path / "ck"), eng, params, opt)

        eng2 = HybridEngine(CFG, dp=2, mp=2, sharding=2)
        params2, opt2 = load_engine_state(str(tmp_path / "ck"), eng2)
        assert int(opt2["step"]) == 2
        _, _, l23 = _train(eng2, params2, opt2, 2)
        np.testing.assert_allclose(l01 + l23, ref_losses, atol=2e-4,
                                   rtol=1e-4)

    def test_cross_topology_resume(self, tmp_path):
        """dp2.mp2.sharding2 → mp4.sharding2 (different mesh, different
        ZeRO chunking): loss continuity must hold."""
        ref_losses = self._uninterrupted()
        eng = HybridEngine(CFG, dp=2, mp=2, sharding=2)
        params, opt = eng.init(seed=0)
        params, opt, l01 = _train(eng, params, opt, 2)
        save_engine_state(str(tmp_path / "ck"), eng, params, opt)

        eng2 = HybridEngine(CFG, mp=4, sharding=2)
        params2, opt2 = load_engine_state(str(tmp_path / "ck"), eng2)
        _, _, l23 = _train(eng2, params2, opt2, 2)
        np.testing.assert_allclose(l01 + l23, ref_losses, atol=5e-4,
                                   rtol=1e-4)

    def test_cross_zero_stage_resume(self, tmp_path):
        """stage-2 checkpoint restored into a stage-3 engine (params go
        from replicated to sharded)."""
        ref_losses = self._uninterrupted()
        eng = HybridEngine(CFG, dp=2, mp=2, sharding=2)
        params, opt = eng.init(seed=0)
        params, opt, l01 = _train(eng, params, opt, 2)
        save_engine_state(str(tmp_path / "ck"), eng, params, opt)

        eng2 = HybridEngine(CFG, dp=2, sharding=4,
                            engine_cfg=EngineConfig(zero_stage=3))
        params2, opt2 = load_engine_state(str(tmp_path / "ck"), eng2)
        _, _, l23 = _train(eng2, params2, opt2, 2)
        np.testing.assert_allclose(l01 + l23, ref_losses, atol=5e-4,
                                   rtol=1e-4)


class TestDtypes:
    def test_bfloat16_roundtrip(self, tmp_path):
        """np.save/load of ml_dtypes arrays returns raw void dtype; the
        loader must reinterpret via the manifest dtype."""
        import jax.numpy as jnp

        x = jnp.arange(16.0, dtype=jnp.bfloat16).reshape(4, 4)
        save_sharded(str(tmp_path / "ck"), {"x": x})
        host, _ = load_sharded(str(tmp_path / "ck"))
        assert host["x"].dtype == np.dtype(jnp.bfloat16)
        np.testing.assert_array_equal(host["x"].astype(np.float32),
                                      np.asarray(x).astype(np.float32))
