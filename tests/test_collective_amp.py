"""Collective-op semantics + GradScaler defaults (ADVICE round-1 items)."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from paddle_tpu.distributed.collective import ReduceOp, Group, all_reduce


def test_allreduce_prod_signs_and_zeros():
    mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
    vals = np.array([[2.0, -3.0, 0.0, -1.0],
                     [1.0, -2.0, 5.0, 4.0],
                     [3.0, 1.0, 2.0, -2.0],
                     [-1.0, 2.0, 1.0, 1.0]], np.float32)  # [rank, elem]
    expect = np.prod(vals, axis=0)

    def local(x):
        return all_reduce(x, op=ReduceOp.PROD, group=Group(axis_name="x", gid=1))

    out = jax.jit(shard_map(local, mesh=mesh, in_specs=P("x"),
                            out_specs=P("x")))(vals.reshape(-1))
    out = np.asarray(out).reshape(4, 4)
    for r in range(4):
        np.testing.assert_allclose(out[r], expect, rtol=1e-5)


def test_grad_scaler_dynamic_by_default():
    from paddle_tpu.amp.grad_scaler import GradScaler
    import paddle_tpu as paddle

    s = GradScaler(init_loss_scaling=1024.0)
    loss = paddle.to_tensor(np.float32(2.0))
    scaled = s.scale(loss)
    assert float(scaled.numpy() if hasattr(scaled, "numpy") else scaled) == 2048.0


def test_allreduce_prod_int_exact():
    mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
    vals = np.array([3, 2, 7, 11], np.int32)  # product 462

    def local(x):
        return all_reduce(x, op=ReduceOp.PROD, group=Group(axis_name="x", gid=1))

    out = jax.jit(shard_map(local, mesh=mesh, in_specs=P("x"),
                            out_specs=P("x")))(vals)
    assert np.asarray(out).tolist() == [462, 462, 462, 462]


def test_grad_scaler_jit_raises_clear_error():
    import pytest
    from paddle_tpu.amp.grad_scaler import GradScaler
    import paddle_tpu as paddle

    lin = paddle.nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
    s = GradScaler(init_loss_scaling=1024.0)

    def train(xval):
        x = paddle.Tensor(xval)
        loss = s.scale(lin(x).sum())
        loss.backward()
        with pytest.raises(RuntimeError, match="outside"):
            s.step(opt)
        return xval

    jax.jit(train)(jnp.ones((2, 4)))
