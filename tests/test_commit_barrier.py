"""Multi-host checkpoint commit barrier — globally-consistent latest().

Extends the PR 3/PR 6 kill-at-every-boundary matrix across HOSTS: ranks
run as threads sharing one checkpoint directory (the shared-filesystem
model) and one TCPStore, each with its own client + CommitBarrier.  The
invariant under every fault: ``latest()`` moves on ALL ranks or on
NONE — a rank killed before its shard ack (``checkpoint.shard_ack``)
or a committer killed pre-rename (``checkpoint.before_barrier_commit``)
must leave every survivor resolving the PREVIOUS checkpoint.
"""
import os
import threading

import numpy as np
import pytest

from paddle_tpu.distributed.checkpoint import (CommitBarrier,
                                               CommitBarrierError,
                                               load_sharded,
                                               save_sharded)
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.resilience.checkpoint_manager import CheckpointManager
from paddle_tpu.resilience.faults import (FaultSpec, SimulatedCrash,
                                          injected_faults)

WORLD = 2


@pytest.fixture
def master_store():
    store = TCPStore(is_master=True, world_size=WORLD)
    yield store


def _client(master):
    return TCPStore(port=master.port, world_size=WORLD)


def _tree(step):
    return {"w": np.arange(16.0) + step, "b": np.full((4,), float(step))}


def _run_ranks(fn, world=WORLD):
    """Run fn(rank) on one thread per rank; returns {rank: outcome}
    where outcome is ("ok", value) or (ExceptionName, value)."""
    results = {}

    def wrap(r):
        try:
            results[r] = ("ok", fn(r))
        except BaseException as e:     # noqa: BLE001 - SimulatedCrash IS the point
            results[r] = (type(e).__name__, None)

    threads = [threading.Thread(target=wrap, args=(r,), daemon=True)
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    return results


# ------------------------------------------------------- happy protocol


class TestBarrierProtocol:
    def test_all_ranks_commit_and_agree(self, master_store, tmp_path):
        d = str(tmp_path / "ck")

        def rank(r):
            mgr = CheckpointManager(
                d, barrier=CommitBarrier(_client(master_store), r,
                                         WORLD, timeout=10.0))
            mgr.save(_tree(1), 1)
            return mgr.latest()

        results = _run_ranks(rank)
        assert results == {0: ("ok", 1), 1: ("ok", 1)}
        # both ranks' manifests landed under the committed step dir
        step_dir = os.path.join(d, "step_0000000001")
        names = sorted(os.listdir(step_dir))
        assert "manifest.0.json" in names and "manifest.1.json" in names

    def test_bare_save_sharded_barrier_commit(self, master_store,
                                              tmp_path):
        """Manifest-level commit (no manager): pending manifests become
        visible only after rank 0's barrier rename."""
        d = str(tmp_path / "raw")

        def rank(r):
            save_sharded(d, _tree(3), step=3,
                         barrier=CommitBarrier(_client(master_store), r,
                                               WORLD, timeout=10.0))
            return True

        results = _run_ranks(rank)
        assert all(v == ("ok", True) for v in results.values())
        host, manifest = load_sharded(d)
        assert manifest["step"] == 3
        np.testing.assert_array_equal(host["w"], _tree(3)["w"])
        assert not [n for n in os.listdir(d) if n.endswith(".pending")]

    def test_retry_after_failed_attempt_uses_new_generation(
            self, master_store, tmp_path):
        """A dead attempt's stale acks must not satisfy a retried save
        of the SAME step (generation-qualified keys)."""
        d = str(tmp_path / "ck")

        # attempt 1: rank 1 never shows up -> rank 0 times out
        def lone(r):
            mgr = CheckpointManager(
                d, barrier=CommitBarrier(_client(master_store), r,
                                         WORLD, timeout=1.0))
            mgr.save(_tree(1), 1)

        results = _run_ranks(lone, world=1)
        assert results[0][0] == "CommitBarrierError"
        mgr = CheckpointManager(d, sweep_orphans=False)
        assert mgr.latest() is None

        # attempt 2, same step: both ranks -> commits cleanly
        def rank(r):
            mgr = CheckpointManager(
                d, barrier=CommitBarrier(_client(master_store), r,
                                         WORLD, timeout=10.0))
            mgr.save(_tree(1), 1)
            return mgr.latest()

        results = _run_ranks(rank)
        assert results == {0: ("ok", 1), 1: ("ok", 1)}


# ------------------------------------------------------- the kill matrix


class TestCommitBarrierKillMatrix:
    def _committed_step_then(self, master_store, d, faults, timeout=2.0):
        """Commit step 1 cleanly, then attempt step 2 under ``faults``;
        returns the per-rank outcomes of attempt 2."""

        def save_step(r, step, t):
            mgr = CheckpointManager(
                d, barrier=CommitBarrier(_client(master_store), r,
                                         WORLD, timeout=t))
            mgr.save(_tree(step), step)
            return mgr.latest()

        results = _run_ranks(lambda r: save_step(r, 1, 10.0))
        assert results == {0: ("ok", 1), 1: ("ok", 1)}
        with injected_faults(*faults):
            return _run_ranks(lambda r: save_step(r, 2, timeout))

    def test_rank_killed_before_ack_never_advances_latest(
            self, master_store, tmp_path):
        """THE acceptance case: one rank dies at checkpoint.shard_ack
        before publishing its CRCs — the barrier starves, nothing is
        renamed, and latest() on every surviving rank (and for any
        later reader) is still the PREVIOUS step."""
        d = str(tmp_path / "ck")
        results = self._committed_step_then(
            master_store, d,
            [FaultSpec("checkpoint.shard_ack", "kill", occurrence=1)])
        outcomes = sorted(kind for kind, _ in results.values())
        assert outcomes == ["CommitBarrierError", "SimulatedCrash"]
        reader = CheckpointManager(d, sweep_orphans=False)
        assert reader.latest() == 1
        _, tree, manifest = reader.restore()
        assert manifest["step"] == 1
        np.testing.assert_array_equal(tree["w"], _tree(1)["w"])

    def test_committer_killed_before_barrier_commit(self, master_store,
                                                    tmp_path):
        """Rank 0 collects every ack then dies at
        checkpoint.before_barrier_commit — still nothing renamed, every
        survivor times out, latest() == previous everywhere."""
        d = str(tmp_path / "ck")
        results = self._committed_step_then(
            master_store, d,
            [FaultSpec("checkpoint.before_barrier_commit", "kill",
                       occurrence=1)])
        kinds = {r: kind for r, (kind, _) in results.items()}
        assert kinds[0] == "SimulatedCrash"
        assert kinds[1] == "CommitBarrierError"
        assert CheckpointManager(d, sweep_orphans=False).latest() == 1

    def test_ack_stall_is_tolerated_within_timeout(self, master_store,
                                                   tmp_path):
        """A SLOW rank (stall at checkpoint.shard_ack) is not a dead
        rank: the barrier waits it out and the commit completes on
        every rank."""
        d = str(tmp_path / "ck")
        results = self._committed_step_then(
            master_store, d,
            [FaultSpec("checkpoint.shard_ack", "stall", occurrence=1,
                       stall_s=0.3)],
            timeout=10.0)
        assert results == {0: ("ok", 2), 1: ("ok", 2)}
        assert CheckpointManager(d, sweep_orphans=False).latest() == 2

    def test_crashed_attempt_resumes_from_previous_everywhere(
            self, master_store, tmp_path):
        """After the failed step-2 attempt, a relaunched fleet retries
        step 2 and every rank converges on it (the tmp debris of the
        dead attempt is swept by rank 0's next begin())."""
        d = str(tmp_path / "ck")
        self._committed_step_then(
            master_store, d,
            [FaultSpec("checkpoint.shard_ack", "kill", occurrence=1)])

        def rank(r):
            mgr = CheckpointManager(
                d, barrier=CommitBarrier(_client(master_store), r,
                                         WORLD, timeout=10.0))
            mgr.save(_tree(2), 2)
            return mgr.latest()

        results = _run_ranks(rank)
        assert results == {0: ("ok", 2), 1: ("ok", 2)}
        _, tree, _ = CheckpointManager(d, sweep_orphans=False).restore()
        np.testing.assert_array_equal(tree["w"], _tree(2)["w"])


class TestBarrierIntrospection:
    def test_status_snapshot(self, master_store, tmp_path):
        def rank(r):
            b = CommitBarrier(_client(master_store), r, WORLD,
                              timeout=10.0)
            mgr = CheckpointManager(str(tmp_path / "ck"), barrier=b)
            mgr.save(_tree(1), 1)
            return b.status()

        results = _run_ranks(rank)
        st0 = results[0][1]
        assert st0["tokens"] == {"step_0000000001": "committed"}
        assert st0["acked_ranks"] == {"step_0000000001": [0, 1]}
        assert results[1][1]["tokens"] == {
            "step_0000000001": "committed"}


# ------------------------------------------------- bounded-wait fixes


class TestBoundedWaits:
    """Deadline regressions for the blocking waits the new
    collective-discipline pass polices (ISSUE 13 satellite): each fix
    is proven by a wall-clock bound, the lockedness-test analogue for
    time — the probe fails on the pre-fix code."""

    def test_store_wait_shares_one_deadline_across_keys(self,
                                                        master_store):
        """wait() on N missing keys used to cost N x timeout (each
        get() got a fresh budget); now one Deadline spans them all."""
        import time as _t

        client = _client(master_store)
        t0 = _t.monotonic()
        with pytest.raises(TimeoutError):
            client.wait(["never/a", "never/b", "never/c", "never/d"],
                        timeout=0.4)
        assert _t.monotonic() - t0 < 1.2    # one budget, not four

    def test_store_get_zero_timeout_fails_fast(self, master_store):
        """get(timeout=0) used to promote the falsy budget to the 30s
        store default; an exhausted deadline must miss promptly."""
        import time as _t

        client = _client(master_store)
        t0 = _t.monotonic()
        with pytest.raises(TimeoutError):
            client.get("never/zero", timeout=0)
        assert _t.monotonic() - t0 < 1.0

    def test_barrier_timeout_bounded(self, master_store):
        """A counted barrier nobody else joins must miss within its
        own budget (Deadline-bounded ack poll)."""
        import time as _t

        client = _client(master_store)
        t0 = _t.monotonic()
        with pytest.raises(TimeoutError):
            client.barrier(name="lonely", timeout=0.4)
        assert _t.monotonic() - t0 < 1.5

    def test_begin_join_miss_is_protocol_error(self, master_store):
        """A joiner whose rank 0 never opens a generation used to leak
        a raw store TimeoutError; the miss is the barrier's own
        failure type, within the barrier's budget."""
        import time as _t

        b = CommitBarrier(_client(master_store), 1, WORLD, timeout=0.4)
        t0 = _t.monotonic()
        with pytest.raises(CommitBarrierError):
            b.begin("orphan_token")
        assert _t.monotonic() - t0 < 2.0

    def test_collect_acks_aborts_promptly_at_scale(self, master_store):
        """Rank 0 committing a 16-rank world with zero acks: expiry
        surfaces once — the old per-rank minimum wait overshot the
        budget by O(world_size)."""
        import time as _t

        b = CommitBarrier(_client(master_store), 0, 16, timeout=0.4)
        b.begin("tok_scale")
        t0 = _t.monotonic()
        with pytest.raises(CommitBarrierError):
            b.commit("tok_scale", fn=lambda: None)
        # pre-fix: 0.4 + 16*0.05 = 1.2s minimum; now ~0.4s
        assert _t.monotonic() - t0 < 1.1
