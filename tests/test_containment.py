"""Blast-radius containment: per-row FAILED isolation in the engine,
the router's poison-request suspicion → canary trial → QUARANTINE
pipeline, the fleet cascade breaker, and the supporting plumbing
(deterministic failover re-enqueue order, the ``router.canary_dispatch``
fault site, the soft-breaker ``/healthz`` fold).

The acceptance matrix mirrors the zero-loss failover contract one
level down: a request that *causes* failures is contained — terminal
``FAILED`` (row-attributable) or ``QUARANTINED`` (replica-killing) with
evidence attached — while every innocent co-batched / co-scheduled
request still finishes with greedy output token-identical to a
poison-free run, and the number of uncontrolled replica kills a single
poison pattern can cause is bounded by ``canary_threshold + 1``.
"""
import dataclasses
import json
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models.gpt import GPT_CONFIGS, gpt_forward, gpt_init
from paddle_tpu.observability.exporter import start_telemetry_server
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.resilience import FaultSpec, injected_faults
from paddle_tpu.serving import (Engine, FleetRequestState, FleetRouter,
                                ReplicaState, RequestState, SamplingParams)


def _tiny_cfg():
    # fp32: parity asserts compare argmax across replicas / re-dispatch
    return dataclasses.replace(GPT_CONFIGS["tiny"], dtype="float32")


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny_cfg()
    params = gpt_init(cfg, jax.random.key(0), dtype=jnp.float32)
    return cfg, params


_ORACLE_FWD = {}


def naive_generate(cfg, params, prompt, n_new):
    """Full-recompute greedy decoding — the poison-free oracle."""
    fwd = _ORACLE_FWD.get(id(cfg))
    if fwd is None:
        fwd = _ORACLE_FWD.setdefault(
            id(cfg), jax.jit(lambda p, t: gpt_forward(cfg, p, t)))
    toks = list(prompt)
    for _ in range(n_new):
        logits = fwd(params, jnp.asarray([toks], jnp.int32))
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def _factory(cfg, params, **kw):
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 64)
    kw.setdefault("max_batch_size", 2)
    kw.setdefault("chunk_len", 8)

    def make():
        return Engine(cfg, params, **kw)

    return make


def _router(cfg, params, n=3, engine_kw=None, **kw):
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("canary_threshold", 2)
    kw.setdefault("cascade_threshold", 2)
    kw.setdefault("cascade_window_s", 3.0)
    return FleetRouter([_factory(cfg, params, **(engine_kw or {}))] * n,
                       **kw)


def _settle(router, ticks=400):
    for i in range(ticks):
        if not router.has_work():
            return i
        router.step()
    raise AssertionError(f"fleet did not settle in {ticks} ticks")


# ------------------------------------------------- per-row isolation


class TestPerRowIsolation:
    def test_row_failure_pins_failed_and_spares_the_batch(
            self, tiny_model):
        """An exception attributable to ONE row (its page-table lookup
        explodes mid-plan) retires that request terminal FAILED — pages
        freed, trace closed on the error — while the co-batched request
        finishes token-identical and the engine keeps serving."""
        cfg, params = tiny_model
        eng = _factory(cfg, params)()
        rng = np.random.RandomState(7)
        good_prompt = list(rng.randint(0, cfg.vocab_size, 6))
        ref = naive_generate(cfg, params, good_prompt, 4)
        sp = SamplingParams(max_new_tokens=4)
        good = eng.add_request(good_prompt, sp)
        bad = eng.add_request(list(rng.randint(0, cfg.vocab_size, 5)), sp)

        real = eng.cache.page_table

        def sabotaged(seq_id):
            if seq_id == bad.id:
                raise RuntimeError("synthetic row fault")
            return real(seq_id)

        eng.cache.page_table = sabotaged
        for _ in range(60):
            if not eng.has_work():
                break
            eng.step()
        eng.cache.page_table = real

        assert bad.state == RequestState.FAILED
        assert "synthetic row fault" in bad.finish_reason
        assert good.state == RequestState.FINISHED
        assert good.output == ref
        assert eng.metrics.requests_failed.value == 1
        # the failed row's pages came back to the pool
        assert eng.cache.num_used_pages == 0
        # the engine is alive — a fresh request sails through
        again = eng.add_request(good_prompt, sp)
        for _ in range(60):
            if not eng.has_work():
                break
            eng.step()
        assert again.state == RequestState.FINISHED
        assert again.output == ref

    def test_commit_failure_is_row_scoped_too(self, tiny_model):
        """A failure in the post-step commit path (sampling /
        bookkeeping) of one row leaves the other rows committing
        normally."""
        cfg, params = tiny_model
        eng = _factory(cfg, params)()
        rng = np.random.RandomState(11)
        good_prompt = list(rng.randint(0, cfg.vocab_size, 7))
        ref = naive_generate(cfg, params, good_prompt, 4)
        sp = SamplingParams(max_new_tokens=4)
        good = eng.add_request(good_prompt, sp)
        bad = eng.add_request(list(rng.randint(0, cfg.vocab_size, 6)), sp)

        real = eng._sample_token

        def sabotaged(logits_row, req):
            if req.id == bad.id:
                raise ValueError("synthetic commit fault")
            return real(logits_row, req)

        eng._sample_token = sabotaged
        for _ in range(60):
            if not eng.has_work():
                break
            eng.step()
        assert bad.state == RequestState.FAILED
        assert good.state == RequestState.FINISHED
        assert good.output == ref

    def test_fleet_surfaces_row_failure_as_failed_not_failover(
            self, tiny_model):
        """A row-attributable failure under the router stays FAILED on
        that fleet request — no replica failover, no suspicion charged
        to innocent co-tenants."""
        cfg, params = tiny_model
        router = _router(cfg, params, n=2)
        rng = np.random.RandomState(3)
        good_prompt = list(rng.randint(0, cfg.vocab_size, 6))
        ref = naive_generate(cfg, params, good_prompt, 4)
        sp = SamplingParams(max_new_tokens=4)
        good = router.submit(good_prompt, sp)
        bad = router.submit(list(rng.randint(0, cfg.vocab_size, 8)), sp)
        router.step()                      # dispatch both
        assert bad.replica_id is not None
        eng = router.replicas[bad.replica_id].engine
        real = eng.cache.page_table
        bad_engine_id = bad._engine_req.id

        def sabotaged(seq_id):
            if seq_id == bad_engine_id:
                raise RuntimeError("synthetic row fault")
            return real(seq_id)

        eng.cache.page_table = sabotaged
        _settle(router)
        eng.cache.page_table = real
        snap = router.metrics.snapshot()
        assert bad.state == FleetRequestState.FAILED
        assert "synthetic row fault" in bad.finish_reason
        assert bad.redispatches == 0       # not a failover
        assert good.state == FleetRequestState.FINISHED
        assert good.output == ref
        assert snap["failure_events"] == 0
        assert snap["lost"] == 0
        assert all(rep.state == ReplicaState.HEALTHY
                   for rep in router.replicas)


# ---------------------------------------- poison → canary → quarantine


@pytest.mark.faultinject
class TestPoisonQuarantine:
    def test_poison_request_quarantined_innocents_token_identical(
            self, tiny_model):
        """The tentpole end-to-end: a poison_request fault armed on a
        token pattern kills whatever replica co-batches it; after
        ``canary_threshold`` distinct uncontrolled kills the suspect is
        re-admitted ALONE on a canary replica, killing the canary
        convicts it (terminal QUARANTINED with evidence), and every
        innocent finishes greedy-token-identical to a poison-free run.
        Uncontrolled kills are bounded by canary_threshold + 1."""
        cfg, params = tiny_model
        rng = np.random.RandomState(0)
        innocents = [list(rng.randint(0, cfg.vocab_size, n))
                     for n in (5, 9, 7, 11)]
        refs = [naive_generate(cfg, params, p, 6) for p in innocents]
        poison = [7, 8, 9, 10]

        router = _router(cfg, params, n=3)
        sp = SamplingParams(max_new_tokens=6)
        with injected_faults(FaultSpec("serving.step", "poison_request",
                                       pattern=(7, 8, 9))):
            reqs = [router.submit(p, sp) for p in innocents[:2]]
            preq = router.submit(poison, sp)
            reqs += [router.submit(p, sp) for p in innocents[2:]]
            _settle(router)

        snap = router.metrics.snapshot()
        assert preq.state == FleetRequestState.QUARANTINED
        ev = preq.quarantine_evidence
        assert ev["suspicion"] >= 2
        assert len(ev["failure_events"]) == ev["suspicion"]
        assert ev["canary_replica"] is not None
        # innocents: all finished, token-identical — never taxed
        assert [r.state for r in reqs] == \
            [FleetRequestState.FINISHED] * len(reqs)
        assert [r.output for r in reqs] == refs
        # blast radius: at most canary_threshold + 1 replica kills,
        # and the canary death was the controlled (+1) one
        assert snap["failure_events"] <= 3
        assert snap["canary_deaths"] == 1
        assert snap["canary_dispatches"] >= 1
        assert snap["quarantined"] == 1
        assert snap["cascade_breaker_opens"] == 1
        assert snap["lost"] == 0
        # the quarantined request's trace is tail-retained with the
        # quarantine verdict on it
        kept = {t["name"]: t for t in router.tracer.traces()
                if t.get("retained")}
        qt = [t for t in kept.values() if t["retained"] == "quarantined"]
        assert qt, sorted(kept)
        assert any(s.get("name") == "router::quarantine"
                   for t in qt for s in t.get("spans", ()))

    def test_convicted_prompt_sibling_quarantined_at_admission(
            self, tiny_model):
        """Conviction outlives the convicted request: a later request
        with the same prompt content is quarantined at admission —
        zero additional replica kills for a repeated poison."""
        cfg, params = tiny_model
        router = _router(cfg, params, n=3)
        poison = [7, 8, 9, 10]
        sp = SamplingParams(max_new_tokens=6)
        with injected_faults(FaultSpec("serving.step", "poison_request",
                                       pattern=(7, 8, 9))):
            preq = router.submit(poison, sp)
            _settle(router)
            kills_before = router.metrics.snapshot()["failure_events"]
            sibling = router.submit(list(poison), sp)
            _settle(router)
        assert preq.state == FleetRequestState.QUARANTINED
        assert sibling.state == FleetRequestState.QUARANTINED
        assert sibling.quarantine_evidence["convicted_sibling"] is True
        snap = router.metrics.snapshot()
        assert snap["failure_events"] == kills_before  # zero new kills
        assert snap["quarantined"] == 2
        assert snap["lost"] == 0

    def test_benign_suspect_survives_canary_trial_and_is_exonerated(
            self, tiny_model):
        """A request that accrued suspicion by riding along with real
        failures (not by causing them) survives its canary trial:
        it finishes token-identical and its suspicion entry is
        dropped.  Also exercises the ``router.canary_dispatch`` fault
        site: a transient io_error on the first dispatch attempt keeps
        the suspect at the queue head and the next tick retries."""
        cfg, params = tiny_model
        rng = np.random.RandomState(5)
        prompt = list(rng.randint(0, cfg.vocab_size, 8))
        ref = naive_generate(cfg, params, prompt, 5)
        router = _router(cfg, params, n=2)
        sp = SamplingParams(max_new_tokens=5)
        with injected_faults(
                FaultSpec("router.canary_dispatch", "io_error",
                          occurrence=1)):
            req = router.submit(prompt, sp)
            # charge two distinct failure events by hand — the innocent
            # was aboard for two unrelated replica deaths
            router._suspects[req._prompt_key] = {1, 2}
            router.step()              # canary dispatch faults: io_error
            assert req.state == FleetRequestState.PENDING
            assert all(rep.canary_for is None for rep in router.replicas)
            _settle(router)            # retried next tick, then runs
        snap = router.metrics.snapshot()
        assert req.state == FleetRequestState.FINISHED
        assert req.output == ref
        assert req._prompt_key not in router._suspects   # exonerated
        assert snap["canary_dispatches"] == 1
        assert snap["canary_deaths"] == 0
        assert snap["quarantined"] == 0
        assert all(rep.canary_for is None for rep in router.replicas)

    def test_canary_runs_suspect_alone(self, tiny_model):
        """While a suspect is on trial, its reserved replica admits
        nothing else — no innocent is ever co-batched with a suspect."""
        cfg, params = tiny_model
        router = _router(cfg, params, n=2)
        sp = SamplingParams(max_new_tokens=6)
        rng = np.random.RandomState(9)
        suspect = router.submit(list(rng.randint(0, cfg.vocab_size, 8)),
                                sp)
        router._suspects[suspect._prompt_key] = {1, 2}
        others = [router.submit(list(rng.randint(0, cfg.vocab_size, 6)),
                                sp) for _ in range(3)]
        router.step()
        canaries = [rep for rep in router.replicas
                    if rep.canary_for == suspect.id]
        assert len(canaries) == 1
        rep = canaries[0]
        table = router._assigned[rep.replica_id]
        assert set(table) == {suspect.id}  # the suspect rides alone
        assert all(o.replica_id != rep.replica_id
                   for o in others if o.replica_id is not None)
        _settle(router)
        assert suspect.state == FleetRequestState.FINISHED
        assert all(o.state == FleetRequestState.FINISHED for o in others)


# --------------------------------------------- failover re-enqueue order


@pytest.mark.faultinject
class TestFailoverOrder:
    def test_reclaim_re_enqueues_in_admission_order(self, tiny_model):
        """Harvested in-flight requests re-enter the queue at the head
        in their original admission order (ascending request id), not
        the assignment table's dict order."""
        cfg, params = tiny_model
        router = _router(cfg, params, n=2,
                         engine_kw={"max_batch_size": 4})
        rng = np.random.RandomState(2)
        sp = SamplingParams(max_new_tokens=12)
        reqs = [router.submit(list(rng.randint(0, cfg.vocab_size, 6)),
                              sp) for _ in range(6)]
        router.step()                     # dispatch across both replicas
        victim = router.replicas[0]
        aboard = sorted(router._assigned[victim.replica_id])
        assert len(aboard) >= 2           # a multi-request harvest
        # scramble the assignment table's insertion order to prove the
        # re-enqueue does NOT depend on it
        table = router._assigned[victim.replica_id]
        items = list(table.items())[::-1]
        table.clear()
        table.update(items)
        router.kill_replica(0)
        # drive the detection path directly so the harvest is
        # observable in _pending before the next admission pass
        router._on_replica_failure(victim, "killed",
                                   OSError("replica 0 process is dead"))
        moved = [f.id for f in router._pending
                 if f.id in set(aboard)]
        assert moved == aboard            # ascending admission order
        _settle(router)
        assert all(r.state == FleetRequestState.FINISHED for r in reqs)
        assert router.metrics.snapshot()["lost"] == 0


# ------------------------------------------------- breaker + health fold


@pytest.mark.faultinject
class TestBreakerHealthFold:
    def test_fleet_health_soft_breaker_and_healthz_200(self, tiny_model):
        """An open cascade breaker with >= 1 admittable replica is a
        soft condition: /fleet exposes quarantine count + breaker
        state, and /healthz stays 200 because the fleet still serves
        (suspects drain through canary mode; innocents keep going)."""
        cfg, params = tiny_model
        registry = MetricsRegistry()
        # a LONG window so the breaker is still open after the poison
        # is contained — observable state, not a race
        router = _router(cfg, params, n=3, registry=registry,
                         cascade_window_s=60.0)
        server = start_telemetry_server(port=0, router=router,
                                        registry=registry,
                                        tracer=router.tracer)
        try:
            rng = np.random.RandomState(1)
            sp = SamplingParams(max_new_tokens=6)
            poison = [7, 8, 9, 10]
            with injected_faults(
                    FaultSpec("serving.step", "poison_request",
                              pattern=(7, 8, 9))):
                preq = router.submit(poison, sp)
                innocent = router.submit(
                    list(rng.randint(0, cfg.vocab_size, 6)), sp)
                _settle(router)
            assert preq.state == FleetRequestState.QUARANTINED
            assert innocent.state == FleetRequestState.FINISHED
            assert router.cascade_open()   # 60s window: still open
            fh = router.fleet_health()
            assert fh["cascade_breaker_open"] is True
            assert fh["quarantined"] == 1
            assert fh["suspects"] == 0     # drained, not lingering
            assert fh["healthy"] is True   # soft: fleet still admits

            base = f"http://127.0.0.1:{server.port}"
            with urllib.request.urlopen(base + "/healthz") as resp:
                assert resp.status == 200
                body = json.loads(resp.read())
            assert body["healthy"] is True
            assert body["cascade_breaker_open"] is True
            assert body["quarantined"] == 1
            with urllib.request.urlopen(base + "/fleet") as resp:
                fleet = json.loads(resp.read())
            assert fleet["quarantined"] == 1
            assert fleet["cascade_breaker_open"] is True
            assert fleet["counters"]["quarantined"] == 1
        finally:
            server.shutdown()

    def test_breaker_closes_when_window_drains(self, tiny_model):
        """With no failures left in the window, no canary in flight and
        no queued suspects, the breaker closes and the router::cascade
        trace ends with the quarantine tally."""
        cfg, params = tiny_model
        clock = [0.0]
        router = _router(cfg, params, n=3, cascade_window_s=2.0,
                         clock=lambda: clock[0])
        sp = SamplingParams(max_new_tokens=6)
        with injected_faults(FaultSpec("serving.step", "poison_request",
                                       pattern=(7, 8, 9))):
            preq = router.submit([7, 8, 9, 10], sp)
            for _ in range(400):
                if not router.has_work():
                    break
                clock[0] += 0.01
                router.step()
        assert preq.state == FleetRequestState.QUARANTINED
        assert router.cascade_open()
        clock[0] += 5.0                    # window empties
        router.step()
        assert not router.cascade_open()
        snap = router.metrics.snapshot()
        assert snap["cascade_breaker_opens"] == 1
        assert snap["cascade_breaker_open"] == 0
        cascade = [t for t in router.tracer.traces()
                   if t["name"] == "router::cascade"]
        assert cascade
        root = cascade[0]["spans"][0]
        assert root["attributes"]["quarantined_total"] == 1
