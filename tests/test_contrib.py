"""contrib tests: quantization (QAT/PTQ) + ASP sparsity + DDP bucketing +
hybrid mesh."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


class TestFakeQuant:
    def test_quantize_dequantize(self):
        import jax.numpy as jnp

        from paddle_tpu.contrib.quant import fake_quant

        x = jnp.asarray(np.linspace(-1, 1, 9, dtype=np.float32))
        out = np.asarray(fake_quant(x, jnp.float32(1.0), 8))
        # values snap to the 127-level grid, endpoints exact
        np.testing.assert_allclose(out[[0, -1]], [-1.0, 1.0], atol=1e-6)
        err = np.abs(out - np.asarray(x)).max()
        assert 0 < err < 1.0 / 127

    def test_straight_through_gradient(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.contrib.quant import fake_quant

        g = jax.grad(lambda x: fake_quant(x, jnp.float32(1.0), 8).sum())(
            jnp.asarray([0.5, 2.0], jnp.float32))
        np.testing.assert_allclose(np.asarray(g), [1.0, 0.0])  # STE clips


class TestQAT:
    def _net(self):
        paddle.seed(0)
        return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))

    def test_quantize_swaps_linears(self):
        from paddle_tpu.contrib import QAT, QuantizedLinear

        net = self._net()
        QAT().quantize(net)
        kinds = [type(l).__name__ for l in net]
        assert kinds.count("QuantizedLinear") == 2

    def test_qat_forward_close_and_trainable(self):
        from paddle_tpu.contrib import QAT, quant_scales
        from paddle_tpu.contrib.quant import quant_scales

        net = self._net()
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 8).astype(np.float32))
        ref = np.asarray(net(x).data)
        QAT().quantize(net)
        out = net(x)
        np.testing.assert_allclose(np.asarray(out.data), ref, atol=0.1)
        # gradients flow to the shared fp weights
        loss = (out ** 2).mean()
        loss.backward()
        inner = net[0].inner
        assert inner.weight.grad is not None
        assert float(np.abs(np.asarray(inner.weight.grad.data)).sum()) > 0
        scales = quant_scales(net)
        assert len(scales) == 2 and all(
            s["weight"] > 0 for s in scales.values())

    def test_ptq_calibrate_and_convert(self):
        from paddle_tpu.contrib import PTQ

        net = self._net()
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(16, 8).astype(np.float32))
        ref = np.asarray(net(x).data)
        ptq = PTQ()
        ptq.quantize(net)
        net(x)                      # calibration pass
        assert len(ptq.scales()) == 2
        ptq.convert(net)
        out = np.asarray(net(x).data)
        np.testing.assert_allclose(out, ref, atol=0.15)


class TestASP:
    def test_create_mask_2_4(self):
        from paddle_tpu.contrib import check_mask, create_mask

        w = np.random.RandomState(0).randn(16, 16).astype(np.float32)
        mask = create_mask(w)
        assert mask.sum() == w.size // 2          # exactly 2 of 4 kept
        assert check_mask(w * mask)
        assert not check_mask(w)                  # dense fails the check
        # the kept entries are the 2 largest |w| of each group
        flat_w = np.abs(w.reshape(-1, 4))
        flat_m = mask.reshape(-1, 4)
        for i in range(flat_w.shape[0]):
            kept = set(np.nonzero(flat_m[i])[0])
            top2 = set(np.argsort(-flat_w[i])[:2])
            assert kept == top2

    def test_prune_and_decorate_keeps_sparsity(self):
        from paddle_tpu.contrib import check_mask, decorate, prune_model

        paddle.seed(2)
        net = nn.Sequential(nn.Linear(16, 16), nn.ReLU(), nn.Linear(16, 4))
        prune_model(net)
        assert check_mask(net[0].weight)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        decorate(opt, net)
        x = paddle.to_tensor(
            np.random.RandomState(3).randn(8, 16).astype(np.float32))
        for _ in range(3):
            loss = (net(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        # masks survived the optimizer updates
        assert check_mask(net[0].weight)
        assert check_mask(net[2].weight)


class TestBucketsAndHybridMesh:
    def test_grad_buckets_fuse(self):
        import paddle_tpu.distributed as dist

        paddle.seed(4)
        net = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 8),
                            nn.Linear(8, 2))
        dp = dist.DataParallel(net, comm_buffer_size=25)
        x = paddle.to_tensor(np.ones((2, 8), np.float32))
        (dp(x) ** 2).mean().backward()
        buckets = dp._grad_buckets()
        # all fp32 grads fit one 25MB bucket: ONE fused allreduce
        assert len(buckets) == 1
        assert len(buckets[0]) == 6
        g_before = np.asarray(net[0].weight.grad.data).copy()
        dp.apply_collective_grads()   # 1 process: identity
        np.testing.assert_allclose(np.asarray(net[0].weight.grad.data),
                                   g_before, atol=0)

    def test_unused_params_raise_without_flag(self):
        import paddle_tpu.distributed as dist

        paddle.seed(4)

        class Partial(nn.Layer):
            def __init__(self):
                super().__init__()
                self.used = nn.Linear(8, 2)
                self.unused = nn.Linear(8, 2)

            def forward(self, x):
                return self.used(x)

        net = Partial()
        dp = dist.DataParallel(net)
        x = paddle.to_tensor(np.ones((2, 8), np.float32))
        (dp(x) ** 2).mean().backward()
        with pytest.raises(RuntimeError, match="find_unused_parameters"):
            dp.apply_collective_grads()

    def test_unused_params_zero_filled_with_flag(self):
        import paddle_tpu.distributed as dist

        paddle.seed(4)

        class Partial(nn.Layer):
            def __init__(self):
                super().__init__()
                self.used = nn.Linear(8, 2)
                self.unused = nn.Linear(8, 2)

            def forward(self, x):
                return self.used(x)

        net = Partial()
        dp = dist.DataParallel(net, find_unused_parameters=True)
        x = paddle.to_tensor(np.ones((2, 8), np.float32))
        (dp(x) ** 2).mean().backward()
        assert net.unused.weight.grad is None
        dp.apply_collective_grads()
        # zero-filled so every rank all-reduces an identical bucket set
        np.testing.assert_array_equal(
            np.asarray(net.unused.weight.grad.data), 0.0)

    def test_tiny_buffer_splits_buckets(self):
        import paddle_tpu.distributed as dist

        paddle.seed(4)
        net = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 2))
        dp = dist.DataParallel(net, comm_buffer_size=1e-5)
        x = paddle.to_tensor(np.ones((2, 8), np.float32))
        (dp(x) ** 2).mean().backward()
        assert len(dp._grad_buckets()) > 1

    def test_hybrid_mesh_single_slice(self):
        import jax

        from paddle_tpu.distributed.topology import build_hybrid_mesh

        mesh = build_hybrid_mesh(ici=dict(dp=2, mp=4))
        assert mesh.axis_names == ("dp", "pp", "sharding", "sep", "ep",
                                   "mp")
        assert mesh.devices.shape == (2, 1, 1, 1, 1, 4)


class TestQuantEdgeCases:
    def test_attribute_style_model_quantized(self):
        """QAT must swap the layer in BOTH registries (review repro)."""
        from paddle_tpu.contrib import QAT, QuantizedLinear

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                paddle.seed(0)
                self.fc = nn.Linear(8, 4)

            def forward(self, x):
                return self.fc(x)

        net = Net()
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 8).astype(np.float32))
        ref = np.asarray(net(x).data)
        QAT().quantize(net)
        assert isinstance(net.fc, QuantizedLinear)   # attribute swapped
        out = np.asarray(net(x).data)
        assert np.abs(out - ref).max() > 0           # really quantized

    def test_converted_scales_frozen(self):
        from paddle_tpu.contrib import PTQ

        paddle.seed(1)
        net = nn.Sequential(nn.Linear(8, 4))
        ptq = PTQ()
        ptq.quantize(net)
        big = paddle.to_tensor(np.full((4, 8), 23.0, np.float32))
        net(big)                      # calibration sees the outlier
        ptq.convert(net)
        s0 = net[0]._a_scale.scale
        small = paddle.to_tensor(np.full((4, 8), 0.1, np.float32))
        for _ in range(5):
            net(small)
        assert net[0]._a_scale.scale == s0   # no drift after convert

    def test_uncalibrated_raises(self):
        from paddle_tpu.contrib.quant import QuantizedLinear

        paddle.seed(2)
        q = QuantizedLinear(nn.Linear(4, 2))
        import jax

        with pytest.raises(RuntimeError, match="calibrate"):
            jax.eval_shape(
                lambda a: q(paddle.Tensor(a)).data,
                jax.ShapeDtypeStruct((2, 4), np.float32))
