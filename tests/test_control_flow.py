"""Static control flow: cond / while_loop ops in the Program IR.

Reference strategy: unittests/test_cond.py and test_while_loop.py run
the same construct in dygraph and static mode and compare against a
Python reference; conditional_block/while ops execute sub-blocks with
scope-hierarchy lookup — here child Programs lowered onto
jax.lax.cond/while_loop.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.core.tensor import Tensor


def _run(program, feed, fetch):
    exe = static.Executor()
    return exe.run(program, feed=feed, fetch_list=fetch)


class TestEager:
    def test_cond_eager(self):
        x = paddle.to_tensor(3.0)
        out = static.cond(x > 2.0, lambda: x * 2.0, lambda: x - 1.0)
        assert float(out.data) == 6.0
        out = static.cond(x > 5.0, lambda: x * 2.0, lambda: x - 1.0)
        assert float(out.data) == 2.0

    def test_while_eager(self):
        i = paddle.to_tensor(0.0)
        s = paddle.to_tensor(1.0)
        i, s = static.while_loop(lambda i, s: i < 4.0,
                                 lambda i, s: (i + 1.0, s * 2.0), [i, s])
        assert float(i.data) == 4.0 and float(s.data) == 16.0

    def test_case_and_switch_eager(self):
        x = paddle.to_tensor(1.0)
        out = static.nn.case(
            [(x > 2.0, lambda: x * 10.0), (x > 0.0, lambda: x + 1.0)],
            default=lambda: x)
        assert float(out.data) == 2.0


class TestCapturedCond:
    def test_cond_matches_eager_both_ways(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4], "float32")
            pred = (x.sum() > 0.0)
            out = static.cond(pred, lambda: x * 2.0, lambda: x - 1.0)
        pos = np.ones(4, np.float32)
        neg = -np.ones(4, np.float32)
        (r_pos,) = _run(prog, {"x": pos}, [out])
        (r_neg,) = _run(prog, {"x": neg}, [out])
        np.testing.assert_allclose(r_pos, pos * 2.0)
        np.testing.assert_allclose(r_neg, neg - 1.0)

    def test_branch_mismatch_is_loud(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4], "float32")
            with pytest.raises(ValueError, match="mismatch"):
                static.cond(x.sum() > 0, lambda: x.reshape([2, 2]),
                            lambda: x * 1.0)
            with pytest.raises(ValueError, match="same number"):
                static.cond(x.sum() > 0, lambda: (x, x), lambda: x)

    def test_params_inside_branch_stay_live(self):
        """A Layer parameter read inside a branch must see optimizer
        updates between runs (scope semantics through the sub-block)."""
        import paddle_tpu.nn as nn

        lin = nn.Linear(4, 4)
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4], "float32")
            out = static.cond(x.sum() > 0.0,
                              lambda: lin(x).sum(),
                              lambda: x.sum() * 0.0)
        feed = {"x": np.ones(4, np.float32)}
        (before,) = _run(prog, feed, [out])
        with paddle.no_grad():
            lin.weight.set_value(Tensor(lin.weight.data * 2.0))
            lin.bias.set_value(Tensor(lin.bias.data * 2.0))
        (after,) = _run(prog, feed, [out])
        np.testing.assert_allclose(after, before * 2.0, rtol=1e-6)

    def test_switch_case_captured(self):
        prog = static.Program()
        with static.program_guard(prog):
            idx = static.data("i", [], "int32")
            x = static.data("x", [3], "float32")
            out = static.nn.switch_case(
                idx, {0: lambda: x + 1.0, 1: lambda: x * 10.0},
                default=lambda: x * 0.0)
        xs = np.array([1.0, 2.0, 3.0], np.float32)
        (r0,) = _run(prog, {"i": np.int32(0), "x": xs}, [out])
        (r1,) = _run(prog, {"i": np.int32(1), "x": xs}, [out])
        (r9,) = _run(prog, {"i": np.int32(9), "x": xs}, [out])
        np.testing.assert_allclose(r0, xs + 1.0)
        np.testing.assert_allclose(r1, xs * 10.0)
        np.testing.assert_allclose(r9, xs * 0.0)


class TestCapturedWhile:
    def test_while_matches_eager(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [], "float32")
            i0 = paddle.to_tensor(0.0)
            i, acc = static.while_loop(
                lambda i, acc: i < x,          # x is a free outer var
                lambda i, acc: (i + 1.0, acc + i),
                [i0, paddle.to_tensor(0.0)])
        (r,) = _run(prog, {"x": np.float32(5.0)}, [acc])
        assert float(r) == 0 + 1 + 2 + 3 + 4

    def test_loop_until_converged_model(self):
        """The VERDICT acceptance bar: a loop-until-converged model
        compiles (data-dependent trip count under jit) and matches the
        eager Python loop.  Newton iteration for sqrt(a)."""
        def newton_sqrt_eager(a, tol):
            x = a / 2.0
            while abs(x * x - a) > tol:
                x = 0.5 * (x + a / x)
            return x

        prog = static.Program()
        with static.program_guard(prog):
            a = static.data("a", [], "float32")
            tol = static.data("tol", [], "float32")
            (x,) = static.while_loop(
                lambda x: (x * x - a).abs() > tol,
                lambda x: (0.5 * (x + a / x),),
                [a / 2.0])
        for val in (9.0, 2.0, 100.0):
            (r,) = _run(prog, {"a": np.float32(val),
                               "tol": np.float32(1e-4)}, [x])
            expect = newton_sqrt_eager(val, 1e-4)
            np.testing.assert_allclose(r, expect, rtol=1e-5)
            np.testing.assert_allclose(r, np.sqrt(val), rtol=1e-3)

    def test_carry_signature_change_is_loud(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4], "float32")
            with pytest.raises(ValueError, match="shape-static"):
                static.while_loop(lambda v: v.sum() < 10.0,
                                  lambda v: (v.reshape([2, 2]),), [x])

    def test_nested_cond_in_while(self):
        """Collatz step count — cond nested inside while, both captured."""
        def collatz_eager(n):
            steps = 0
            while n != 1:
                n = n // 2 if n % 2 == 0 else 3 * n + 1
                steps += 1
            return steps

        prog = static.Program()
        with static.program_guard(prog):
            n0 = static.data("n", [], "int32")
            n, steps = static.while_loop(
                lambda n, s: n != 1,
                lambda n, s: (
                    static.cond((n % 2) == 0,
                                lambda: n // 2,
                                lambda: 3 * n + 1),
                    s + 1),
                [n0, paddle.to_tensor(np.int32(0))])
        for val in (6, 27):
            (r,) = _run(prog, {"n": np.int32(val)}, [steps])
            assert int(r) == collatz_eager(val)


class TestTraceGuard:
    def test_branch_on_traced_tensor_is_loud(self):
        import paddle_tpu.jit as jit

        @jit.to_static
        def f(x):
            if x.sum() > 0:          # Python branch on a traced value
                return x * 2.0
            return x

        with pytest.raises(Exception, match="cond"):
            f(paddle.to_tensor(np.ones(4, np.float32)))


class TestReviewRegressions:
    def test_case_without_default_under_capture(self):
        """case(default=None) uses the LAST pair's fn as the default
        (reference semantics) instead of erroring on an empty branch."""
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4], "float32")
            out = static.nn.case([(x.sum() > 0.0, lambda: x * 2.0),
                                  (x.sum() <= 0.0, lambda: x - 1.0)])
        pos = np.ones(4, np.float32)
        neg = -np.ones(4, np.float32)
        (r_pos,) = _run(prog, {"x": pos}, [out])
        (r_neg,) = _run(prog, {"x": neg}, [out])
        np.testing.assert_allclose(r_pos, pos * 2.0)
        np.testing.assert_allclose(r_neg, neg - 1.0)

    def test_inner_block_tensor_escape_is_loud(self):
        """Using a tensor computed inside a branch after the cond must
        raise (scope rules), not silently bake a stale value."""
        leak = []
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4], "float32")

            def tf():
                h = x * 2.0
                leak.append(h)
                return h

            static.cond(x.sum() > 0.0, tf, lambda: x * 1.0)
            with pytest.raises(RuntimeError, match="sub-block"):
                _ = leak[0] + 1.0


class TestPartialGradHookGate:
    def test_hook_on_nontarget_pruned_intermediate_does_not_fire(self):
        """A hooked intermediate that is NOT a grad target and whose
        producer got pruned holds only a PARTIAL cotangent — its hook
        must not fire with that wrong value."""
        import paddle_tpu as paddle
        from paddle_tpu.core.autograd import grad as fgrad

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        x.stop_gradient = False
        w = paddle.to_tensor(np.array([3.0, 4.0], np.float32))
        w.stop_gradient = False
        m = w * 2.0
        fired = []
        m.register_hook(lambda g: fired.append(np.asarray(g.data)))
        out = (x * m).sum() + (m * m).sum()
        (g,) = fgrad(out, [x])
        np.testing.assert_allclose(np.asarray(g.data), [6.0, 8.0])  # = m
        assert fired == []   # partial cotangent: hook must stay silent

    def test_cond_and_while_work_under_to_static(self):
        """The guard error tells users to reach for static.nn.cond /
        while_loop — they must actually work inside jit.to_static (no
        program_guard, live jax trace)."""
        import paddle_tpu.jit as jit

        @jit.to_static
        def f(x):
            doubled = static.cond(x.sum() > 0.0, lambda: x * 2.0,
                                  lambda: x - 1.0)
            (count,) = static.while_loop(
                lambda c: c.sum() < 20.0, lambda c: (c + doubled.sum(),),
                [doubled * 0.0])
            return count

        pos = paddle.to_tensor(np.ones(4, np.float32))
        out = f(pos)
        # doubled = 2s, sum 8; count grows by 8/elem until sum >= 20:
        # 0 -> 8*4=32 per tick summed... count vec adds 8 each tick;
        # sum(count) hits 32 after one tick < 20? 32 >= 20 -> one tick
        np.testing.assert_allclose(np.asarray(out.data), np.full(4, 8.0))
