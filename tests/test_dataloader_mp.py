"""Multiprocess DataLoader (parity: fluid/dataloader/dataloader_iter.py:341
_DataLoaderIterMultiProcess: worker processes, shared memory, order
preservation, error propagation, worker_info)."""
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.io import DataLoader, Dataset, IterableDataset, \
    get_worker_info


class SquareDataset(Dataset):
    def __init__(self, n=32):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        # a Python-heavy transform stand-in
        x = np.full((8,), float(i), np.float32)
        return x * x, np.int64(i)


class FailingDataset(SquareDataset):
    def __getitem__(self, i):
        if i == 7:
            raise ValueError("poisoned sample 7")
        return super().__getitem__(i)


class CountStream(IterableDataset):
    """Iterable dataset sharded across workers via get_worker_info
    (reference worker.py semantics: each worker iterates its own
    replica; unsharded streams duplicate num_workers times)."""

    def __init__(self, n=24):
        self.n = n

    def __iter__(self):
        info = get_worker_info()
        wid = info.id if info else 0
        nw = info.num_workers if info else 1
        for i in range(self.n):
            if i % nw == wid:
                yield np.full((4,), float(i), np.float32)


def _collect(loader):
    out = []
    for batch in loader:
        x = batch[0] if isinstance(batch, (tuple, list)) else batch
        assert isinstance(x, Tensor)
        out.append(np.asarray(x.data))
    return out


class TestMultiprocess:
    def test_matches_inline_order_and_values(self):
        ds = SquareDataset(32)
        ref = _collect(DataLoader(ds, batch_size=4, num_workers=0))
        got = _collect(DataLoader(ds, batch_size=4, num_workers=3))
        assert len(got) == len(ref)
        for a, b in zip(got, ref):
            np.testing.assert_array_equal(a, b)

    def test_no_shared_memory_path(self):
        ds = SquareDataset(16)
        ref = _collect(DataLoader(ds, batch_size=4, num_workers=0))
        got = _collect(DataLoader(ds, batch_size=4, num_workers=2,
                                  use_shared_memory=False))
        for a, b in zip(got, ref):
            np.testing.assert_array_equal(a, b)

    def test_worker_error_propagates_with_traceback(self):
        loader = DataLoader(FailingDataset(16), batch_size=4,
                            num_workers=2)
        with pytest.raises(RuntimeError, match="poisoned sample 7"):
            _collect(loader)

    def test_worker_init_fn_and_worker_info(self):
        calls = []

        def init(worker_id):
            calls.append(worker_id)  # runs in the CHILD: not visible here
            assert get_worker_info().id == worker_id
            assert get_worker_info().num_workers == 2

        loader = DataLoader(SquareDataset(8), batch_size=2, num_workers=2,
                            worker_init_fn=init)
        out = _collect(loader)
        assert len(out) == 4

    def test_iterable_sharded_covers_dataset_once(self):
        """A worker_info-sharded stream: every sample exactly once across
        the interleaved worker streams (no double-sharding)."""
        got = _collect(DataLoader(CountStream(24), batch_size=4,
                                  num_workers=3))
        seen = sorted(v for b in got for v in b[:, 0])
        assert seen == [float(i) for i in range(24)]

    def test_iterable_unsharded_duplicates_like_reference(self):
        """An UNsharded iterable stream is replicated per worker (the
        documented reference semantics) — each sample appears
        num_workers times."""
        class Plain(IterableDataset):
            def __iter__(self):
                for i in range(8):
                    yield np.full((2,), float(i), np.float32)

        got = _collect(DataLoader(Plain(), batch_size=4, num_workers=2))
        seen = sorted(v for b in got for v in b[:, 0])
        assert seen == sorted([float(i) for i in range(8)] * 2)

    def test_gil_heavy_transform_scales(self):
        """Smoke (not a timing assert): a CPU-burning transform completes
        through the process pool; correctness of values is the check."""
        class Burn(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                t0 = time.perf_counter()
                acc = 0.0
                while time.perf_counter() - t0 < 0.02:
                    acc += i
                return np.full((4,), float(i), np.float32)

        got = _collect(DataLoader(Burn(), batch_size=2, num_workers=4))
        assert len(got) == 4
        np.testing.assert_array_equal(
            got[0], np.stack([np.full((4,), 0.0), np.full((4,), 1.0)]))

    def test_thread_fallback_still_works(self):
        ds = SquareDataset(16)
        ref = _collect(DataLoader(ds, batch_size=4, num_workers=0))
        got = _collect(DataLoader(ds, batch_size=4, num_workers=2,
                                  use_thread_workers=True))
        for a, b in zip(got, ref):
            np.testing.assert_array_equal(a, b)


class TestEarlyAbandon:
    def test_early_break_unlinks_pending_shm(self):
        """Breaking after one batch must not leak /dev/shm segments from
        prefetched-but-unconsumed results."""
        import glob

        before = set(glob.glob("/dev/shm/psm_*"))
        loader = DataLoader(SquareDataset(64), batch_size=4, num_workers=3)
        it = iter(loader)
        next(it)
        it.close()
        time.sleep(0.3)
        after = set(glob.glob("/dev/shm/psm_*"))
        assert after - before == set(), f"leaked: {after - before}"


class TestWorkerSafety:
    def test_tensor_in_worker_is_loud_not_deadlocked(self):
        """Dataset code constructing a Tensor inside a forked worker must
        raise the directed error (a device-put would hang forever)."""
        class TensorDataset(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return paddle.to_tensor(np.ones(4, np.float32) * i)

        loader = DataLoader(TensorDataset(), batch_size=2, num_workers=2,
                            timeout=30)
        with pytest.raises(RuntimeError,
                           match="Tensor construction inside a DataLoader"):
            _collect(loader)

    def test_sigkilled_worker_raises_not_hangs(self):
        """A worker killed by the OS (no error message possible) must
        surface as RuntimeError via the liveness poll, not hang."""
        class Killer(Dataset):
            def __len__(self):
                return 16

            def __getitem__(self, i):
                if i == 5:
                    os._exit(137)      # simulates SIGKILL/OOM
                time.sleep(0.01)
                return np.ones(2, np.float32)

        loader = DataLoader(Killer(), batch_size=4, num_workers=2)
        with pytest.raises(RuntimeError, match="worker process died"):
            _collect(loader)

    def test_killed_worker_error_names_the_worker_promptly(self):
        """A SIGKILLed worker must be named in the error (which worker
        to look at in the OOM-killer log) and surface within the
        liveness-poll budget, not after a long timeout."""
        class Killer(Dataset):
            def __len__(self):
                return 16

            def __getitem__(self, i):
                from paddle_tpu.io.multiprocess import get_worker_info

                if get_worker_info().id == 1:
                    os._exit(137)
                time.sleep(0.01)
                return np.ones(2, np.float32)

        loader = DataLoader(Killer(), batch_size=4, num_workers=2)
        t0 = time.monotonic()
        with pytest.raises(RuntimeError,
                           match=r"DataLoader worker 1 failed") as ei:
            _collect(loader)
        assert "exitcode 137" in str(ei.value)
        assert time.monotonic() - t0 < 20.0

    def test_clean_exit_without_batch_raises_not_hangs(self):
        """Regression: a worker that exits CLEANLY mid-epoch (exitcode
        0 — dataset code calling sys.exit) left _get() blocking forever
        with the default timeout=None, because the liveness poll only
        looked for nonzero exit codes.  All-dead + empty queue must
        raise."""
        class CleanExit(Dataset):
            def __len__(self):
                return 16

            def __getitem__(self, i):
                if i >= 4:
                    os._exit(0)        # clean death, no "done" marker
                time.sleep(0.01)
                return np.ones(2, np.float32)

        loader = DataLoader(CleanExit(), batch_size=4, num_workers=2)
        t0 = time.monotonic()
        with pytest.raises(RuntimeError,
                           match="without producing the awaited batch"):
            _collect(loader)
        assert time.monotonic() - t0 < 20.0

    def test_iterable_early_break_unlinks_worker_held_shm(self):
        """Iterable mode + bounded queue: a worker blocked in put() holds
        a segment whose name hasn't reached the parent — the cooperative
        stop must let it through for unlinking (review r4 regression)."""
        import glob

        class BigStream(IterableDataset):
            def __iter__(self):
                for i in range(500):
                    yield np.full((256,), float(i), np.float32)

        before = set(glob.glob("/dev/shm/psm_*"))
        loader = DataLoader(BigStream(), batch_size=4, num_workers=2)
        it = iter(loader)
        next(it)
        time.sleep(0.5)          # let workers run ahead and fill the queue
        it.close()
        time.sleep(0.3)
        after = set(glob.glob("/dev/shm/psm_*"))
        assert after - before == set(), f"leaked: {after - before}"


class TestIndustrialDatasets:
    def _write_slot_files(self, tmp_path, n_files=2, lines=6):
        files = []
        for fi in range(n_files):
            f = tmp_path / f"part-{fi}.txt"
            rows = []
            for i in range(lines):
                uid = fi * lines + i
                rows.append(f"click:{uid % 2} slot1:{uid} slot1:{uid+100} "
                            f"dense:{uid/10:.2f}")
            f.write_text("\n".join(rows) + "\n")
            files.append(str(f))
        return files

    def test_in_memory_load_shuffle_iterate(self, tmp_path):
        from paddle_tpu.io import DataLoader, InMemoryDataset

        files = self._write_slot_files(tmp_path)
        ds = InMemoryDataset()
        ds.init(use_slots=["click", "slot1", "dense"],
                dense_slots=("dense",))
        ds.set_filelist([str(tmp_path / "part-*.txt")])
        ds.load_into_memory()
        assert ds.get_memory_data_size() == 12
        ids_before = [int(ds[i]["slot1"][0]) for i in range(12)]
        ds.local_shuffle(seed=3)
        ids_after = [int(ds[i]["slot1"][0]) for i in range(12)]
        assert sorted(ids_after) == sorted(ids_before)   # same multiset
        assert ids_after != ids_before                   # actually moved
        order = [int(ds[i]["click"][0]) for i in range(12)]
        assert sorted(order) == [0] * 6 + [1] * 6
        assert ds[0]["dense"].dtype == np.float32
        # feeds the regular loader stack unchanged
        loader = DataLoader(ds, batch_size=4,
                            collate_fn=lambda b: b)    # ragged: no stack
        assert sum(len(b) for b in loader) == 12

    def test_queue_dataset_streams_and_shards(self, tmp_path):
        from paddle_tpu.io import DataLoader, QueueDataset

        files = self._write_slot_files(tmp_path)
        ds = QueueDataset()
        ds.init(parse_fn=lambda line: np.asarray(
            [float(t.split(":")[1]) for t in line.split()[:1]], np.float32))
        ds.set_filelist(files)
        # single process sees every line once
        seen = [float(s[0]) for s in ds]
        assert len(seen) == 12
        # through the multiprocess loader with worker sharding
        got = _collect(DataLoader(ds, batch_size=3, num_workers=2))
        assert sum(b.shape[0] for b in got) == 12

    def test_unloaded_access_is_loud(self):
        from paddle_tpu.io import InMemoryDataset

        with pytest.raises(RuntimeError, match="load_into_memory"):
            len(InMemoryDataset())

    def test_global_shuffle_guards(self, tmp_path):
        from paddle_tpu.io import InMemoryDataset

        self._write_slot_files(tmp_path)
        ds = InMemoryDataset()
        ds.init(use_slots=["click"])
        ds.set_filelist([str(tmp_path / "part-*.txt")])
        ds.load_into_memory()
        n = ds.get_memory_data_size()
        ds.global_shuffle()                 # 1 process: decorrelated local
        assert ds.get_memory_data_size() == n
        with pytest.raises(NotImplementedError, match="pipe_command"):
            InMemoryDataset().init(pipe_command="awk ...")
        with pytest.raises(TypeError, match="unknown init"):
            InMemoryDataset().init(bogus=1)
