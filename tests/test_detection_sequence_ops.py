"""NumPy-golden OpTests for the detection + sequence + CTC + beam-search
pack (VERDICT r4 item 7; reference test strategy: OpTest compares each
kernel against a hand-written numpy model).

Golden oracles: scalar-loop numpy reimplementations (roi_align,
yolo_box, box_coder, prior_box), torch.nn.functional.ctc_loss (CPU), and
hand-computed lattices (beam search)."""
import numpy as np
import pytest

from paddle_tpu.ops.loss import ctc_loss
from paddle_tpu.ops.search import beam_search, beam_search_step
from paddle_tpu.ops.sequence import (sequence_expand, sequence_mask,
                                     sequence_pad, sequence_pool,
                                     sequence_reverse, sequence_softmax,
                                     sequence_unpad)
from paddle_tpu.vision.detection_ops import (box_coder, prior_box,
                                             roi_align, yolo_box)


# ------------------------------------------------------------ roi_align


def _roi_align_np(x, boxes, batch_idx, out_size, scale, samples, aligned):
    """Scalar-loop golden model."""
    N, C, H, W = x.shape
    R = boxes.shape[0]
    out = np.zeros((R, C, out_size, out_size), np.float32)

    def bil(feat, y, xx):
        if y < -1.0 or y > H or xx < -1.0 or xx > W:
            return np.zeros((C,), np.float32)
        y, xx = min(max(y, 0.0), H - 1), min(max(xx, 0.0), W - 1)
        y0, x0 = int(np.floor(y)), int(np.floor(xx))
        y1, x1 = min(y0 + 1, H - 1), min(x0 + 1, W - 1)
        ly, lx = y - y0, xx - x0
        return (feat[:, y0, x0] * (1 - ly) * (1 - lx)
                + feat[:, y0, x1] * (1 - ly) * lx
                + feat[:, y1, x0] * ly * (1 - lx)
                + feat[:, y1, x1] * ly * lx)

    off = 0.5 if aligned else 0.0
    for r in range(R):
        feat = x[batch_idx[r]]
        x1, y1, x2, y2 = boxes[r] * scale - off
        rw, rh = x2 - x1, y2 - y1
        if not aligned:
            rw, rh = max(rw, 1.0), max(rh, 1.0)
        bh, bw = rh / out_size, rw / out_size
        for ph in range(out_size):
            for pw in range(out_size):
                acc = np.zeros((C,), np.float32)
                for iy in range(samples):
                    for ix in range(samples):
                        yy = y1 + (ph + (iy + 0.5) / samples) * bh
                        xx = x1 + (pw + (ix + 0.5) / samples) * bw
                        acc += bil(feat, yy, xx)
                out[r, :, ph, pw] = acc / (samples * samples)
    return out


class TestRoiAlign:
    @pytest.mark.parametrize("aligned", [True, False])
    def test_matches_numpy(self, aligned):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 16, 16).astype(np.float32)
        boxes = np.array([[1.0, 1.0, 10.0, 12.0],
                          [0.0, 0.0, 31.0, 31.0],
                          [4.5, 3.2, 20.0, 25.0]], np.float32)
        boxes_num = np.array([2, 1])
        got = np.asarray(roi_align(x, boxes, boxes_num, output_size=4,
                                   spatial_scale=0.5, sampling_ratio=2,
                                   aligned=aligned))
        want = _roi_align_np(x, boxes, [0, 0, 1], 4, 0.5, 2, aligned)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_output_shape_and_jit(self):
        import jax

        x = np.zeros((1, 2, 8, 8), np.float32)
        boxes = np.zeros((5, 4), np.float32)
        f = jax.jit(lambda x, b: roi_align(x, b, output_size=7))
        assert f(x, boxes).shape == (5, 2, 7, 7)


# ------------------------------------------------------------- yolo_box


class TestYoloBox:
    def test_matches_numpy(self):
        rng = np.random.RandomState(1)
        N, A, H, W, ncls = 1, 2, 3, 3, 4
        anchors = [10, 13, 16, 30]
        x = rng.randn(N, A * (5 + ncls), H, W).astype(np.float32)
        img = np.array([[96, 96]], np.float32)
        boxes, scores = yolo_box(x, img, anchors, ncls, conf_thresh=0.0,
                                 downsample_ratio=32, clip_bbox=False)
        boxes, scores = np.asarray(boxes), np.asarray(scores)

        def sig(v):
            return 1 / (1 + np.exp(-v))

        p = x.reshape(N, A, 5 + ncls, H, W)
        # check one cell by hand: anchor a=1, cell (i=2, j=1)
        a, i, j = 1, 2, 1
        cx = (sig(p[0, a, 0, i, j]) + j) / W * 96
        cy = (sig(p[0, a, 1, i, j]) + i) / H * 96
        bw = np.exp(p[0, a, 2, i, j]) * anchors[2] / (32 * W) * 96
        bh = np.exp(p[0, a, 3, i, j]) * anchors[3] / (32 * H) * 96
        k = a * H * W + i * W + j
        np.testing.assert_allclose(
            boxes[0, k], [cx - bw / 2, cy - bh / 2, cx + bw / 2,
                          cy + bh / 2], rtol=1e-5)
        np.testing.assert_allclose(
            scores[0, k], sig(p[0, a, 5:, i, j]) * sig(p[0, a, 4, i, j]),
            rtol=1e-5)

    def test_conf_thresh_zeroes(self):
        x = np.full((1, 10, 2, 2), -10.0, np.float32)   # obj ~ 0
        boxes, scores = yolo_box(x, np.array([[64, 64]]), [10, 13], 5,
                                 conf_thresh=0.5)
        assert np.all(np.asarray(boxes) == 0)
        assert np.all(np.asarray(scores) == 0)


# ------------------------------------------------------------ prior_box


class TestPriorBox:
    def test_center_and_sizes(self):
        boxes, var = prior_box((2, 2), (32, 32), min_sizes=[8.0],
                               max_sizes=[16.0], aspect_ratios=[2.0],
                               flip=True)
        boxes, var = np.asarray(boxes), np.asarray(var)
        # priors per cell: 1 (min) + ar 2 + ar 0.5 + 1 (sqrt(min*max))
        assert boxes.shape == (2, 2, 4, 4)
        # cell (0,0) center = (0.5*16, 0.5*16) = (8, 8); min prior 8x8
        np.testing.assert_allclose(
            boxes[0, 0, 0], [(8 - 4) / 32, (8 - 4) / 32,
                             (8 + 4) / 32, (8 + 4) / 32], rtol=1e-6)
        # the max prior is sqrt(8*16) square
        big = np.sqrt(8 * 16) / 2
        np.testing.assert_allclose(
            boxes[0, 0, 3], [(8 - big) / 32, (8 - big) / 32,
                             (8 + big) / 32, (8 + big) / 32], rtol=1e-6)
        np.testing.assert_allclose(var[0, 0, 0], [0.1, 0.1, 0.2, 0.2])

    def test_clip(self):
        boxes, _ = prior_box((1, 1), (16, 16), min_sizes=[32.0], clip=True)
        b = np.asarray(boxes)
        assert b.min() >= 0.0 and b.max() <= 1.0


# ------------------------------------------------------------ box_coder


class TestBoxCoder:
    def test_encode_decode_roundtrip(self):
        rng = np.random.RandomState(2)
        priors = np.array([[2, 2, 10, 10], [4, 4, 8, 12]], np.float32)
        var = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
        targets = np.array([[3, 3, 9, 9], [1, 2, 7, 10]], np.float32)
        enc = np.asarray(box_coder(priors, targets, var, "encode"))
        assert enc.shape == (2, 2, 4)
        # encode emits [T, P, 4] (priors on dim 1) -> decode with axis=1
        # (reference box_coder_op.cc: axis selects the prior-aligned dim)
        dec = np.asarray(box_coder(priors, enc, var, "decode", axis=1))
        for t in range(2):
            for p in range(2):
                np.testing.assert_allclose(dec[t, p], targets[t],
                                           rtol=1e-4, atol=1e-4)

    def test_encode_golden(self):
        priors = np.array([[0, 0, 10, 10]], np.float32)
        targets = np.array([[2, 2, 6, 8]], np.float32)
        enc = np.asarray(box_coder(priors, targets, None, "encode"))
        # centers: prior (5,5) wh (10,10); target (4,5) wh (4,6)
        np.testing.assert_allclose(
            enc[0, 0], [(4 - 5) / 10, 0.0, np.log(0.4), np.log(0.6)],
            rtol=1e-5, atol=1e-6)


# ------------------------------------------------------------- ctc_loss


class TestCtcLoss:
    def _torch_ref(self, lp, labels, in_len, lab_len, reduction):
        import torch
        import torch.nn.functional as F

        return F.ctc_loss(torch.tensor(lp), torch.tensor(labels),
                          torch.tensor(in_len), torch.tensor(lab_len),
                          blank=0, reduction=reduction,
                          zero_infinity=False).numpy()

    @pytest.mark.parametrize("reduction", ["none", "mean", "sum"])
    def test_matches_torch(self, reduction):
        rng = np.random.RandomState(0)
        T, B, C, S = 14, 4, 7, 5
        logits = rng.randn(T, B, C).astype(np.float32)
        lp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        labels = rng.randint(1, C, (B, S)).astype(np.int64)
        in_len = np.array([14, 12, 9, 14])
        lab_len = np.array([5, 4, 2, 1])
        got = np.asarray(ctc_loss(lp, labels.astype(np.int32), in_len,
                                  lab_len, reduction=reduction))
        want = self._torch_ref(lp, labels, in_len, lab_len, reduction)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_repeated_labels_skip_rule(self):
        rng = np.random.RandomState(1)
        T, B, C = 10, 2, 5
        logits = rng.randn(T, B, C).astype(np.float32)
        lp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        labels = np.array([[2, 2, 2, 3], [1, 2, 1, 2]], np.int64)
        in_len = np.array([10, 10])
        lab_len = np.array([4, 4])
        got = np.asarray(ctc_loss(lp, labels.astype(np.int32), in_len,
                                  lab_len, reduction="none"))
        want = self._torch_ref(lp, labels, in_len, lab_len, "none")
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_grad_flows(self):
        import jax

        rng = np.random.RandomState(2)
        T, B, C = 8, 2, 5
        logits = rng.randn(T, B, C).astype(np.float32)

        def f(logits):
            lp = jax.nn.log_softmax(logits, -1)
            # pure_fn: the jit/grad-traceable entry (the eager wrapper
            # returns framework Tensors)
            return ctc_loss.pure_fn(
                lp, np.array([[1, 2], [3, 1]], np.int32),
                np.array([8, 8]), np.array([2, 2]))

        g = jax.grad(f)(logits)
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).sum() > 0


# ---------------------------------------------------------- beam search


class TestBeamSearch:
    def test_step_freezes_finished(self):
        import jax.numpy as jnp

        pre = jnp.asarray([[0.0, -1.0]])
        lp = jnp.log(jnp.asarray([[[0.5, 0.25, 0.25],
                                   [0.6, 0.2, 0.2]]]))
        fin = jnp.asarray([[True, False]])
        tok, parent, scores, new_fin = beam_search_step(pre, lp, 2, 0,
                                                        fin)
        # finished beam 0 extends only with end_id at unchanged score
        assert int(tok[0, 0]) == 0 and float(scores[0, 0]) == 0.0
        assert bool(new_fin[0, 0])

    def test_finds_better_than_greedy(self):
        import jax
        import jax.numpy as jnp

        def step_fn(hist, t):
            prev = jax.vmap(lambda h, tt: h[:, tt],
                            in_axes=(0, None))(hist, t)
            t0 = jnp.asarray([-5.0, -0.3, -0.5, -9.0])
            after1 = jnp.asarray([-3.0, -4.0, -4.0, -9.0])
            after2 = jnp.asarray([-0.1, -4.0, -4.0, -9.0])
            return jnp.where((prev == 1)[..., None], after1,
                             jnp.where((prev == 2)[..., None], after2,
                                       t0))

        seqs, scores = beam_search(step_fn, bos_id=3, end_id=0,
                                   beam_size=2, max_len=3, batch_size=1)
        assert abs(float(scores[0, 0]) - (-0.6)) < 1e-5
        assert list(np.asarray(seqs[0, 0])) == [3, 2, 0, 0]
        assert abs(float(scores[0, 1]) - (-3.3)) < 1e-5


# ---------------------------------------------------------- sequence ops


class TestSequenceOps:
    def test_mask(self):
        m = np.asarray(sequence_mask([2, 0, 3], maxlen=4, dtype="int32"))
        np.testing.assert_array_equal(
            m, [[1, 1, 0, 0], [0, 0, 0, 0], [1, 1, 1, 0]])

    def test_pad_unpad_roundtrip(self):
        rng = np.random.RandomState(0)
        flat = rng.randn(6, 3).astype(np.float32)
        lens = [2, 1, 3]
        padded = np.asarray(sequence_pad(flat, lens, pad_value=-9.0))
        assert padded.shape == (3, 3, 3)
        assert np.all(padded[1, 1:] == -9.0)
        np.testing.assert_allclose(padded[2, :3], flat[3:6])
        back = sequence_unpad(padded, lens)
        np.testing.assert_allclose(back, flat)

    def test_softmax_masks_padding(self):
        x = np.array([[1.0, 2.0, 3.0], [5.0, 1.0, 1.0]], np.float32)
        p = np.asarray(sequence_softmax(x, [2, 1]))
        np.testing.assert_allclose(p.sum(1), [1.0, 1.0], rtol=1e-6)
        assert p[0, 2] == 0.0 and p[1, 1] == 0.0 and p[1, 0] == 1.0

    def test_reverse_prefix_only(self):
        x = np.asarray([[1, 2, 3, 0], [4, 5, 6, 7]], np.float32)
        r = np.asarray(sequence_reverse(x, [3, 4]))
        np.testing.assert_array_equal(r[0], [3, 2, 1, 0])
        np.testing.assert_array_equal(r[1], [7, 6, 5, 4])

    def test_expand(self):
        x = np.asarray([[1.0], [2.0], [3.0]])
        out = np.asarray(sequence_expand(x, [2, 0, 1]))
        np.testing.assert_array_equal(out, [[1.0], [1.0], [3.0]])

    @pytest.mark.parametrize("kind,want", [
        ("sum", [[3.0], [4.0]]),
        ("mean", [[1.5], [4.0]]),
        ("max", [[2.0], [4.0]]),
        ("first", [[1.0], [4.0]]),
        ("last", [[2.0], [4.0]]),
    ])
    def test_pool(self, kind, want):
        x = np.asarray([[[1.0], [2.0], [9.0]],
                        [[4.0], [8.0], [8.0]]], np.float32)
        out = np.asarray(sequence_pool(x, kind, [2, 1]))
        np.testing.assert_allclose(out, want)


class TestBoxCoderAxis:
    def test_axis0_is_transposed_axis1(self):
        rng = np.random.RandomState(4)
        priors = np.abs(rng.randn(3, 4)).astype(np.float32) + \
            np.float32([0, 0, 2, 2])
        deltas = (rng.randn(2, 3, 4) * 0.1).astype(np.float32)
        a1 = np.asarray(box_coder(priors, deltas, None, "decode", axis=1))
        a0 = np.asarray(box_coder(priors, deltas.transpose(1, 0, 2), None,
                                  "decode", axis=0))
        np.testing.assert_allclose(a0, a1.transpose(1, 0, 2), rtol=1e-5)

    def test_bad_axis_rejected(self):
        with pytest.raises(ValueError, match="axis"):
            box_coder(np.zeros((1, 4), np.float32),
                      np.zeros((1, 1, 4), np.float32), None, "decode",
                      axis=2)
