"""Device API tests (N3 pluggable-device facade)."""
import os
import subprocess
import sys

import pytest

import paddle_tpu as paddle

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestDeviceAPI:
    def test_set_get_device(self):
        prev = paddle.device.get_device()
        paddle.device.set_device("cpu")
        assert paddle.device.get_device().startswith("cpu")
        paddle.device.set_device(prev)

    def test_register_after_init_raises(self):
        import jax

        jax.devices()   # force backend init
        with pytest.raises(RuntimeError, match="before"):
            paddle.device.register_custom_device("mydev", "/tmp/x.so")

    def test_register_missing_plugin_raises(self):
        """Fresh process (backend not initialized): missing .so must be a
        clear FileNotFoundError, not a lazy jax failure."""
        script = f"""
import sys
sys.path.insert(0, {REPO!r})
import paddle_tpu as paddle
try:
    paddle.device.register_custom_device("npu", "/nonexistent/libnpu.so")
    print("NO_RAISE")
except FileNotFoundError as e:
    print("RAISED_OK")
"""
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("XLA_", "JAX_"))}
        proc = subprocess.run([sys.executable, "-c", script], env=env,
                              capture_output=True, text=True, timeout=120)
        assert "RAISED_OK" in proc.stdout, (proc.stdout, proc.stderr)

    def test_custom_device_queries(self):
        assert paddle.device.get_all_custom_device_type() == []
        assert not paddle.device.is_custom_device_available("not_a_device")
