"""Distributed flight recorder tests: per-collective ring accounting,
chrome-timeline spans next to hapi::step, stall fault sites, the
cross-rank HangWatchdog acceptance run (one of three TCPStore-backed
ranks stalled inside all_reduce -> every rank writes an atomic debug
bundle and the desync report names the stalled rank), the /flight +
folded /healthz endpoints, the supervisor's on_hang escalation, the
collective-instrumentation lint, and the recorder-overhead smoke
bound."""
import importlib.util
import json
import os
import threading
import time
import types
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import collective
from paddle_tpu.io import Dataset
from paddle_tpu.observability import (FlightRecorder, HangWatchdog,
                                      MetricsRegistry, Tracer,
                                      default_flight_recorder,
                                      start_telemetry_server,
                                      use_flight_recorder)
from paddle_tpu.resilience import FaultSpec, injected_faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    path = os.path.join(REPO, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _recorder(capacity=64):
    return FlightRecorder(capacity=capacity, registry=MetricsRegistry(),
                          tracer=Tracer())


# ------------------------------------------------------- ring semantics


class TestFlightRecorderRing:
    def test_seq_monotonic_and_ring_bounded(self):
        rec = _recorder(capacity=8)
        with use_flight_recorder(rec):
            for _ in range(20):
                collective.all_reduce(jnp.ones((4,), jnp.float32))
        recs = rec.records()
        assert len(recs) == 8                   # ring evicted the rest
        seqs = [r["seq"] for r in recs]
        assert seqs == list(range(13, 21))      # newest 8, strictly up
        assert rec.summary()["completed"] == 20
        assert rec.last_seq == 20

    def test_per_group_seq_independent(self):
        rec = _recorder()
        dp = types.SimpleNamespace(axis_name=None, nranks=1)  # degenerate
        g_mp = types.SimpleNamespace(axis_name="mp", nranks=4)
        del dp
        x = np.ones((4,), np.float32)
        with rec.record("all_reduce", tensors=(x,)):
            pass
        with rec.record("all_reduce", group=g_mp, tensors=(x,)):
            pass
        with rec.record("barrier"):
            pass
        recs = rec.records()
        assert [(r["group"], r["group_seq"]) for r in recs] == \
            [("world", 1), ("mp", 1), ("world", 2)]
        assert [r["seq"] for r in recs] == [1, 2, 3]   # global monotonic

    def test_record_fields_and_metrics(self):
        rec = _recorder()
        with use_flight_recorder(rec):
            collective.all_reduce(jnp.ones((8, 4), jnp.float32))
        r = rec.records()[-1]
        assert r["op"] == "all_reduce" and r["group"] == "world"
        assert r["shapes"] == [[8, 4]] and r["nbytes"] == 8 * 4 * 4
        assert r["dtypes"] == ["float32"]
        assert r["end_s"] >= r["start_s"]
        assert r["caller"] and r["caller"].startswith(
            "test_distributed_flight.py")
        snap = rec.registry().snapshot()
        ops = {(s["labels"]["op"], s["labels"]["group"]): s["value"]
               for s in snap["collective_ops_total"]["series"]}
        assert ops[("all_reduce", "world")] == 1
        byt = {s["labels"]["op"]: s["value"]
               for s in snap["collective_bytes_total"]["series"]}
        assert byt["all_reduce"] == 128
        lat = snap["collective_latency_seconds"]["series"][0]["value"]
        assert lat["count"] == 1

    def test_failed_collective_recorded_with_error(self):
        rec = _recorder()
        with use_flight_recorder(rec):
            with pytest.raises(NotImplementedError):
                collective.send(jnp.ones((4,), jnp.float32))
        r = rec.records()[-1]
        assert r["op"] == "send" and "NotImplementedError" in r["error"]

    def test_inflight_visible_until_finish(self):
        rec = _recorder()
        r = rec.start("all_reduce", tensors=(np.ones(4, np.float32),))
        brief = rec.inflight_brief()
        assert brief == {"seq": 1, "op": "all_reduce", "group": "world"}
        assert rec.last_seq == 0                # not completed yet
        rec.finish(r)
        assert rec.inflight_brief() is None
        assert rec.last_seq == 1

    def test_note_step_rides_summary(self):
        rec = _recorder()
        rec.note_step(7, epoch=2)
        s = rec.summary()
        assert (s["step"], s["epoch"]) == (7, 2)


# -------------------------------------------------- chrome-trace export


class _Toy(Dataset):
    def __init__(self, n=8):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, 4).astype(np.float32)
        self.y = rng.randint(0, 2, (n,)).astype(np.int64)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class TestChromeTimeline:
    def test_collective_spans_next_to_hapi_step(self, tmp_path):
        """Acceptance: collective spans land in the same chrome export
        as hapi::step spans (one Perfetto view for training + comms),
        and Model.fit stamped the step-progress heartbeat."""
        from paddle_tpu.observability import default_tracer

        model = paddle.Model(nn.Sequential(nn.Linear(4, 8), nn.ReLU(),
                                           nn.Linear(8, 2)))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss())
        model.fit(_Toy(8), batch_size=4, epochs=1, verbose=0)
        collective.all_reduce(jnp.ones((4,), jnp.float32))

        path = default_tracer().export_chrome(str(tmp_path / "t.json"))
        with open(path) as f:
            events = json.load(f)["traceEvents"]
        names = {e["name"] for e in events if e.get("ph") == "X"}
        assert "hapi::step" in names
        assert "collective::all_reduce" in names
        # the fit loop stamped the process flight recorder's step
        assert default_flight_recorder().step is not None


# ---------------------------------------------------- stall fault sites


class TestStallFaultSites:
    def test_stall_inside_all_reduce_shows_in_latency(self):
        rec = _recorder()
        with use_flight_recorder(rec), \
                injected_faults(FaultSpec("collective.all_reduce",
                                          "stall", occurrence=1,
                                          stall_s=0.12)):
            collective.all_reduce(jnp.ones((4,), jnp.float32))
        r = rec.records()[-1]
        assert r["end_s"] - r["start_s"] >= 0.1   # the stall is visible

    def test_stall_inside_barrier_shows_in_latency(self):
        rec = _recorder()
        with use_flight_recorder(rec), \
                injected_faults(FaultSpec("collective.barrier", "stall",
                                          occurrence=1, stall_s=0.12)):
            collective.barrier()
        r = rec.records()[-1]
        assert r["op"] == "barrier"
        assert r["end_s"] - r["start_s"] >= 0.1


# ------------------------------------------------ cross-rank watchdog


STALLED = 1


@pytest.mark.faultinject
class TestHangWatchdogMultiRank:
    def test_stalled_rank_detected_bundled_and_named(self, tmp_path):
        """Acceptance: 3 TCPStore-backed ranks, rank 1 stalled inside
        all_reduce via fault injection.  Every rank's watchdog fires
        within the configured timeout, every rank writes an atomic
        debug bundle whose collective rings agree up to the divergent
        seq, and the desync report names the stalled rank + op.  When
        the stall clears, the watchdogs see the fleet re-converge."""
        from paddle_tpu.distributed.store import TCPStore

        master = TCPStore(is_master=True, world_size=3)
        recs, hws, regs = {}, {}, {}
        for r in range(3):
            st = master if r == 0 else TCPStore(port=master.port,
                                               world_size=3)
            regs[r] = MetricsRegistry()
            recs[r] = FlightRecorder(capacity=64, registry=regs[r],
                                     tracer=Tracer())
            hws[r] = HangWatchdog(
                st, rank=r, world_size=3, recorder=recs[r],
                stall_timeout_s=0.4, interval_s=0.1,
                bundle_dir=str(tmp_path / f"r{r}"),
                registry=regs[r], tracer=Tracer())

        # deterministic warmup: ranks 0/2 complete seq 1..4, the
        # to-be-stalled rank only 1..3 (recorders don't care which
        # thread records, so one thread can lay down all the history)
        x = jnp.ones((16,), jnp.float32)
        for r in range(3):
            with use_flight_recorder(recs[r]):
                for _ in range(3 if r == STALLED else 4):
                    collective.all_reduce(x)

        stall_entered = threading.Event()

        def stalled_rank():
            with use_flight_recorder(recs[STALLED]):
                stall_entered.set()
                collective.all_reduce(x)     # seq 4: stalls mid-flight

        errs = []
        with injected_faults(FaultSpec("collective.all_reduce", "stall",
                                       occurrence=1, stall_s=3.0)):
            t = threading.Thread(target=stalled_rank, daemon=True)
            t.start()
            assert stall_entered.wait(timeout=5)
            time.sleep(0.1)                  # record is in flight now
            assert recs[STALLED].inflight_brief()["op"] == "all_reduce"
            t0 = time.monotonic()
            for hw in hws.values():
                hw.start(interval_s=0.1)
            try:
                while time.monotonic() - t0 < 2.0 and \
                        not all(hw.fired for hw in hws.values()):
                    time.sleep(0.02)
                elapsed = time.monotonic() - t0
                # every rank fired, within the timeout budget, while
                # the hang was still live
                assert all(hw.fired == 1 for hw in hws.values()), \
                    {r: hw.fired for r, hw in hws.items()}
                assert elapsed < 2.0
                assert t.is_alive()          # hang still in progress
                for r, hw in hws.items():
                    d = hw.last_desync
                    assert d["lagging_rank"] == STALLED
                    assert d["stalled_ranks"] == [STALLED]
                    assert d["divergent_seq"] == 4
                    assert d["op"] == "all_reduce"
                    assert d["seqs"] == {"0": 4, "1": 3, "2": 4}
                    assert hw.hang_active
                    assert regs[r].get(
                        "hang_watchdog_fired_total").value == 1
                    assert regs[r].get(
                        "hang_watchdog_active").value == 1
            except BaseException as e:
                errs.append(e)
            t.join(timeout=10)
        if errs:
            raise errs[0]

        # ---- every rank wrote one atomic bundle; rings agree --------
        prefixes = {}
        for r, hw in hws.items():
            assert len(hw.bundles) == 1
            with open(hw.bundles[0]) as f:
                b = json.load(f)
            assert b["rank"] == r and b["reason"] == "hang"
            assert b["desync"]["lagging_rank"] == STALLED
            assert b["threads"]                 # live stacks captured
            assert "metrics" in b and "live_spans" in b
            prefixes[r] = [(rec["seq"], rec["op"]) for rec in b["records"]
                           if rec["seq"] < b["desync"]["divergent_seq"]]
        # collective rings agree up to the divergent seq
        assert prefixes[0] == prefixes[1] == prefixes[2] == \
            [(1, "all_reduce"), (2, "all_reduce"), (3, "all_reduce")]
        # the stalled rank's bundle shows WHERE it was stuck
        with open(hws[STALLED].bundles[0]) as f:
            b1 = json.load(f)
        assert [(r["seq"], r["op"]) for r in b1["inflight"]] == \
            [(4, "all_reduce")]

        # ---- the stall cleared: fleet re-converges, fire stays at 1 -
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and \
                any(hw.hang_active for hw in hws.values()):
            time.sleep(0.05)
        for r, hw in hws.items():
            assert not hw.hang_active
            assert hw.fired == 1                # no re-fire
            assert regs[r].get("hang_watchdog_active").value == 0
            hw.stop()

    def test_observer_mode_monitors_without_publishing(self):
        """rank=None (the supervisor's parent-side view) reads every
        rank's heartbeat and detects the lag without a recorder."""
        from paddle_tpu.distributed.store import TCPStore

        master = TCPStore(is_master=True, world_size=2)
        recs = {r: _recorder() for r in range(2)}
        pubs = {r: HangWatchdog(master, rank=r, world_size=2,
                                recorder=recs[r], stall_timeout_s=0.2,
                                registry=MetricsRegistry(),
                                tracer=Tracer())
                for r in range(2)}
        with use_flight_recorder(recs[0]):
            collective.all_reduce(jnp.ones((4,), jnp.float32))
        for p in pubs.values():
            p.poll()                            # publish both heartbeats
        obs = HangWatchdog(master, rank=None, world_size=2,
                           stall_timeout_s=0.2, registry=MetricsRegistry(),
                           tracer=Tracer())
        assert obs.poll() is False              # baseline, not yet stalled
        assert obs.published == 0               # observer publishes nothing
        time.sleep(0.25)
        pubs[0].poll()                          # rank 0 still at seq 1
        assert obs.poll() is True               # rank 1 frozen at seq 0
        assert obs.last_desync["lagging_rank"] == 1
        assert obs.check() is True


# ------------------------------------------------- /flight + /healthz


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class TestFlightEndpointAndHealthz:
    def _hang_stub(self, reg):
        hw = HangWatchdog(store=None, rank=None, world_size=1,
                          registry=reg, tracer=Tracer())
        return hw

    def test_flight_endpoint_serves_ring_and_desync(self, tmp_path):
        reg = MetricsRegistry()
        rec = FlightRecorder(registry=reg, tracer=Tracer())
        with use_flight_recorder(rec):
            for _ in range(3):
                collective.all_reduce(jnp.ones((4,), jnp.float32))
        hw = self._hang_stub(reg)
        hw.hang_active = True
        hw.fired = 1
        hw.last_desync = {"lagging_rank": 2, "divergent_seq": 9,
                          "op": "barrier"}
        srv = start_telemetry_server(port=0, registry=reg,
                                     tracer=Tracer(), flight=rec,
                                     hang=hw)
        try:
            code, body = _get(srv.url + "/flight")
            assert code == 200
            fl = json.loads(body)
            assert fl["summary"]["completed"] == 3
            assert [r["op"] for r in fl["records"]] == ["all_reduce"] * 3
            assert fl["hang"]["active"] is True
            assert fl["hang"]["desync"]["lagging_rank"] == 2
        finally:
            srv.stop()

    def test_healthz_503_on_active_hang(self):
        reg = MetricsRegistry()
        hw = self._hang_stub(reg)
        srv = start_telemetry_server(port=0, registry=reg,
                                     tracer=Tracer(), hang=hw)
        try:
            code, body = _get(srv.url + "/healthz")
            assert code == 200 and json.loads(body)["healthy"] is True
            hw.hang_active = True
            code, body = _get(srv.url + "/healthz")
            health = json.loads(body)
            assert code == 503
            assert health["healthy"] is False
            assert health["hang_active"] is True
            hw.hang_active = False
            code, _ = _get(srv.url + "/healthz")
            assert code == 200
        finally:
            srv.stop()

    def test_healthz_folds_training_healthy(self):
        """One probe covers training liveness too: the HealthMonitor's
        training_healthy gauge flips /healthz to 503."""
        reg = MetricsRegistry()
        srv = start_telemetry_server(port=0, registry=reg,
                                     tracer=Tracer())
        try:
            code, body = _get(srv.url + "/healthz")
            assert code == 200          # no trainer -> signal absent -> ok
            assert json.loads(body)["training_healthy"] is None
            reg.gauge("training_healthy",
                      "1 while no training anomaly is active").set(0)
            code, body = _get(srv.url + "/healthz")
            health = json.loads(body)
            assert code == 503 and health["healthy"] is False
            assert health["training_healthy"] is False
            reg.gauge("training_healthy").set(1)
            code, body = _get(srv.url + "/healthz")
            assert code == 200 and json.loads(body)["healthy"] is True
        finally:
            srv.stop()

    def test_healthz_hang_gauge_fallback(self):
        """Without an attached watchdog object the hang_watchdog_active
        gauge (published by a watchdog elsewhere in-process) drives the
        same 503."""
        reg = MetricsRegistry()
        reg.gauge("hang_watchdog_active").set(1)
        srv = start_telemetry_server(port=0, registry=reg,
                                     tracer=Tracer())
        try:
            code, body = _get(srv.url + "/healthz")
            assert code == 503
            assert json.loads(body)["hang_active"] is True
        finally:
            srv.stop()


# ------------------------------------------- supervisor hang escalation


class _StubWatchdog:
    def __init__(self):
        self.hang_active = False
        self.bundle_reasons = []
        self.resets = 0

    def check(self):
        return self.hang_active

    def write_bundle(self, reason="hang"):
        self.bundle_reasons.append(reason)
        return "stub-bundle"

    def reset(self):
        self.resets += 1
        self.hang_active = False


def _script(tmp_path, body):
    import sys

    p = tmp_path / "child.py"
    p.write_text("import os, sys\n"
                 "attempt = int(os.environ.get("
                 "'PADDLE_RESTART_ATTEMPT', '0'))\n" + body)
    return [sys.executable, str(p)]


class TestSupervisorHangEscalation:
    def test_hung_child_bundled_and_relaunched(self, tmp_path):
        """on_hang='bundle+restart': a wedged child (never exits) is
        dumped, killed and relaunched; the watchdog is reset so the
        relaunch re-baselines."""
        from paddle_tpu.observability import default_registry
        from paddle_tpu.resilience import TrainingSupervisor

        fam = default_registry().get("supervisor_restarts_total")
        before = fam.labels(reason="hang").value if fam else 0
        stub = _StubWatchdog()
        body = ("import time\n"
                "time.sleep(60 if attempt == 0 else 0)\n"
                "sys.exit(0)\n")
        sup = TrainingSupervisor(
            _script(tmp_path, body), max_restarts=1, backoff_base=0.01,
            backoff_cap=0.02, membership_interval=0.05, term_grace_s=5.0,
            hang_watchdog=stub, on_hang="bundle+restart")

        def trip():
            time.sleep(0.5)
            stub.hang_active = True

        t = threading.Thread(target=trip, daemon=True)
        t.start()
        assert sup.run() == 0
        t.join()
        assert [r for r, _ in sup.restarts] == ["hang"]
        assert stub.bundle_reasons == ["supervisor_hang"]
        assert stub.resets == 1
        assert default_registry().get("supervisor_restarts_total")\
            .labels(reason="hang").value == before + 1

    def test_on_hang_restart_skips_bundle(self, tmp_path):
        from paddle_tpu.resilience import TrainingSupervisor

        stub = _StubWatchdog()
        body = ("import time\n"
                "time.sleep(60 if attempt == 0 else 0)\n"
                "sys.exit(0)\n")
        sup = TrainingSupervisor(
            _script(tmp_path, body), max_restarts=1, backoff_base=0.01,
            backoff_cap=0.02, membership_interval=0.05, term_grace_s=5.0,
            hang_watchdog=stub, on_hang="restart")

        def trip():
            time.sleep(0.3)
            stub.hang_active = True

        threading.Thread(target=trip, daemon=True).start()
        assert sup.run() == 0
        assert [r for r, _ in sup.restarts] == ["hang"]
        assert stub.bundle_reasons == []

    def test_unknown_on_hang_policy_rejected(self):
        from paddle_tpu.resilience import TrainingSupervisor

        with pytest.raises(ValueError):
            TrainingSupervisor(["true"], on_hang="page-someone")


# ----------------------------------------------------------- lints


class TestCollectiveInstrumentedLint:
    # the repo-wide sweep now runs ONCE in the consolidated suite:
    # tests/test_static_analysis.py::TestTier1Suite

    def test_uninstrumented_op_detected(self, tmp_path):
        bad = tmp_path / "fake_collective.py"
        bad.write_text(
            "__all__ = ['all_reduce', 'barrier', 'new_group']\n"
            "from paddle_tpu.observability.flight import "
            "record_collective\n"
            "def all_reduce(x, group=None):\n"
            "    return x\n"
            "@record_collective('barrier')\n"
            "def barrier(group=None):\n"
            "    pass\n"
            "def new_group():\n"          # exempt plumbing
            "    pass\n")
        violations = _load_tool("check_collective_instrumented").check(
            path=str(bad))
        assert len(violations) == 1
        assert "all_reduce" in violations[0]
        assert "record_collective" in violations[0]


# --------------------------------------------------- overhead smoke


class TestRecorderOverheadSmoke:
    def test_implied_step_overhead_under_bound(self):
        """Acceptance: the recorder's per-collective cost, scaled to a
        documented 1.3B-class step (64 collectives, 1.5 s), stays under
        the 3% bound bench --section distributed publishes."""
        spec = importlib.util.spec_from_file_location(
            "bench_mod", os.path.join(REPO, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        out = bench.bench_distributed(iters=900, reps=3)
        assert out["implied_step_overhead_ratio"] < out["bound_ratio"], out
        # absolute sanity: tens of microseconds per op, not milliseconds
        assert out["per_op_overhead_us"] < 1000, out
