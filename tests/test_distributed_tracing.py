"""Fleet-wide distributed tracing: TraceContext propagation across
tracers/processes, tail-based retention under ring pressure, histogram
exemplars in the OpenMetrics exposition, the trace-gossip store plane,
the merged fleet view (``merge_traces`` + ``/traces?fleet=1``), and the
hard-kill-failover acceptance — ONE trace per re-dispatched request,
asserted over live HTTP from the merged fleet view."""
import dataclasses
import json
import threading
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models.gpt import GPT_CONFIGS, gpt_init
from paddle_tpu.observability.exporter import start_telemetry_server
from paddle_tpu.observability.metrics import Histogram, MetricsRegistry
from paddle_tpu.observability.tracing import (TailRetention, TraceContext,
                                              Tracer, activate,
                                              export_traces_chrome,
                                              merge_traces)
from paddle_tpu.resilience import FaultSpec, fault_point, injected_faults
from paddle_tpu.serving import (Engine, FleetRequestState, FleetRouter,
                                SamplingParams)
from paddle_tpu.serving.metrics import ServingMetrics


class ManualClock:
    def __init__(self, auto=0.0):
        self.t = 0.0
        self.auto = auto

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        self.t += self.auto
        return self.t


def _tiny_cfg():
    return dataclasses.replace(GPT_CONFIGS["tiny"], dtype="float32")


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny_cfg()
    params = gpt_init(cfg, jax.random.key(0), dtype=jnp.float32)
    return cfg, params


def _get_json(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


# ------------------------------------------------ context propagation


class TestTraceContextPropagation:
    def test_nonce_prefixed_ids_never_collide_across_tracers(self):
        a, b = Tracer(clock=ManualClock(auto=1.0)), \
            Tracer(clock=ManualClock(auto=1.0))
        ra, rb = a.start_trace("op"), b.start_trace("op")
        assert ra.trace_id != rb.trace_id
        assert ra.span_id != rb.span_id
        assert a.nonce != b.nonce
        assert ra.trace_id.startswith(a.nonce)

    def test_context_json_round_trip_continues_trace(self):
        """The cross-process shape: a context serialized to JSON in one
        tracer re-roots a segment under the SAME trace_id in another,
        parented to the originating span."""
        router_tr = Tracer(clock=ManualClock(auto=1.0))
        replica_tr = Tracer(clock=ManualClock(auto=1.0))
        root = router_tr.start_trace("fleet#0")
        dispatch = router_tr.start_span("router::dispatch", root)
        wire = json.dumps(dispatch.context().to_dict())   # crosses the wire

        ctx = TraceContext.from_dict(json.loads(wire))
        seg = replica_tr.start_trace("request#0", context=ctx)
        assert seg.trace_id == root.trace_id
        assert seg.parent_id == dispatch.span_id
        child = replica_tr.start_span("decode[1]", seg)
        child.end()
        seg.end()
        dispatch.end()
        root.end()

        (remote,) = replica_tr.traces()
        assert remote["trace_id"] == root.trace_id
        merged = merge_traces([("router", router_tr.traces()),
                               ("replica0", replica_tr.traces())])
        (m,) = merged                        # ONE trace, two segments
        assert m["trace_id"] == root.trace_id
        assert m["name"] == "fleet#0"        # origin segment names it
        assert len(m["segments"]) == 2
        sources = {s["source"] for s in m["spans"]}
        assert sources == {"router", "replica0"}
        by_name = {s["name"]: s for s in m["spans"]}
        assert by_name["request#0"]["parent_id"] == \
            by_name["router::dispatch"]["span_id"]

    def test_context_joins_live_trace_in_same_tracer(self):
        """In-process fleets share one tracer: a context-continued
        start_trace joins the LIVE trace as an ordinary child — no
        split segments to merge."""
        tr = Tracer(clock=ManualClock(auto=1.0))
        root = tr.start_trace("fleet#1")
        seg = tr.start_trace("request#1", context=root.context())
        assert seg.trace_id == root.trace_id
        seg.end()
        assert tr.traces() == []             # still one live trace
        root.end()
        (done,) = tr.traces()
        assert {s["name"] for s in done["spans"]} == \
            {"fleet#1", "request#1"}

    def test_disabled_tracer_propagates_no_context(self):
        tr = Tracer(enabled=False)
        span = tr.start_trace("op")
        assert span.context() is None
        assert tr.start_span("child", span) is span   # shared null span
        span.end()
        assert tr.traces() == []


# ------------------------------------------------- tail-based retention


class TestTailRetention:
    def _finish(self, tr, name, attrs=None, dur=0.001):
        clk = tr.clock
        root = tr.start_trace(name, attributes=attrs, start_s=clk.t)
        root.end(clk.t + dur)
        clk.advance(dur)

    def test_interesting_survive_ring_pressure(self):
        """Under ring pressure the boring sampled traces are evicted
        first; shed/evicted/failover/slow traces survive a flood of
        boring ones that overflows the ring many times over."""
        clk = ManualClock()
        tr = Tracer(clock=clk, max_traces=8,
                    retention=TailRetention(slow_threshold_s=0.5))
        self._finish(tr, "req#shed", {"state": "retry_after"})
        self._finish(tr, "req#evicted", {"state": "evicted"})
        self._finish(tr, "req#error", {"error": "OSError('boom')"})
        self._finish(tr, "req#slow", dur=0.9)
        root = tr.start_trace("req#failover", start_s=clk.t)
        tr.start_span("router::failover", root, start_s=clk.t).end(clk.t)
        root.end(clk.t)
        for i in range(50):                  # 6x the ring of boredom
            self._finish(tr, f"boring#{i}")
        kept = {t["name"]: t["retained"] for t in tr.traces()}
        assert kept["req#shed"] == "retry_after"
        assert kept["req#evicted"] == "evicted"
        assert kept["req#error"] == "error"
        assert kept["req#slow"] == "slow"
        assert kept["req#failover"] == "failover"
        assert len(tr.traces()) == 8         # ring stays bounded
        assert sum(1 for r in kept.values() if r == "sampled") == 3

    def test_boring_traces_sampled_out(self):
        clk = ManualClock()
        tr = Tracer(clock=clk, max_traces=64,
                    retention=TailRetention(sample_rate=0.0))
        for i in range(20):
            self._finish(tr, f"boring#{i}")
        self._finish(tr, "req#evicted", {"state": "evicted"})
        assert [t["name"] for t in tr.traces()] == ["req#evicted"]
        s = tr.summary()
        assert s["completed"] == 21 and s["dropped"] == 20
        assert s["retained_by_reason"] == {"evicted": 1}

    def test_sampling_is_seeded_and_probabilistic(self):
        def run(seed):
            clk = ManualClock()
            tr = Tracer(clock=clk, max_traces=4096,
                        retention=TailRetention(sample_rate=0.1,
                                                seed=seed))
            for i in range(1000):
                self._finish(tr, f"b#{i}")
            return [t["name"] for t in tr.traces()]

        a, b = run(7), run(7)
        assert a == b                        # reproducible
        assert 40 <= len(a) <= 250           # ~10% of 1000

    def test_fired_fault_pins_trace_in_ring(self):
        """A fired fault lands a (site, kind, occurrence, seed) event on
        the thread's ambient span, and retention classifies the trace as
        always-keep."""
        clk = ManualClock()
        tr = Tracer(clock=clk, max_traces=4,
                    retention=TailRetention(sample_rate=0.0))
        root = tr.start_trace("req#faulted", start_s=clk.t)
        with injected_faults(FaultSpec("test.site", "stall", stall_s=0.0),
                             seed=42):
            with activate(root):
                fault_point("test.site")
        root.end(clk.t)
        (done,) = tr.traces()
        assert done["retained"] == "fault"
        (event,) = done["spans"][0]["attributes"]["faults"]
        assert event == {"site": "test.site", "kind": "stall",
                         "occurrence": 1, "seed": 42}


# ---------------------------------------------------- histogram exemplars


class TestHistogramExemplars:
    def test_exposition_carries_bucket_exemplars(self):
        reg = MetricsRegistry()
        h = reg.register(Histogram("demo_seconds"))
        h.observe(0.004, exemplar="abc.t7")
        h.observe(123.0, exemplar="abc.t9")   # overflow (+Inf) bucket
        h.observe(0.004)                      # exemplar-less: no change
        ex = h.exemplars()
        # log buckets from 1e-4 at factor 2: 0.004 lands in le=0.0064
        assert ex["0.0064"] == {"trace_id": "abc.t7", "value": 0.004}
        assert ex["+Inf"] == {"trace_id": "abc.t9", "value": 123.0}
        text = reg.expose_prometheus()
        line = next(ln for ln in text.splitlines()
                    if ln.startswith('demo_seconds_bucket{le="0.0064"}'))
        assert '# {trace_id="abc.t7"} 0.004' in line
        inf = next(ln for ln in text.splitlines()
                   if ln.startswith('demo_seconds_bucket{le="+Inf"}'))
        assert '# {trace_id="abc.t9"} 123' in inf

    def test_ttft_exemplar_resolves_to_retained_trace(self, tiny_model):
        """Acceptance: the serving_ttft_seconds exposition carries an
        exemplar trace_id that resolves to a retained trace in the
        engine's ring — grafana's histogram-to-trace jump works."""
        cfg, params = tiny_model
        eng = Engine(cfg, params, page_size=8, num_pages=64,
                     max_batch_size=2, chunk_len=8,
                     clock=ManualClock(auto=0.001))
        eng.metrics = ServingMetrics(MetricsRegistry())
        eng.generate([[1, 2, 3]], SamplingParams(max_new_tokens=3))
        ex = eng.metrics.ttft.exemplars()
        assert ex, "TTFT observation recorded no exemplar"
        tids = {e["trace_id"] for e in ex.values()}
        ring = {t["trace_id"] for t in eng.tracer.traces()}
        assert tids <= ring
        text = eng.metrics.registry.expose_prometheus()
        assert any(f'trace_id="{t}"' in text for t in tids)


# ------------------------------------------------------ trace gossip


class TestTraceGossip:
    def _split_fleet_traces(self):
        """Router + two replica tracers, one request failed over across
        both replicas — the real split-ring topology."""
        router_tr = Tracer(clock=ManualClock(auto=1.0))
        reps = [Tracer(clock=ManualClock(auto=1.0)) for _ in range(2)]
        root = router_tr.start_trace("fleet#0")
        d0 = router_tr.start_span("router::dispatch", root)
        seg0 = reps[0].start_trace("request#0", context=d0.context())
        seg0.set_attribute("state", "evacuated")
        seg0.end()
        d0.end()
        fo = router_tr.start_span("router::failover", root)
        fo.end()
        d1 = router_tr.start_span("router::dispatch", root)
        seg1 = reps[1].start_trace("request#0", context=d1.context())
        seg1.end()
        d1.end()
        root.end()
        return router_tr, reps

    def test_publish_collect_merge_round_trip(self, tmp_path):
        from paddle_tpu.distributed.store import TCPStore
        from paddle_tpu.observability.trace_gossip import (
            TraceRingPublisher, collect_fleet_traces, collect_trace_rings)

        router_tr, reps = self._split_fleet_traces()
        store = TCPStore(is_master=True, world_size=1)
        pubs = [TraceRingPublisher(tr, rid, store)
                for rid, tr in enumerate(reps)]
        for pub in pubs:
            pub.publish()
        rings = collect_trace_rings(store, [0, 1, 2])   # 2 never published
        assert [src for src, _ in rings] == ["replica0", "replica1"]

        merged = collect_fleet_traces(
            store, [0, 1],
            extra_rings=[("router", router_tr.traces())])
        (m,) = merged                        # ONE trace across 3 rings
        assert len(m["segments"]) == 3
        assert m["name"] == "fleet#0"
        assert m["retained"] == "failover"   # strongest reason wins
        sources = [s["source"] for s in m["spans"]]
        assert {"router", "replica0", "replica1"} == set(sources)

        # chrome export of the merged view: integer tracks, labels
        # carry the source so the timeline reads across processes
        path = str(tmp_path / "fleet.json")
        export_traces_chrome(merged, path)
        with open(path) as f:
            evs = json.load(f)["traceEvents"]
        xs = [e for e in evs if e["ph"] == "X"]
        assert all(isinstance(e["tid"], int) for e in xs)
        assert any(e["name"] == "replica1: request#0" for e in xs)
        assert any(e["name"] == "router: router::failover" for e in xs)

    def test_garbled_and_stale_rings_absent(self):
        from paddle_tpu.distributed.store import TCPStore
        from paddle_tpu.observability.trace_gossip import (
            TraceRingPublisher, collect_trace_rings)

        store = TCPStore(is_master=True, world_size=1)
        store.set("traces/replica_0", "}{ not json")
        tr = Tracer(clock=ManualClock(auto=1.0))
        tr.start_trace("op").end()
        TraceRingPublisher(tr, 1, store,
                           clock=lambda: 100.0).publish()
        rings = collect_trace_rings(store, [0, 1])
        assert [src for src, _ in rings] == ["replica1"]     # 0 garbled
        assert collect_trace_rings(store, [0, 1], stale_after_s=5.0,
                                   clock=lambda: 200.0) == []
        fresh = collect_trace_rings(store, [0, 1], stale_after_s=5.0,
                                    clock=lambda: 101.0)
        assert [src for src, _ in fresh] == ["replica1"]

    def test_publisher_payload_bounds_and_stamps(self):
        from paddle_tpu.observability.trace_gossip import TraceRingPublisher

        class _Sink:
            def set(self, key, value):
                self.last = (key, value)

        tr = Tracer(clock=ManualClock(auto=1.0))
        for i in range(10):
            tr.start_trace(f"t{i}").end()
        pub = TraceRingPublisher(tr, 3, _Sink(), max_traces=4)
        payload = pub.publish()
        assert payload["replica"] == 3
        assert len(payload["traces"]) == 4   # newest win the slots
        assert payload["traces"][-1]["name"] == "t9"
        assert "clock_offset_s" in payload
        key, raw = pub.store.last
        assert key == "traces/replica_3"
        json.loads(raw)                      # JSON on the wire


# ---------------------------------------- fleet failover over live HTTP


def _factory(cfg, params, **kw):
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 64)
    kw.setdefault("max_batch_size", 2)
    kw.setdefault("chunk_len", 8)

    def make():
        # a private tracer per engine: the split-ring topology a real
        # per-process fleet has (the shared-default-tracer in-process
        # shape is covered by the soak test)
        return Engine(cfg, params, tracer=Tracer(), **kw)

    return make


@pytest.mark.faultinject
class TestFleetFailoverTraceHTTP:
    def test_hard_kill_yields_one_merged_trace_over_http(self, tiny_model):
        """Acceptance: hard-kill a replica mid-decode; every
        re-dispatched request reads as ONE trace — original dispatch,
        failover hop, re-dispatch, and the surviving replica's request
        segment — in the merged fleet view scraped from
        ``/traces?fleet=1`` over live HTTP."""
        cfg, params = tiny_model
        registry = MetricsRegistry()
        router = FleetRouter([_factory(cfg, params)] * 2,
                             tracer=Tracer(), registry=registry)
        rng = np.random.RandomState(11)
        prompts = [list(rng.randint(0, cfg.vocab_size, n))
                   for n in (5, 9, 7, 12)]
        reqs = [router.submit(p, SamplingParams(max_new_tokens=8))
                for p in prompts]
        # the root span is released when a request finishes — snapshot
        # the trace ids while the traces are in flight
        tids = {r.id: r._span.trace_id for r in reqs}
        for _ in range(3):
            router.step()
        assert any(r.tokens_out for r in reqs)
        victim = next(r.replica_id for r in reqs
                      if r.replica_id is not None)
        router.kill_replica(victim)
        while router.has_work():
            router.step()
        assert all(r.state == FleetRequestState.FINISHED for r in reqs)
        moved = [r for r in reqs if r.redispatches == 1]
        assert moved, "the kill moved no request"

        server = start_telemetry_server(port=0, registry=registry,
                                        tracer=router.tracer,
                                        router=router)
        try:
            body = _get_json(server.url + "/traces?fleet=1")
        finally:
            server.stop()
        assert body["fleet"] is True
        merged = {t["trace_id"]: t for t in body["traces"]}
        # one entry per trace_id — by construction of the merge, but
        # assert it on the wire anyway
        assert len(body["traces"]) == len(merged)
        for r in moved:
            tr = merged[tids[r.id]]          # present, exactly once
            names = [s["name"] for s in tr["spans"]]
            assert names.count("router::dispatch") == 2
            assert "router::failover" in names
            assert tr["retained"] == "failover"
            # the surviving replica's segment landed under the same
            # trace (the victim's unpublished ring died with it)
            survivor = f"replica{r.replica_id}"
            seg_sources = {s["source"] for s in tr["segments"]}
            assert survivor in seg_sources and "router" in seg_sources
            req_seg = [s for s in tr["spans"]
                       if s["source"] == survivor and
                       s["name"].startswith("request#")]
            assert req_seg, tr["spans"]
        # un-moved requests: one dispatch, no failover hop
        for r in reqs:
            if r.redispatches:
                continue
            names = [s["name"] for s in merged[tids[r.id]]["spans"]]
            assert names.count("router::dispatch") == 1
            assert "router::failover" not in names


# ------------------------------------------------- concurrent scrape


class TestConcurrentScrape:
    def test_fleet_scrape_during_generate_is_torn_read_free(
            self, tiny_model):
        """Scrape ``/traces`` and ``/traces?fleet=1`` continuously while
        the fleet decodes: every response parses, every trace is
        internally consistent (root-first spans, window covers every
        span) — no torn reads from the rings under mutation."""
        cfg, params = tiny_model
        registry = MetricsRegistry()
        router = FleetRouter([_factory(cfg, params)] * 2,
                             tracer=Tracer(), registry=registry)
        server = start_telemetry_server(port=0, registry=registry,
                                        tracer=router.tracer,
                                        router=router)
        errors, bodies = [], []
        stop = threading.Event()

        def scrape():
            while not stop.is_set():
                try:
                    bodies.append(_get_json(server.url + "/traces"))
                    bodies.append(
                        _get_json(server.url + "/traces?fleet=1"))
                except Exception as e:       # noqa: BLE001 - collected
                    errors.append(repr(e))

        t = threading.Thread(target=scrape, daemon=True)
        try:
            t.start()
            rng = np.random.RandomState(5)
            reqs = [router.submit(list(rng.randint(0, cfg.vocab_size, 6)),
                                  SamplingParams(max_new_tokens=6))
                    for _ in range(6)]
            while router.has_work():
                router.step()
            assert all(r.state == FleetRequestState.FINISHED
                       for r in reqs)
        finally:
            stop.set()
            t.join(timeout=5.0)
            server.stop()
        assert errors == []
        assert len(bodies) >= 2
        for body in bodies:
            for tr in body["traces"]:
                spans = tr["spans"]
                assert spans, tr
                for s in spans:
                    assert s["trace_id"] == tr["trace_id"]
                    assert tr["start_s"] <= s["start_s"]
                    if s["end_s"] is not None and tr["end_s"] is not None:
                        assert s["end_s"] <= tr["end_s"] + 1e-9
