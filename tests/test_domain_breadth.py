"""signal / text / onnx / vision-zoo breadth (reference strategy:
test_signal.py compares stft/istft against scipy-style references;
test_viterbi_decode.py against a brute-force dynamic program; vision
model tests are shape/forward smoke)."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestSignal:
    def test_frame_overlap_add_roundtrip_disjoint(self):
        from paddle_tpu.signal import frame, overlap_add

        x = np.arange(32, dtype=np.float32)
        f = frame(x, 8, 8)               # disjoint frames
        assert f.shape == (8, 4)
        y = overlap_add(f, 8)
        np.testing.assert_allclose(np.asarray(y), x)

    def test_frame_values(self):
        from paddle_tpu.signal import frame

        x = np.arange(10, dtype=np.float32)
        f = np.asarray(frame(x, 4, 2))
        np.testing.assert_array_equal(f[:, 0], [0, 1, 2, 3])
        np.testing.assert_array_equal(f[:, 1], [2, 3, 4, 5])

    def test_stft_matches_numpy_dft(self):
        from paddle_tpu.signal import stft

        rng = np.random.RandomState(0)
        x = rng.randn(256).astype(np.float32)
        n_fft, hop = 64, 16
        spec = np.asarray(stft(x, n_fft, hop_length=hop, center=False))
        # frame 0 of the numpy reference
        ref0 = np.fft.rfft(x[:n_fft])
        np.testing.assert_allclose(spec[:, 0], ref0, atol=1e-4)
        ref3 = np.fft.rfft(x[3 * hop:3 * hop + n_fft])
        np.testing.assert_allclose(spec[:, 3], ref3, atol=1e-4)

    def test_stft_istft_reconstruction(self):
        from paddle_tpu.signal import istft, stft

        rng = np.random.RandomState(1)
        x = rng.randn(512).astype(np.float32)
        n_fft, hop = 64, 16
        win = np.hanning(n_fft).astype(np.float32)
        spec = stft(x, n_fft, hop_length=hop, window=win, center=True)
        y = np.asarray(istft(spec, n_fft, hop_length=hop, window=win,
                             center=True, length=512))
        np.testing.assert_allclose(y, x, atol=1e-3)


class TestViterbi:
    @staticmethod
    def _brute(emis, trans, start, stop):
        """Exhaustive best-path search (tiny T, N)."""
        import itertools

        T, N = emis.shape
        best, path = -1e30, None
        for tags in itertools.product(range(N), repeat=T):
            s = start[tags[0]] + emis[0, tags[0]]
            for t in range(1, T):
                s += trans[tags[t - 1], tags[t]] + emis[t, tags[t]]
            s += stop[tags[-1]]
            if s > best:
                best, path = s, tags
        return best, list(path)

    def test_matches_bruteforce(self):
        from paddle_tpu.text import viterbi_decode

        rng = np.random.RandomState(0)
        B, T, N = 3, 5, 4
        emis = rng.randn(B, T, N).astype(np.float32)
        trans = rng.randn(N, N).astype(np.float32)
        scores, paths = viterbi_decode(emis, trans, lengths=None,
                                       include_bos_eos_tag=True)
        start, stop = trans[N - 2], trans[:, N - 1]
        for b in range(B):
            s_ref, p_ref = self._brute(emis[b], trans, start, stop)
            np.testing.assert_allclose(float(np.asarray(scores)[b]),
                                       s_ref, rtol=1e-5)
            assert list(np.asarray(paths)[b]) == p_ref

    def test_variable_lengths(self):
        from paddle_tpu.text import ViterbiDecoder

        rng = np.random.RandomState(1)
        B, T, N = 2, 6, 3
        emis = rng.randn(B, T, N).astype(np.float32)
        trans = rng.randn(N, N).astype(np.float32)
        dec = ViterbiDecoder(trans, include_bos_eos_tag=False)
        lens = np.array([4, 6], np.int32)
        scores, paths = dec(paddle.to_tensor(emis),
                            paddle.to_tensor(lens))
        # batch 0's score must equal decoding its 4-step prefix alone
        s_short, p_short = dec(paddle.to_tensor(emis[:1, :4]))
        np.testing.assert_allclose(float(np.asarray(scores.data)[0]),
                                   float(np.asarray(s_short.data)[0]),
                                   rtol=1e-5)
        assert (list(np.asarray(paths.data)[0][:4])
                == list(np.asarray(p_short.data)[0]))


class TestTextDatasets:
    def test_uci_housing_parses_local_table(self, tmp_path):
        from paddle_tpu.text import UCIHousing

        rng = np.random.RandomState(0)
        rows = rng.rand(20, 14).astype(np.float32)
        f = tmp_path / "housing.data"
        np.savetxt(f, rows)
        train = UCIHousing(data_file=str(f), mode="train")
        test = UCIHousing(data_file=str(f), mode="test")
        assert len(train) == 16 and len(test) == 4
        x, y = train[0]
        assert x.shape == (13,) and y.shape == (1,)
        assert x.min() >= 0.0 and x.max() <= 1.0

    def test_imikolov_ngrams(self, tmp_path):
        from paddle_tpu.text import Imikolov

        f = tmp_path / "ptb.txt"
        f.write_text("the cat sat on the mat\nthe dog sat\n")
        ds = Imikolov(data_file=str(f), window_size=3)
        assert len(ds) == 4 + 1
        assert all(g.shape == (3,) for g in ds)

    def test_no_egress_error_is_directed(self):
        from paddle_tpu.text import UCIHousing, WMT14

        with pytest.raises(FileNotFoundError, match="no network egress"):
            UCIHousing()
        with pytest.raises(FileNotFoundError, match="no network egress"):
            WMT14()


class TestOnnxDesignOut:
    def test_export_emits_stablehlo_artifact(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu.jit import Predictor

        model = nn.Linear(4, 2)
        x = paddle.to_tensor(np.ones((1, 4), np.float32))
        path = paddle.onnx.export(model, str(tmp_path / "m.onnx"),
                                  input_spec=[x])
        pred = Predictor(path)
        out = pred(np.ones((1, 4), np.float32))
        np.testing.assert_allclose(np.asarray(out.data),
                                   np.asarray(model(x).data), atol=1e-6)


class TestVisionZoo:
    @pytest.mark.parametrize("ctor,shape", [
        ("alexnet", (1, 3, 224, 224)),
        ("squeezenet1_1", (1, 3, 224, 224)),
        ("shufflenet_v2_x1_0", (1, 3, 224, 224)),
        ("densenet121", (1, 3, 64, 64)),
    ])
    def test_forward_shapes(self, ctor, shape):
        from paddle_tpu.vision import models

        paddle.seed(0)
        model = getattr(models, ctor)(num_classes=10)
        model.eval()
        x = paddle.to_tensor(
            np.random.RandomState(0).rand(*shape).astype(np.float32))
        out = model(x)
        assert tuple(out.shape) == (1, 10)
        assert np.isfinite(np.asarray(out.data)).all()


class TestSignalAxis0:
    def test_frame_axis0_layout(self):
        from paddle_tpu.signal import frame

        x = np.arange(20, dtype=np.float32).reshape(10, 2)
        f = np.asarray(frame(x, 4, 2, axis=0))
        assert f.shape == (4, 4, 2)          # (flen, num, batch)
        np.testing.assert_array_equal(f[:, 0, 0], x[:4, 0])
        np.testing.assert_array_equal(f[:, 1, 1], x[2:6, 1])

    def test_overlap_add_axis0_inverts_frame(self):
        from paddle_tpu.signal import frame, overlap_add

        x = np.arange(16, dtype=np.float32).reshape(16, 1)
        f = frame(x, 4, 4, axis=0)           # disjoint
        y = np.asarray(overlap_add(f, 4, axis=0))
        np.testing.assert_array_equal(y, x)

    def test_bad_axis_is_loud(self):
        from paddle_tpu.signal import frame

        with pytest.raises(ValueError, match="axis 0 or -1"):
            frame(np.zeros((4, 8), np.float32), 2, 1, axis=1)


class TestImdbParse:
    def test_parses_tar_with_min_freq_cutoff(self, tmp_path):
        import io
        import tarfile

        from paddle_tpu.text import Imdb

        tar_path = tmp_path / "aclImdb.tar.gz"
        docs = {
            "aclImdb/train/pos/0_9.txt": b"good good good film",
            "aclImdb/train/neg/1_2.txt": b"bad bad film",
            "aclImdb/test/pos/0_8.txt": b"ignored split",
        }
        with tarfile.open(tar_path, "w:gz") as tf:
            for name, body in docs.items():
                info = tarfile.TarInfo(name)
                info.size = len(body)
                tf.addfile(info, io.BytesIO(body))

        ds = Imdb(data_file=str(tar_path), mode="train", cutoff=1)
        assert len(ds) == 2
        # cutoff=1 keeps words with freq > 1: good(3), bad(2), film(2)
        assert set(ds.word_idx) == {"good", "bad", "film", "<unk>"}
        labels = sorted(int(l) for _, l in [ds[i] for i in range(2)])
        assert labels == [0, 1]


class TestFusedTransformer:
    def test_fused_attention_matches_unfused_math(self):
        """The fused layer must equal the hand-computed pre-LN qkv/attn/
        proj/residual chain (fused_attention_op semantics)."""
        import jax.numpy as jnp

        from paddle_tpu.incubate.nn import FusedMultiHeadAttention

        paddle.seed(0)
        layer = FusedMultiHeadAttention(32, 4, dropout_rate=0.0,
                                        attn_dropout_rate=0.0,
                                        normalize_before=True)
        layer.eval()
        x = np.random.RandomState(0).randn(2, 8, 32).astype(np.float32)
        out = np.asarray(layer(paddle.to_tensor(x)).data)

        # manual reference
        import jax

        def ln(a, g, b):
            mu = a.mean(-1, keepdims=True)
            var = a.var(-1, keepdims=True)
            return (a - mu) / np.sqrt(var + 1e-5) * g + b

        g = np.asarray(layer.ln_scale.data)
        bb = np.asarray(layer.ln_bias.data)
        W = np.asarray(layer.qkv_weight.data)
        bqkv = np.asarray(layer.qkv_bias.data)
        Wo = np.asarray(layer.linear_weight.data)
        bo = np.asarray(layer.linear_bias.data)
        h = ln(x, g, bb)
        qkv = (h @ W + bqkv).reshape(2, 8, 3, 4, 8)
        q, k, v = [qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3)]
        att = q @ k.transpose(0, 1, 3, 2) / np.sqrt(8.0)
        att = np.exp(att - att.max(-1, keepdims=True))
        att = att / att.sum(-1, keepdims=True)
        o = (att @ v).transpose(0, 2, 1, 3).reshape(2, 8, 32)
        ref = x + (o @ Wo + bo)
        np.testing.assert_allclose(out, ref, atol=2e-4)

    def test_fused_encoder_layer_trains(self):
        from paddle_tpu.incubate.nn import FusedTransformerEncoderLayer

        paddle.seed(1)
        layer = FusedTransformerEncoderLayer(32, 4, 64, dropout_rate=0.0,
                                             normalize_before=True)
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=layer.parameters())
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 8, 32).astype(np.float32))
        losses = []
        for _ in range(4):
            loss = (layer(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.data))
        assert losses[-1] < losses[0]

    def test_fused_attention_rejects_unsupported(self):
        from paddle_tpu.incubate.nn import FusedMultiHeadAttention

        with pytest.raises(NotImplementedError, match="need_weights"):
            FusedMultiHeadAttention(32, 4, need_weights=True)
        layer = FusedMultiHeadAttention(32, 4)
        with pytest.raises(NotImplementedError, match="cache"):
            layer(paddle.to_tensor(np.ones((1, 4, 32), np.float32)),
                  cache=("k", "v"))


class TestAuc:
    def test_matches_exact_rank_statistic(self):
        """Bucketed AUC (reference metrics.py:592) vs the exact
        Mann-Whitney rank statistic."""
        from paddle_tpu.metric import Auc

        rng = np.random.RandomState(0)
        n = 4000
        labels = rng.randint(0, 2, n)
        score = np.clip(labels * 0.3 + rng.rand(n) * 0.7, 0, 1)
        m = Auc()
        for lo in range(0, n, 512):
            m.update(np.stack([1 - score[lo:lo + 512],
                               score[lo:lo + 512]], 1),
                     labels[lo:lo + 512])
        pos, neg = score[labels == 1], score[labels == 0]
        exact = (sum(float(np.sum(p > neg) + 0.5 * np.sum(p == neg))
                     for p in pos) / (len(pos) * len(neg)))
        assert abs(m.accumulate() - exact) < 2e-3

    def test_empty_and_single_class(self):
        from paddle_tpu.metric import Auc

        m = Auc()
        assert m.accumulate() == 0.0
        m.update(np.array([[0.3, 0.7]]), np.array([1]))
        assert m.accumulate() == 0.0     # no negatives yet
        m.reset()
        assert m.accumulate() == 0.0

    def test_non_roc_curve_rejected(self):
        from paddle_tpu.metric import Auc

        import pytest as _pytest
        with _pytest.raises(ValueError, match="ROC"):
            Auc(curve="PR")
