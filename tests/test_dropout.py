"""Dropout tests: the GPTConfig.dropout knob must be REAL (round-2 verdict
weak #10: accepted-and-ignored knobs are worse than none), deterministic
under an explicit key, and TP-safe (identical masks across an mp group —
the reference's RNGStatesTracker global_seed discipline)."""
import jax
import numpy as np
import pytest

from paddle_tpu.distributed.engine import EngineConfig, HybridEngine
from paddle_tpu.models.gpt import GPTConfig, gpt_loss

BASE = dict(vocab_size=256, max_seq_len=64, hidden=64, num_layers=4,
            num_heads=4, ffn_hidden=128, dtype="float32", use_flash=False,
            remat="nothing")


def _batch(bs=8, seq=32, seed=0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, 256, (bs, seq)).astype(np.int32)
    labels = np.concatenate([tokens[:, 1:], np.full((bs, 1), -100)],
                            axis=1).astype(np.int32)
    return tokens, labels


class TestFunctionalDropout:
    def test_key_changes_loss(self):
        from paddle_tpu.models.gpt import gpt_init

        cfg = GPTConfig(**BASE, dropout=0.2)
        params = gpt_init(cfg, jax.random.key(0))
        tokens, labels = _batch()
        l1 = float(gpt_loss(cfg, params, tokens, labels,
                            dropout_key=jax.random.key(1)))
        l2 = float(gpt_loss(cfg, params, tokens, labels,
                            dropout_key=jax.random.key(2)))
        l1b = float(gpt_loss(cfg, params, tokens, labels,
                             dropout_key=jax.random.key(1)))
        assert l1 != l2          # different masks
        assert l1 == l1b         # deterministic per key

    def test_no_key_is_eval_mode(self):
        from paddle_tpu.models.gpt import gpt_init

        cfg_d = GPTConfig(**BASE, dropout=0.2)
        cfg_0 = GPTConfig(**BASE, dropout=0.0)
        params = gpt_init(cfg_d, jax.random.key(0))
        tokens, labels = _batch()
        l_eval = float(gpt_loss(cfg_d, params, tokens, labels))
        l_zero = float(gpt_loss(cfg_0, params, tokens, labels))
        assert l_eval == l_zero  # dropout off without a key

    def test_expectation_approximates_eval(self):
        """Inverted dropout: mean train loss over many keys ≈ eval loss
        neighborhood (coarse sanity, not an identity)."""
        from paddle_tpu.models.gpt import gpt_init

        cfg = GPTConfig(**BASE, dropout=0.1)
        params = gpt_init(cfg, jax.random.key(0))
        tokens, labels = _batch(bs=4)
        l_eval = float(gpt_loss(cfg, params, tokens, labels))
        ls = [float(gpt_loss(cfg, params, tokens, labels,
                             dropout_key=jax.random.key(i)))
              for i in range(8)]
        assert abs(np.mean(ls) - l_eval) < 0.25


@pytest.mark.slow
class TestEngineDropout:
    def test_step_deterministic_per_seed(self):
        cfg = GPTConfig(**BASE, dropout=0.2)
        tokens, labels = _batch()

        def run(seed):
            eng = HybridEngine(cfg, devices=jax.devices()[:1])
            p, o = eng.init(seed=0)
            _, _, loss = eng.step(p, o, tokens, labels, lr=1e-3,
                                  dropout_seed=seed)
            return float(loss)

        assert run(7) == run(7)
        assert run(7) != run(8)

    def test_tp_replicas_stay_synced_under_dropout(self):
        """THE TP-dropout invariant: with mp=2 (+zr), masks must agree
        within each TP group or grads desync and replicated params drift."""
        cfg = GPTConfig(**BASE, dropout=0.2)
        eng = HybridEngine(cfg, dp=2, mp=2, sharding=2)
        params, opt = eng.init(seed=0)
        tokens, labels = _batch()
        for s in range(3):
            params, opt, _ = eng.step(params, opt, tokens, labels, lr=1e-3,
                                      dropout_seed=s)
        for leaf in jax.tree_util.tree_leaves(params):
            by_index = {}
            for shard in leaf.addressable_shards:
                k = str(shard.index)
                if k in by_index:
                    np.testing.assert_array_equal(np.asarray(shard.data),
                                                  by_index[k])
                else:
                    by_index[k] = np.asarray(shard.data)

    def test_pipeline_and_accum_with_dropout(self):
        cfg = GPTConfig(**BASE, dropout=0.1)
        eng = HybridEngine(cfg, pp=2, dp=2, devices=jax.devices()[:4],
                           engine_cfg=EngineConfig(num_microbatches=2,
                                                   accum_steps=2))
        params, opt = eng.init(seed=0)
        tokens, labels = _batch()
        losses = []
        for s in range(2):
            params, opt, loss = eng.step(params, opt, tokens, labels,
                                         lr=1e-3, dropout_seed=s)
            losses.append(float(loss))
        assert all(np.isfinite(losses))

    def test_dropout_zero_unchanged(self):
        """dropout=0 must produce bit-identical losses to before the knob
        existed (seed arg ignored)."""
        cfg = GPTConfig(**BASE, dropout=0.0)
        eng = HybridEngine(cfg, devices=jax.devices()[:1])
        p, o = eng.init(seed=0)
        tokens, labels = _batch()
        p2, o2, l1 = eng.step(p, o, tokens, labels, lr=1e-3, dropout_seed=1)
        eng2 = HybridEngine(cfg, devices=jax.devices()[:1])
        p, o = eng2.init(seed=0)
        _, _, l2 = eng2.step(p, o, tokens, labels, lr=1e-3, dropout_seed=2)
        assert float(l1) == float(l2)
