"""Elastic manager + launcher relaunch tests (reference strategy:
test_fleet_elastic_manager.py mocks etcd; here the membership store is
the framework's real native TCPStore)."""
import os
import subprocess
import sys
import time

import pytest

from paddle_tpu.distributed.fleet.elastic import (ELASTIC_EXIT_CODE,
                                                  ElasticManager)
from paddle_tpu.distributed.store import TCPStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestElasticManager:
    def test_register_and_probe(self):
        store = TCPStore(is_master=True, world_size=2)
        a = ElasticManager(store, job_id="j1", np=2, host="nodeA",
                           heartbeat_interval=0.1, node_timeout=0.5)
        b = ElasticManager(store, job_id="j1", np=2, host="nodeB",
                           heartbeat_interval=0.1, node_timeout=0.5)
        a.register()
        b.register()
        assert a.probe("nodeA") and a.probe("nodeB")
        assert a.match(["nodeA", "nodeB"])
        a.deregister()
        b.deregister()

    def test_watch_detects_lost_node(self):
        store = TCPStore(is_master=True, world_size=2)
        a = ElasticManager(store, job_id="j2", np=2, host="nodeA",
                           heartbeat_interval=0.1, node_timeout=0.4)
        b = ElasticManager(store, job_id="j2", np=2, host="nodeB",
                           heartbeat_interval=0.1, node_timeout=0.4)
        a.register()
        b.register()
        assert a.wait_for_np(["nodeA", "nodeB"], timeout=5)
        b.deregister()   # node B dies
        event, dead = a.watch(["nodeA", "nodeB"], timeout=5)
        assert event == "lost" and dead == ["nodeB"]
        a.deregister()

    def test_stale_heartbeat_counts_as_dead(self):
        # liveness = the per-node counter keeps ADVANCING; a node whose
        # counter stalls for > node_timeout (of the READER's monotonic
        # clock — wall clocks never cross hosts) probes dead
        store = TCPStore(is_master=True, world_size=1)
        a = ElasticManager(store, job_id="j3", np=1, host="nodeA",
                           heartbeat_interval=10.0, node_timeout=0.3)
        store.add("elastic/j3/nodeA", 1)       # one beat, then silence
        assert a.probe("nodeA")                # first sighting: alive
        time.sleep(0.4)
        assert not a.probe("nodeA")            # counter never advanced

    def test_relaunch_not_fooled_by_stale_counter(self):
        # a freshly-constructed manager (empty _seen, e.g. right after
        # a relaunch) must NOT wait_for_np-succeed on a crashed peer
        # whose counter merely exists
        store = TCPStore(is_master=True, world_size=2)
        dead = ElasticManager(store, job_id="j5", np=2, host="deadB",
                              heartbeat_interval=0.1, node_timeout=0.3)
        store.add("elastic/j5/deadB", 1)   # B beat once, then crashed
        live = ElasticManager(store, job_id="j5", np=2, host="nodeA",
                              heartbeat_interval=0.1, node_timeout=0.3)
        live.register()
        fresh = ElasticManager(store, job_id="j5", np=2, host="nodeA",
                               heartbeat_interval=0.1, node_timeout=0.3)
        assert not fresh.wait_for_np(["nodeA", "deadB"], timeout=1.5)
        live.deregister()

    def test_never_registered_is_dead(self):
        store = TCPStore(is_master=True, world_size=1)
        a = ElasticManager(store, job_id="j4", np=1, host="nodeA",
                           heartbeat_interval=0.1, node_timeout=0.5)
        assert not a.probe("ghost")


WORKER_ELASTIC = """
import os, sys
marker = os.path.join({tmp!r}, "attempt.flag")
attempt = int(os.environ["PADDLE_RESTART_ATTEMPT"])
rank = int(os.environ["PADDLE_TRAINER_ID"])
print(f"run rank={{rank}} attempt={{attempt}}")
if attempt == 0 and rank == 1:
    sys.exit({code})   # request relaunch
print(f"DONE rank={{rank}} attempt={{attempt}}")
"""


class TestLauncherRestart:
    def _launch(self, tmp_path, max_restarts, code=101):
        script = tmp_path / "w.py"
        script.write_text(WORKER_ELASTIC.format(tmp=str(tmp_path),
                                                code=code))
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("PADDLE_", "XLA_", "JAX_"))}
        env["PYTHONPATH"] = REPO
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--max_restarts", str(max_restarts),
             "--log_dir", str(tmp_path / "logs"), str(script)],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
        logs = {f.name: f.read_text()
                for f in sorted((tmp_path / "logs").iterdir())}
        return proc, logs

    def test_relaunch_after_elastic_exit(self, tmp_path):
        proc, logs = self._launch(tmp_path, max_restarts=1,
                                  code=ELASTIC_EXIT_CODE)
        assert proc.returncode == 0, (proc.stdout, proc.stderr, logs)
        assert "restart attempt 1" in logs["workerlog.1"]
        assert "DONE rank=1 attempt=1" in logs["workerlog.1"]
        assert "DONE rank=0 attempt=1" in logs["workerlog.0"]

    def test_no_restart_budget_fails(self, tmp_path):
        proc, _ = self._launch(tmp_path, max_restarts=0,
                               code=ELASTIC_EXIT_CODE)
        assert proc.returncode == ELASTIC_EXIT_CODE
