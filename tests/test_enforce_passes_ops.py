"""Enforce taxonomy, allocator flags, constant-folding/CSE passes,
detection ops (reference strategy: per-pass program-rewrite assertions
a la ir pass unit tests; nms against a numpy greedy reference)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.core import enforce as E


class TestEnforce:
    def test_taxonomy_catchable_both_ways(self):
        with pytest.raises(E.EnforceNotMet):
            E.enforce(False, "boom")
        with pytest.raises(ValueError):       # dual-inherits ValueError
            E.enforce(False, "boom")
        with pytest.raises(E.NotFoundError):
            E.enforce(False, "missing {}", "x", error_cls=E.NotFoundError)

    def test_helpers(self):
        E.enforce_eq(3, 3)
        with pytest.raises(E.InvalidArgumentError, match="expected 4"):
            E.enforce_eq(3, 4, what="rank")
        with pytest.raises(E.InvalidArgumentError, match="must be > 0"):
            E.enforce_gt(0, 0, what="hop")
        E.enforce_shape(np.zeros((2, 3)), (2, -1))
        with pytest.raises(E.InvalidArgumentError, match="shape mismatch"):
            E.enforce_shape(np.zeros((2, 3)), (3, 3), what="weight")


class TestAllocatorFlags:
    def test_preallocate_strategy_sets_env(self, monkeypatch):
        import os

        from paddle_tpu.core import flags

        monkeypatch.delenv("XLA_PYTHON_CLIENT_PREALLOCATE", raising=False)
        monkeypatch.delenv("XLA_PYTHON_CLIENT_MEM_FRACTION", raising=False)
        flags.set_flags({"allocator_strategy": "preallocate",
                         "fraction_of_device_memory_to_use": 0.5})
        try:
            flags.apply_allocator_flags()
            assert os.environ["XLA_PYTHON_CLIENT_PREALLOCATE"] == "true"
            assert os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] == "0.5"
        finally:
            # reset flags AND re-apply so the env overrides are cleared
            # for the rest of the process (monkeypatch then restores the
            # pre-test values)
            flags.set_flags({"allocator_strategy": "auto_growth",
                             "fraction_of_device_memory_to_use": 0.0})
            flags.apply_allocator_flags()

    def test_default_flags_leave_user_env_alone(self, monkeypatch):
        """import-time apply must not clobber the user's own
        XLA_PYTHON_CLIENT_* variables when flags are defaults."""
        import importlib
        import os

        from paddle_tpu.core import flags

        monkeypatch.setenv("XLA_PYTHON_CLIENT_MEM_FRACTION", "0.75")
        saved = {n: dict(e) for n, e in flags._registry.items()}
        try:
            flags._registry["fraction_of_device_memory_to_use"][
                "explicit"] = False
            flags._registry["allocator_strategy"]["explicit"] = False
            flags.apply_allocator_flags()
            assert os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] == "0.75"
        finally:
            for n, e in saved.items():
                flags._registry[n] = e
        del importlib


class TestNewPasses:
    def _program(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4], "float32")
            c = paddle.to_tensor(np.float32(2.0)) * paddle.to_tensor(
                np.float32(3.0))                      # fully constant
            a = x * 2.0
            b = x * 2.0                               # duplicate of a
            out = a + b + c
        return prog, x, out

    def test_constant_folding(self):
        prog, x, out = self._program()
        n_before = len(prog.ops)
        folded = static.new_pass("constant_folding").apply(prog, [])
        assert folded >= 1
        assert len(prog.ops) < n_before
        exe = static.Executor()
        (r,) = exe.run(prog, feed={"x": np.ones(4, np.float32)},
                       fetch_list=[out], use_passes=())
        np.testing.assert_allclose(r, np.ones(4) * 2 + np.ones(4) * 2 + 6)

    def test_cse_merges_duplicates(self):
        prog, x, out = self._program()
        merged = static.new_pass(
            "common_subexpression_elimination").apply(prog, [])
        assert merged >= 1
        exe = static.Executor()
        (r,) = exe.run(prog, feed={"x": np.ones(4, np.float32)},
                       fetch_list=[out], use_passes=())
        np.testing.assert_allclose(r, np.ones(4) * 2 + np.ones(4) * 2 + 6)


class TestDetectionOps:
    def test_box_iou_known_values(self):
        from paddle_tpu.vision.ops import box_iou

        a = np.array([[0, 0, 2, 2]], np.float32)
        b = np.array([[1, 1, 3, 3], [0, 0, 2, 2], [4, 4, 5, 5]], np.float32)
        iou = np.asarray(box_iou(a, b))
        np.testing.assert_allclose(iou[0], [1 / 7, 1.0, 0.0], atol=1e-6)

    @staticmethod
    def _greedy_nms_ref(boxes, scores, thr):
        idxs = list(np.argsort(-scores))
        keep = []
        while idxs:
            i = idxs.pop(0)
            keep.append(i)
            rest = []
            for j in idxs:
                xx1 = max(boxes[i, 0], boxes[j, 0])
                yy1 = max(boxes[i, 1], boxes[j, 1])
                xx2 = min(boxes[i, 2], boxes[j, 2])
                yy2 = min(boxes[i, 3], boxes[j, 3])
                inter = max(0, xx2 - xx1) * max(0, yy2 - yy1)
                a1 = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
                a2 = (boxes[j, 2] - boxes[j, 0]) * (boxes[j, 3] - boxes[j, 1])
                if inter / max(a1 + a2 - inter, 1e-10) <= thr:
                    rest.append(j)
            idxs = rest
        return keep

    def test_nms_matches_greedy_reference(self):
        from paddle_tpu.vision.ops import nms

        rng = np.random.RandomState(0)
        xy = rng.rand(24, 2) * 10
        wh = rng.rand(24, 2) * 4 + 0.5
        boxes = np.concatenate([xy, xy + wh], 1).astype(np.float32)
        scores = rng.rand(24).astype(np.float32)
        got = list(np.asarray(nms(boxes, 0.4, scores=scores)))
        ref = self._greedy_nms_ref(boxes, scores, 0.4)
        assert got == ref

    def test_nms_category_aware_and_topk(self):
        from paddle_tpu.vision.ops import nms

        boxes = np.array([[0, 0, 2, 2], [0, 0, 2, 2], [5, 5, 6, 6]],
                         np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        # same box, different categories: both kept
        got = list(np.asarray(nms(boxes, 0.5, scores=scores,
                                  category_idxs=np.array([0, 1, 0]))))
        assert got == [0, 1, 2]
        got = list(np.asarray(nms(boxes, 0.5, scores=scores, top_k=1)))
        assert got == [0]


class TestCSERegressions:
    def test_cse_keeps_fetched_duplicate(self):
        """A fetch target must keep its producer even when another op is
        identical (review r4: KeyError on replay otherwise)."""
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4], "float32")
            a = x * 2.0
            b = x * 2.0
        static.new_pass("common_subexpression_elimination").apply(
            prog, [prog.lookup(b)])
        exe = static.Executor()
        (r,) = exe.run(prog, feed={"x": np.ones(4, np.float32)},
                       fetch_list=[b], use_passes=())
        np.testing.assert_allclose(r, np.full(4, 2.0))

    def test_cse_does_not_mutate_source_program(self):
        """Executor applies passes to a clone; the original program's
        leaves must stay untouched (they are shared objects)."""
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4], "float32")
            a = x * 2.0
            b = x * 2.0
            out = a + b
        import copy

        before = [[getattr(l, "vid", repr(l)) for l in op.leaves]
                  for op in prog.ops]
        exe = static.Executor()
        exe.run(prog, feed={"x": np.ones(4, np.float32)}, fetch_list=[out],
                use_passes=("common_subexpression_elimination",
                            "dead_code_elimination"))
        after = [[getattr(l, "vid", repr(l)) for l in op.leaves]
                 for op in prog.ops]
        assert before == after
        del copy


class TestRandomOpsSurviveOptimization:
    def test_random_op_not_folded_or_merged(self):
        prog = static.Program()
        with static.program_guard(prog):
            a = paddle.rand([4])
            b = paddle.rand([4])
            out = a + b
        n0 = len(prog.ops)
        static.new_pass("constant_folding").apply(prog, [prog.lookup(out)])
        static.new_pass("common_subexpression_elimination").apply(prog, [])
        names = [op.name for op in prog.ops]
        assert names.count("rand") == 2, names   # neither folded nor merged
        exe = static.Executor()
        # the two draws stay INDEPENDENT (a merged/folded program would
        # make out exactly 2*a); replay itself is deterministic by design
        # (functional RNG keys are captured with the program)
        (ra, rb) = exe.run(prog, feed={}, fetch_list=[a, b], use_passes=())
        assert not np.allclose(ra, rb)

    def test_random_op_consuming_folded_constant(self):
        """A random op fed by a folded-away producer must get the folded
        VALUE spliced into its leaves, not a dangling vid (review r4)."""
        prog = static.Program()
        with static.program_guard(prog):
            p = paddle.ones([4]) * 0.3
            x = paddle.bernoulli(p)
        static.new_pass("constant_folding").apply(prog, [prog.lookup(x)])
        exe = static.Executor()
        (r,) = exe.run(prog, feed={}, fetch_list=[x], use_passes=())
        assert set(np.unique(r)).issubset({0.0, 1.0})
