"""fft + distribution API tests (parity: paddle.fft / paddle.distribution
test strategy — numeric comparison against numpy/scipy formulas)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distribution import (Bernoulli, Beta, Categorical,
                                     Dirichlet, Normal, Uniform,
                                     kl_divergence)


class TestFFT:
    def test_fft_roundtrip(self):
        x = np.random.RandomState(0).randn(16).astype(np.float32)
        X = paddle.fft.fft(paddle.to_tensor(x.astype(np.complex64)))
        back = paddle.fft.ifft(X)
        np.testing.assert_allclose(np.asarray(back.data).real, x, atol=1e-5)
        np.testing.assert_allclose(np.asarray(X.data),
                                   np.fft.fft(x), atol=1e-3)

    def test_rfft_matches_numpy(self):
        x = np.random.RandomState(1).randn(4, 32).astype(np.float32)
        out = paddle.fft.rfft(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(out.data),
                                   np.fft.rfft(x), atol=1e-3)

    def test_fft2_and_shift(self):
        x = np.random.RandomState(2).randn(8, 8).astype(np.float32)
        out = paddle.fft.fftshift(paddle.fft.fft2(
            paddle.to_tensor(x.astype(np.complex64))))
        np.testing.assert_allclose(np.asarray(out.data),
                                   np.fft.fftshift(np.fft.fft2(x)),
                                   atol=1e-2)

    def test_fftfreq(self):
        np.testing.assert_allclose(np.asarray(paddle.fft.fftfreq(8, 0.5).data),
                                   np.fft.fftfreq(8, 0.5), atol=1e-7)

    def test_ortho_norm(self):
        x = np.random.RandomState(3).randn(16).astype(np.float32)
        out = paddle.fft.rfft(paddle.to_tensor(x), norm="ortho")
        np.testing.assert_allclose(np.asarray(out.data),
                                   np.fft.rfft(x, norm="ortho"), atol=1e-4)


class TestDistribution:
    def test_normal(self):
        paddle.seed(0)
        d = Normal(1.0, 2.0)
        s = d.sample([20000])
        arr = np.asarray(s.data)
        assert abs(arr.mean() - 1.0) < 0.1
        assert abs(arr.std() - 2.0) < 0.1
        lp = float(d.log_prob(1.0).data)
        ref = -np.log(2.0) - 0.5 * np.log(2 * np.pi)
        np.testing.assert_allclose(lp, ref, atol=1e-5)
        ent = float(d.entropy().data)
        np.testing.assert_allclose(ent, 0.5 + 0.5 * np.log(2 * np.pi)
                                   + np.log(2.0), atol=1e-5)

    def test_uniform(self):
        paddle.seed(1)
        d = Uniform(-1.0, 3.0)
        arr = np.asarray(d.sample([10000]).data)
        assert arr.min() >= -1.0 and arr.max() < 3.0
        assert abs(float(d.log_prob(0.0).data) - np.log(1 / 4)) < 1e-5
        assert float(d.log_prob(5.0).data) == -np.inf

    def test_categorical(self):
        paddle.seed(2)
        d = Categorical(probs=[0.1, 0.2, 0.7])
        arr = np.asarray(d.sample([20000]).data)
        freq = np.bincount(arr, minlength=3) / len(arr)
        np.testing.assert_allclose(freq, [0.1, 0.2, 0.7], atol=0.02)
        np.testing.assert_allclose(float(d.log_prob(2).data), np.log(0.7),
                                   atol=1e-5)

    def test_bernoulli(self):
        paddle.seed(3)
        d = Bernoulli(probs=0.3)
        arr = np.asarray(d.sample([20000]).data)
        assert abs(arr.mean() - 0.3) < 0.02
        np.testing.assert_allclose(float(d.log_prob(1.0).data), np.log(0.3),
                                   atol=1e-4)

    def test_beta_dirichlet_shapes(self):
        paddle.seed(4)
        b = Beta(2.0, 3.0)
        assert np.asarray(b.sample([10]).data).shape == (10,)
        dd = Dirichlet(np.array([1.0, 2.0, 3.0], np.float32))
        s = np.asarray(dd.sample([6]).data)
        assert s.shape == (6, 3)
        np.testing.assert_allclose(s.sum(-1), np.ones(6), atol=1e-5)

    def test_kl_normal(self):
        p, q = Normal(0.0, 1.0), Normal(1.0, 2.0)
        kl = float(kl_divergence(p, q).data)
        ref = np.log(2.0) + (1 + 1) / 8 - 0.5
        np.testing.assert_allclose(kl, ref, atol=1e-5)

    def test_kl_categorical_nonnegative(self):
        p = Categorical(probs=[0.2, 0.8])
        q = Categorical(probs=[0.5, 0.5])
        assert float(kl_divergence(p, q).data) > 0
        assert abs(float(kl_divergence(p, p).data)) < 1e-7
