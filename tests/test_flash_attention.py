"""Flash-attention kernel conformance: forward + backward vs naive XLA path
(interpret mode on the CPU fixture; same code compiles for TPU)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels.flash_attention import flash_attention
from paddle_tpu.ops.attention import _naive_attention


def _rand_qkv(B=1, H=2, S=256, D=64, seed=0):
    k = jax.random.key(seed)
    kq, kk, kv = jax.random.split(k, 3)
    q = jax.random.normal(kq, (B, H, S, D), jnp.float32)
    k_ = jax.random.normal(kk, (B, H, S, D), jnp.float32)
    v = jax.random.normal(kv, (B, H, S, D), jnp.float32)
    return q, k_, v


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_naive(causal):
    q, k, v = _rand_qkv()
    out = flash_attention(q, k, v, causal=causal)
    ref = _naive_attention(q, k, v, causal=causal, training=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_backward_matches_naive(causal):
    q, k, v = _rand_qkv(S=256)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def f_naive(q, k, v):
        return jnp.sum(_naive_attention(q, k, v, causal=causal,
                                        training=False) ** 2)

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(f_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gn, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-3, rtol=5e-3,
                                   err_msg=f"d{name} mismatch")


def test_bf16_forward():
    q, k, v = _rand_qkv(S=128)
    q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))
    out = flash_attention(q, k, v, causal=True)
    ref = _naive_attention(q, k, v, causal=True, training=False)
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref, dtype=np.float32),
                               atol=3e-2, rtol=3e-2)


def test_multiblock_seq():
    q, k, v = _rand_qkv(S=512)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_kv=128)
    ref = _naive_attention(q, k, v, causal=True, training=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)


def test_ragged_seq_causal_pads():
    # S not a 128-multiple: causal path zero-pads and slices back
    q, k, v = _rand_qkv(S=200)
    out = flash_attention(q, k, v, causal=True)
    ref = _naive_attention(q, k, v, causal=True, training=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)


def test_ragged_seq_noncausal_raises():
    q, k, v = _rand_qkv(S=200)
    with pytest.raises(ValueError, match="128"):
        flash_attention(q, k, v, causal=False)
