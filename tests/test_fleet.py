"""Serving-fleet robustness: the FleetRouter's zero-loss failover,
drain-based balancing, backpressure, rolling restarts, the /healthz
fleet fold + /fleet endpoint, and the bounded-retries lint.

The acceptance matrix: for every replica-failure mode — io_error at
the ``serving.step`` fault site, an admission stall at ``serving.admit``,
and a hard process-level engine drop — every admitted request finishes
with greedy output token-identical to a no-failure reference run,
each in-flight request is re-dispatched exactly once per failure event
(no duplicate emission), and the dead replica's page pool is freed.
"""
import dataclasses
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models.gpt import GPT_CONFIGS, gpt_forward, gpt_init
from paddle_tpu.observability.exporter import start_telemetry_server
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.resilience import FaultSpec, injected_faults
from paddle_tpu.serving import (Engine, FleetRequestState, FleetRouter,
                                ReplicaState, RequestState, SamplingParams)


def _tiny_cfg():
    # fp32: the parity matrix compares argmax across replicas/recompute
    return dataclasses.replace(GPT_CONFIGS["tiny"], dtype="float32")


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny_cfg()
    params = gpt_init(cfg, jax.random.key(0), dtype=jnp.float32)
    return cfg, params


# stable jitted forward per config — see the test_serving.py oracle
# note: an eager gpt_forward compiles a fresh scan executable per call,
# exhausting the process mmap budget over a long suite
_ORACLE_FWD = {}


def naive_generate(cfg, params, prompt, n_new):
    """Full-recompute greedy decoding — the no-failure oracle."""
    fwd = _ORACLE_FWD.get(id(cfg))
    if fwd is None:
        fwd = _ORACLE_FWD.setdefault(
            id(cfg), jax.jit(lambda p, t: gpt_forward(cfg, p, t)))
    toks = list(prompt)
    for _ in range(n_new):
        logits = fwd(params, jnp.asarray([toks], jnp.int32))
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def _factory(cfg, params, **kw):
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 64)
    kw.setdefault("max_batch_size", 2)
    kw.setdefault("chunk_len", 8)

    def make():
        return Engine(cfg, params, **kw)

    return make


def _router(cfg, params, n=2, engine_kw=None, **kw):
    kw.setdefault("registry", MetricsRegistry())
    return FleetRouter([_factory(cfg, params, **(engine_kw or {}))] * n,
                       **kw)


def _prompts_and_refs(cfg, params, lens, max_new, seed=0):
    rng = np.random.RandomState(seed)
    prompts = [list(rng.randint(0, cfg.vocab_size, n)) for n in lens]
    return prompts, [naive_generate(cfg, params, p, max_new)
                     for p in prompts]


# --------------------------------------------------------------- basics


class TestFleetBasics:
    def test_multireplica_generate_matches_oracle(self, tiny_model):
        cfg, params = tiny_model
        prompts, refs = _prompts_and_refs(cfg, params, (5, 9, 7, 11, 3),
                                          max_new=6)
        router = _router(cfg, params, n=3)
        outs = router.generate(prompts, SamplingParams(max_new_tokens=6))
        assert outs == refs
        snap = router.metrics.snapshot()
        assert snap["lost"] == 0
        # the load actually spread: more than one replica dispatched
        assert len(snap["dispatches"]) >= 2
        assert sum(snap["dispatches"].values()) == len(prompts)

    def test_admissions_prefer_lowest_drain(self, tiny_model):
        """A replica with a measured backlog loses new admissions to an
        idle peer reporting a smaller drain estimate."""
        cfg, params = tiny_model
        router = _router(cfg, params, n=2,
                         engine_kw={"drain_floor_s": 0.0})
        busy = router.replicas[0].engine
        # build a real backlog + measured decode rate on replica 0
        for _ in range(3):
            busy.add_request(list(range(6)),
                             SamplingParams(max_new_tokens=40))
        for _ in range(3):
            busy.step()
        assert busy.estimated_drain_s() > 0
        req = router.submit(list(range(5)),
                            SamplingParams(max_new_tokens=2))
        router.step()
        assert req.replica_id == 1       # placed on the idle replica

    def test_infeasible_request_rejected_hard(self, tiny_model):
        cfg, params = tiny_model
        router = _router(cfg, params, n=2)
        too_long = list(range(cfg.max_seq_len))
        req = router.submit(too_long, SamplingParams(max_new_tokens=8))
        router.step()
        assert req.state == FleetRequestState.REJECTED
        assert req.redispatches == 0     # a rejection is not a failover


# --------------------------------------- kill-replica-mid-decode matrix


@pytest.mark.faultinject
class TestKillReplicaMidDecode:
    """For each failure site and a hard engine drop: greedy parity with
    the no-failure oracle, exactly-once re-dispatch, freed pages."""

    MAX_NEW = 8

    def _start(self, tiny_model, n=3, **router_kw):
        cfg, params = tiny_model
        prompts, refs = _prompts_and_refs(
            cfg, params, (5, 9, 7, 12, 4), max_new=self.MAX_NEW, seed=3)
        router = _router(cfg, params, n=n, **router_kw)
        reqs = [router.submit(p, SamplingParams(
            max_new_tokens=self.MAX_NEW)) for p in prompts]
        for _ in range(3):
            router.step()            # everyone dispatched, decode underway
        assert any(r.tokens_out for r in reqs)
        return cfg, params, router, reqs, refs

    def _finish_and_check(self, router, reqs, refs, *,
                          expect_dead_rid=None, dead_engine=None):
        while router.has_work():
            router.step()
        assert [r.state for r in reqs] == \
            [FleetRequestState.FINISHED] * len(reqs)
        # token-identical to the un-failed oracle: nothing lost, nothing
        # emitted twice (a duplicate would shift/lengthen the output)
        assert [r.output for r in reqs] == refs
        assert all(len(r.output) == self.MAX_NEW for r in reqs)
        # exactly-once: one failure event => at most one re-dispatch each
        assert all(r.redispatches <= 1 for r in reqs)
        assert any(r.redispatches == 1 for r in reqs)
        snap = router.metrics.snapshot()
        assert snap["lost"] == 0
        assert snap["redispatched"] == sum(r.redispatches for r in reqs)
        if expect_dead_rid is not None:
            rep = router.replicas[expect_dead_rid]
            assert rep.state == ReplicaState.DEAD
            assert snap["breaker_open"][str(expect_dead_rid)][
                "current"] == 1
        if dead_engine is not None:
            # the abandoned replica's pool was reclaimed on evacuation
            assert dead_engine.cache.num_free_pages == \
                dead_engine.cache.num_pages

    def test_io_error_at_serving_step(self, tiny_model):
        _, _, router, reqs, refs = self._start(tiny_model)
        eng0 = router.replicas[0].engine
        with injected_faults(FaultSpec("serving.step", "io_error",
                                       occurrence=1)):
            router.step()        # first engine stepped = replica 0
        assert router.replicas[0].state == ReplicaState.DEAD
        self._finish_and_check(router, reqs, refs, expect_dead_rid=0,
                               dead_engine=eng0)
        snap = router.metrics.snapshot()
        assert snap["failovers"].get("0,io_error") == 1

    def test_stall_at_serving_admit(self, tiny_model):
        cfg, params, router, reqs, refs = self._start(
            tiny_model, stall_timeout_s=0.05)
        late = router.submit(list(np.random.RandomState(9).randint(
            0, cfg.vocab_size, 6)), SamplingParams(
                max_new_tokens=self.MAX_NEW))
        refs = refs + [naive_generate(cfg, params, late.prompt,
                                      self.MAX_NEW)]
        with injected_faults(FaultSpec("serving.admit", "stall",
                                       occurrence=1, stall_s=0.25)):
            router.step()        # the admitting replica wedges
        dead = [rep for rep in router.replicas
                if rep.state == ReplicaState.DEAD]
        assert len(dead) == 1
        eng = dead[0].engine
        self._finish_and_check(router, reqs + [late], refs,
                               expect_dead_rid=dead[0].replica_id,
                               dead_engine=eng)
        snap = router.metrics.snapshot()
        assert snap["failovers"].get(
            f"{dead[0].replica_id},stall") == 1

    def test_hard_process_level_engine_drop(self, tiny_model):
        _, _, router, reqs, refs = self._start(tiny_model)
        corpse = router.replicas[0].engine   # keep the only reference
        router.kill_replica(0)
        self._finish_and_check(router, reqs, refs, expect_dead_rid=0)
        snap = router.metrics.snapshot()
        assert snap["failovers"].get("0,crash") == 1
        # relaunch: the replica re-enters rotation with a FRESH pool
        router.restart_replica(0)
        rep = router.replicas[0]
        assert rep.state == ReplicaState.HEALTHY
        assert rep.engine is not corpse
        assert rep.engine.cache.num_free_pages == \
            rep.engine.cache.num_pages
        assert router.metrics.snapshot()["breaker_open"]["0"][
            "current"] == 0

    def test_second_failure_redispatches_again_without_duplication(
            self, tiny_model):
        """Two successive replica deaths: a request may move twice —
        once per failure event — and the output still matches the
        oracle exactly."""
        _, _, router, reqs, refs = self._start(tiny_model, n=3)
        router.kill_replica(0)
        router.step()
        router.kill_replica(1)
        while router.has_work():
            router.step()
        assert [r.output for r in reqs] == refs
        assert all(r.redispatches <= 2 for r in reqs)
        assert router.metrics.snapshot()["lost"] == 0

    def test_probe_misses_open_the_breaker(self, tiny_model):
        """A replica whose health probe errors (but that never steps —
        it is idle) is retired via the missed-probe path."""
        cfg, params = tiny_model

        class _HealthlessEngine:
            def has_work(self):
                return False

            def health(self):
                raise OSError("health RPC refused")

        router = FleetRouter(
            [_factory(cfg, params), _HealthlessEngine()],
            probe_miss_threshold=2, registry=MetricsRegistry())
        router.step()
        assert router.replicas[1].probe_misses == 1
        assert router.replicas[1].state == ReplicaState.HEALTHY
        router.step()
        assert router.replicas[1].state == ReplicaState.DEAD
        assert router.metrics.snapshot()["failovers"].get(
            "1,probe") == 1
        # no factory: revive is impossible and says so
        with pytest.raises(ValueError, match="cannot\\s+restart"):
            router.restart_replica(1)


# --------------------------------------------------- cache-aware routing


class TestCacheAwareRouting:
    """Prefix-cache-aware dispatch: the router scores replicas by
    expected prefix-hit length jointly with the drain estimate, fed by
    bounded radix summaries — pulled in-process or gossiped over the
    TCPStore plane — and failover re-dispatch re-walks the target's
    tree so harvested-token redispatch stays exactly-once and
    token-identical."""

    def _shared_prompts(self, cfg, sys_len=24, tail_len=5, n=3, seed=71):
        rng = np.random.RandomState(seed)
        system = [int(t) for t in rng.randint(0, cfg.vocab_size, sys_len)]
        return system, [system + [int(t) for t in rng.randint(
            0, cfg.vocab_size, tail_len)] for _ in range(n)]

    def test_warm_replica_wins_dispatch(self, tiny_model):
        """Equal drain, one warm cache: the request goes to the replica
        already holding its system prompt, and the cache-aware counter
        records it."""
        cfg, params = tiny_model
        system, prompts = self._shared_prompts(cfg)
        router = _router(cfg, params, n=2)
        warm = SamplingParams(max_new_tokens=2)
        router.replicas[1].engine.generate([system], warm)  # warm #1 only
        req = router.submit(prompts[0], SamplingParams(max_new_tokens=4))
        router.step()
        assert req.replica_id == 1
        snap = router.metrics.snapshot()
        assert snap["cache_aware_dispatches"] == 1
        router.step()       # engine-side admission runs the radix walk
        # the prediction came true on the engine: a real radix hit
        assert router.replicas[1].engine.cache.prefix_stats()["hits"] == 1

    def test_backlogged_warm_replica_loses_to_idle_cold_peer(self,
                                                             tiny_model):
        """The hit credit is bounded: a deeply drained warm replica
        must not win over an idle cold one."""
        cfg, params = tiny_model
        system, prompts = self._shared_prompts(cfg, seed=73)
        router = _router(cfg, params, n=2,
                         engine_kw={"drain_floor_s": 0.0})
        warm_eng = router.replicas[0].engine
        warm_eng.generate([system], SamplingParams(max_new_tokens=2))
        # build a measured backlog on the warm replica
        for _ in range(3):
            warm_eng.add_request(list(range(6)),
                                 SamplingParams(max_new_tokens=60))
        for _ in range(3):
            warm_eng.step()
        assert warm_eng.estimated_drain_s() > \
            len(system) * router.cache_hit_token_s
        req = router.submit(prompts[0], SamplingParams(max_new_tokens=2))
        router.step()
        assert req.replica_id == 1           # idle cold peer wins

    def test_gossip_rides_tcpstore(self, tiny_model):
        """The cross-process path: each engine publishes its bounded
        radix summary through the StorePublisher machinery, the router
        scores from a one-mget collector — and still routes warm."""
        from paddle_tpu.distributed.store import TCPStore
        from paddle_tpu.serving import (PrefixSummaryPublisher,
                                        collect_prefix_summaries)

        cfg, params = tiny_model
        system, prompts = self._shared_prompts(cfg, seed=79)
        store = TCPStore(is_master=True, world_size=1)
        router = _router(
            cfg, params, n=2,
            prefix_summary_source=lambda: collect_prefix_summaries(
                store, [0, 1]))
        pubs = [PrefixSummaryPublisher(rep.engine, rep.replica_id, store)
                for rep in router.replicas]
        router.replicas[1].engine.generate(
            [system], SamplingParams(max_new_tokens=2))
        for pub in pubs:
            pub.publish()                    # one beat of the gossip
        req = router.submit(prompts[0], SamplingParams(max_new_tokens=4))
        router.step()
        assert req.replica_id == 1
        assert router.metrics.snapshot()["cache_aware_dispatches"] == 1
        # collector shape: stats + bounded entries per replica
        got = collect_prefix_summaries(store, [0, 1])
        assert set(got) == {0, 1}
        assert got[1]["stats"]["cached_pages"] > 0
        assert got[0]["stats"]["cached_pages"] == 0

    def test_cache_hit_then_failover_token_identical(self, tiny_model):
        """A request served from a warm cache, failed over mid-decode,
        re-walks the next replica's tree — harvested-token redispatch
        stays exactly-once and greedy output token-identical."""
        cfg, params = tiny_model
        system, prompts = self._shared_prompts(cfg, seed=83)
        refs = [naive_generate(cfg, params, p, 8) for p in prompts]
        router = _router(cfg, params, n=2)
        warm = SamplingParams(max_new_tokens=2)
        for rep in router.replicas:          # whole fleet warm
            rep.engine.generate([system], warm)
        reqs = [router.submit(p, SamplingParams(max_new_tokens=8))
                for p in prompts]
        for _ in range(3):
            router.step()
        assert any(r.tokens_out for r in reqs)
        victim = reqs[0].replica_id
        assert victim is not None
        router.kill_replica(victim)
        while router.has_work():
            router.step()
        assert [r.output for r in reqs] == refs
        assert all(r.redispatches <= 1 for r in reqs)
        assert any(r.redispatches == 1 for r in reqs)
        snap = router.metrics.snapshot()
        assert snap["lost"] == 0
        # the survivor served redispatches from its own warm tree
        survivor = router.replicas[1 - victim].engine
        assert survivor.cache.prefix_stats()["hits"] >= 1

    def test_fleet_status_reports_cache_state(self, tiny_model):
        """/fleet shows per-replica prefix-cache state once gossip has
        a beat behind it."""
        cfg, params = tiny_model
        system, prompts = self._shared_prompts(cfg, seed=89)
        router = _router(cfg, params, n=2)
        router.generate([prompts[0], prompts[1]],
                        SamplingParams(max_new_tokens=2))
        status = router.fleet_status()
        assert status["cache_aware"] is True
        per = status["replicas"]
        assert any(per[rid].get("prefix_cache", {}).get("cached_pages",
                                                        0) > 0
                   for rid in per)
        for rid in per:
            eng_health = per[rid]["engine"]
            assert "prefix_cache" in eng_health


# ------------------------------------------------------ rolling restart


class TestRollingRestart:
    def test_graceful_drain_finishes_then_restarts(self, tiny_model):
        cfg, params = tiny_model
        prompts, refs = _prompts_and_refs(cfg, params, (5, 9, 7),
                                          max_new=6, seed=5)
        router = _router(cfg, params, n=2, drain_deadline_s=1e6)
        reqs = [router.submit(p, SamplingParams(max_new_tokens=6))
                for p in prompts]
        for _ in range(2):
            router.step()
        drained_rid = reqs[0].replica_id
        old_engine = router.replicas[drained_rid].engine
        router.drain(drained_rid)
        assert router.replicas[drained_rid].state == ReplicaState.DRAINING
        # new work during the drain routes to the OTHER replica
        extra = router.submit(prompts[0], SamplingParams(max_new_tokens=6))
        router.step()
        assert extra.replica_id is not None
        assert extra.replica_id != drained_rid
        while router.has_work():
            router.step()
        # generous deadline: in-flight decode finished in place
        assert all(r.redispatches == 0 for r in reqs)
        assert [r.output for r in reqs] == refs
        assert extra.output == refs[0]
        rep = router.replicas[drained_rid]
        assert rep.state == ReplicaState.HEALTHY       # restarted
        assert rep.engine is not old_engine
        snap = router.metrics.snapshot()
        assert snap["drains"].get(str(drained_rid)) == 1
        assert snap["restarts"].get(str(drained_rid)) == 1

    def test_drain_deadline_redispatches_stragglers(self, tiny_model):
        cfg, params = tiny_model
        prompts, refs = _prompts_and_refs(cfg, params, (5, 9, 7),
                                          max_new=16, seed=7)
        router = _router(cfg, params, n=2)
        reqs = [router.submit(p, SamplingParams(max_new_tokens=16))
                for p in prompts]
        for _ in range(2):
            router.step()
        drained_rid = reqs[0].replica_id
        stragglers = [r for r in reqs if r.replica_id == drained_rid]
        router.drain(drained_rid, deadline_s=0.0)
        router.step()                    # deadline already passed
        assert all(r.redispatches == 1 for r in stragglers)
        assert router.replicas[drained_rid].state == ReplicaState.HEALTHY
        while router.has_work():
            router.step()
        assert [r.output for r in reqs] == refs
        assert router.metrics.snapshot()["lost"] == 0

    def test_drain_restart_requires_factory(self, tiny_model):
        cfg, params = tiny_model
        eng = _factory(cfg, params)()
        router = FleetRouter([eng], registry=MetricsRegistry())
        with pytest.raises(ValueError, match="no factory"):
            router.drain(0)
        # restart=False drains out of rotation instead
        router.drain(0, deadline_s=0.0, restart=False)
        router.step()
        assert router.replicas[0].state == ReplicaState.DEAD
        assert router.fleet_health()["healthy"] is False

    def test_warmup_runs_on_restarted_engine(self, tiny_model):
        cfg, params = tiny_model
        warmed = []
        router = _router(cfg, params, n=1, warmup=warmed.append)
        assert warmed == []              # initial build is caller-warmed
        router.kill_replica(0)
        router.step()
        router.restart_replica(0)
        assert warmed == [router.replicas[0].engine]


# --------------------------------------------------------- backpressure


class TestBackpressure:
    def test_retry_after_defers_with_bounded_backoff(self, tiny_model):
        """A shedding replica is neither hammered nor abandoned: the
        router backs off by the hint (bounded), requests stay pending,
        and everything finishes once the replica drains."""
        cfg, params = tiny_model
        router = _router(
            cfg, params, n=1,
            engine_kw={"shed_queue_high": 2, "shed_queue_low": 0,
                       "max_batch_size": 1, "drain_floor_s": 0.01},
            backoff_base_s=0.001, backoff_cap_s=0.02)
        prompts, refs = _prompts_and_refs(cfg, params, (4, 4, 4, 4, 4),
                                          max_new=3, seed=11)
        reqs = [router.submit(p, SamplingParams(max_new_tokens=3))
                for p in prompts]
        outs = None
        while router.has_work():
            router.step()
        outs = [r.output for r in reqs]
        assert outs == refs
        snap = router.metrics.snapshot()
        assert snap["backpressure_retries"].get("0", 0) > 0
        assert snap["lost"] == 0
        assert all(r.state == FleetRequestState.FINISHED for r in reqs)

    def test_backpressure_window_uses_hint_and_cap(self, tiny_model):
        cfg, params = tiny_model
        clock = _ManualClock()
        router = _router(cfg, params, n=1, clock=clock,
                         backoff_cap_s=2.0)
        rep = router.replicas[0]
        delay = router._backpressure(rep, 1.25, clock())
        assert 1.25 <= delay <= 2.0      # >= hint, <= cap
        assert rep.not_before == pytest.approx(clock() + delay)
        assert not router._can_admit(rep, clock())
        clock.advance(2.5)
        assert router._can_admit(rep, clock())
        big = router._backpressure(rep, 60.0, clock())
        assert big == 2.0                # hint above cap is clamped

    def test_fleet_ttl_expires_while_pending(self, tiny_model):
        """A fleet-level TTL is router-owned: a request nobody could
        place is evicted at dispatch time once its budget is gone."""
        cfg, params = tiny_model
        clock = _ManualClock()
        router = _router(cfg, params, n=1, clock=clock)
        router.kill_replica(0)
        router.step()                    # breaker opens; nothing admits
        req = router.submit(list(range(4)),
                            SamplingParams(max_new_tokens=4, ttl_s=5.0))
        clock.advance(10.0)
        router.restart_replica(0)
        router.step()
        assert req.state == FleetRequestState.EVICTED
        assert req.finish_reason == "deadline"


class _ManualClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


# ------------------------------------------- /healthz fold + /fleet e2e


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:          # non-2xx still has a body
        return e.code, e.read().decode()


class TestHealthzFleetFold:
    """The satellite contract: with a router attached, /healthz is 503
    only when NO replica can admit — all breakers open or draining —
    not when a single replica sheds."""

    def test_healthy_fleet_is_200_and_fleet_endpoint_serves(self,
                                                            tiny_model):
        cfg, params = tiny_model
        router = _router(cfg, params, n=2)
        with start_telemetry_server(port=0, router=router) as srv:
            code, body = _get(srv.url + "/healthz")
            health = json.loads(body)
            assert code == 200 and health["healthy"] is True
            assert health["replicas_admittable"] == 2
            code, body = _get(srv.url + "/fleet")
            fleet = json.loads(body)
            assert code == 200
            assert set(fleet["replicas"]) == {"0", "1"}
            assert fleet["replicas"]["0"]["engine"]["healthy"] is True
            assert "counters" in fleet

    def test_single_shedding_replica_is_not_an_outage(self, tiny_model):
        cfg, params = tiny_model
        router = _router(cfg, params, n=2,
                         engine_kw={"shed_queue_high": 1,
                                    "max_batch_size": 1})
        shed_eng = router.replicas[0].engine
        shed_eng.add_request([1, 2], SamplingParams(max_new_tokens=4))
        assert shed_eng._update_shedding()       # degraded on its own
        with start_telemetry_server(port=0, router=router) as srv:
            code, body = _get(srv.url + "/healthz")
            health = json.loads(body)
            assert code == 200 and health["healthy"] is True
            code, body = _get(srv.url + "/fleet")
            fleet = json.loads(body)
            assert fleet["replicas"]["0"]["engine"]["healthy"] is False
            assert fleet["replicas"]["0"]["state"] == "healthy"

    def test_503_only_when_no_replica_can_admit(self, tiny_model):
        cfg, params = tiny_model
        router = _router(cfg, params, n=2)
        with start_telemetry_server(port=0, router=router) as srv:
            router.kill_replica(0)
            router.step()                        # breaker 0 opens
            code, body = _get(srv.url + "/healthz")
            assert code == 200                   # replica 1 still admits
            assert json.loads(body)["replicas_admittable"] == 1
            router.drain(1, deadline_s=1e6)      # now: open + draining
            code, body = _get(srv.url + "/healthz")
            health = json.loads(body)
            assert code == 503 and health["healthy"] is False
            assert health["replicas_admittable"] == 0
            # recovery: restart the killed replica -> healthy again
            router.restart_replica(0)
            code, _ = _get(srv.url + "/healthz")
            assert code == 200

    def test_fleet_endpoint_404_without_router(self):
        with start_telemetry_server(port=0,
                                    registry=MetricsRegistry()) as srv:
            code, _ = _get(srv.url + "/fleet")
            assert code == 404


# ------------------------------------------------- bounded-retries lint


def _load_tool(name):
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(os.path.dirname(__file__), os.pardir,
                           "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBoundedRetriesLint:
    # the repo-wide sweep now runs ONCE in the consolidated suite:
    # tests/test_static_analysis.py::TestTier1Suite

    def test_sanctioned_daemons_carry_inline_suppressions(self):
        # the legacy module-level ALLOWLIST is retired: the sanctioned
        # unbounded loops (supervisor._watch, multiprocess._get) now
        # carry inline '# lint-ok: bounded-retries <reason>' markers at
        # the loop itself, so the exemption is visible at the site
        import os

        mod = _load_tool("check_bounded_retries")
        assert mod.ALLOWLIST == set()
        root = os.path.join(os.path.dirname(__file__), os.pardir,
                            "paddle_tpu")
        for rel in ("resilience/supervisor.py", "io/multiprocess.py"):
            with open(os.path.join(root, rel)) as f:
                assert "lint-ok: bounded-retries" in f.read(), rel

    def test_lint_catches_bare_retry_loop(self, tmp_path):
        mod = _load_tool("check_bounded_retries")
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "bad.py").write_text(
            "import time\n"
            "def fetch(sock):\n"
            "    while True:\n"
            "        try:\n"
            "            return sock.recv(1024)\n"
            "        except OSError:\n"
            "            time.sleep(0.1)\n")
        (pkg / "good.py").write_text(
            "import time\n"
            "from resilience.retry import Deadline\n"
            "def fetch(sock):\n"
            "    dl = Deadline(5.0)\n"
            "    while True:\n"
            "        if dl.expired():\n"
            "            raise TimeoutError\n"
            "        try:\n"
            "            return sock.recv(1024)\n"
            "        except OSError:\n"
            "            time.sleep(0.1)\n")
        (pkg / "daemon.py").write_text(
            "import time\n"
            "def watch(child):\n"
            "    while True:\n"
            "        if child.poll() is not None:\n"
            "            return\n"
            "        time.sleep(0.5)\n")
        out = mod.check(root=str(pkg), allowlist=())
        assert len(out) == 2
        assert any("bad.py:3 in fetch()" in v for v in out)
        assert any("daemon.py:3 in watch()" in v for v in out)
        # the allowlist clears a sanctioned daemon, nothing else
        out = mod.check(root=str(pkg),
                        allowlist={("daemon.py", "watch")})
        assert len(out) == 1 and "bad.py" in out[0]

    def test_non_blocking_while_true_is_not_flagged(self, tmp_path):
        mod = _load_tool("check_bounded_retries")
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "sched.py").write_text(
            "def plan(items):\n"
            "    while True:\n"
            "        if not items:\n"
            "            return\n"
            "        items.pop()\n")
        assert mod.check(root=str(pkg), allowlist=()) == []
