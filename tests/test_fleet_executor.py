"""Cross-process (DCN-role) pipeline runtime test (VERDICT r4 item 5):
two REAL processes, one pipeline stage each, activations/cotangents
streaming over the native TCPStore message bus — and the result matches
a single-process two-stage reference run exactly.

Reference: fleet_executor.h:35 / carrier.h:49 / message_bus.cc:177."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

WORKER = r"""
import json, os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.distributed.fleet_executor import (MessageBus,
                                                   PipelineStageExecutor)

rank = int(sys.argv[1]); port = int(sys.argv[2])
store = TCPStore("127.0.0.1", port, is_master=(rank == 0), world_size=2)
store.add("rendezvous", 1)
store.wait(["rendezvous"])
bus = MessageBus(store)

D = 8
rng = np.random.RandomState(0)
w0 = jnp.asarray(rng.randn(D, D).astype(np.float32) * 0.3)
w1 = jnp.asarray(rng.randn(D, 1).astype(np.float32) * 0.3)

def stage0(p, x):
    return jnp.tanh(x @ p)

def loss_fn(p, x, y):
    pred = x @ p
    return jnp.mean((pred - y) ** 2)

data = rng.randn(4, 8, D).astype(np.float32)   # 4 microbatches
target = rng.randn(4, 8, 1).astype(np.float32)

if rank == 0:
    ex = PipelineStageExecutor(stage0, w0, 0, 2, bus, lr=0.05)
    for step in range(5):
        ex.train_batch(list(data))
    print("W0SUM", float(jnp.sum(ex.params)))
else:
    ex = PipelineStageExecutor(None, w1, 1, 2, bus, loss_fn=loss_fn,
                               lr=0.05)
    losses = []
    for step in range(5):
        losses.append(ex.train_batch(None, labels=list(target)))
    print("LOSSES", json.dumps(losses))
    print("W1SUM", float(jnp.sum(ex.params)))
"""


def _reference_losses():
    """Single-process two-stage run with identical math."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    D = 8
    rng = np.random.RandomState(0)
    w0 = jnp.asarray(rng.randn(D, D).astype(np.float32) * 0.3)
    w1 = jnp.asarray(rng.randn(D, 1).astype(np.float32) * 0.3)
    data = rng.randn(4, 8, D).astype(np.float32)
    target = rng.randn(4, 8, 1).astype(np.float32)

    def full_loss(ws, x, y):
        w0_, w1_ = ws
        h = jnp.tanh(x @ w0_)
        return jnp.mean((h @ w1_ - y) ** 2)

    losses = []
    for step in range(5):
        per = []
        g0 = g1 = None
        for m in range(4):
            l, (ga, gb) = jax.value_and_grad(
                lambda ws: full_loss(ws, jnp.asarray(data[m]),
                                     jnp.asarray(target[m])))((w0, w1))
            per.append(float(l))
            g0 = ga / 4 if g0 is None else g0 + ga / 4
            g1 = gb / 4 if g1 is None else g1 + gb / 4
        w0 = w0 - 0.05 * g0
        w1 = w1 - 0.05 * g1
        losses.append(float(np.mean(per)))
    return losses


def test_two_process_pipeline_matches_reference(tmp_path):
    port = 23461
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    procs = [subprocess.Popen([sys.executable, str(script), str(r),
                               str(port)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True, env=env)
             for r in (0, 1)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=180)
        assert p.returncode == 0, (out, err)
        outs.append(out)
    line = [l for l in outs[1].splitlines() if l.startswith("LOSSES")][0]
    losses = json.loads(line[len("LOSSES "):])
    ref = _reference_losses()
    np.testing.assert_allclose(losses, ref, rtol=1e-5, atol=1e-6)
    # training across the process boundary actually reduced the loss
    assert losses[-1] < losses[0]


def test_message_bus_preserves_bfloat16(tmp_path):
    """bf16 is the engine's default activation dtype — the bus must
    round-trip it exactly (np.savez mangles ml_dtypes into void)."""
    import jax.numpy as jnp

    from paddle_tpu.distributed.fleet_executor import MessageBus
    from paddle_tpu.distributed.store import TCPStore

    store = TCPStore("127.0.0.1", 0, is_master=True)
    bus = MessageBus(store)
    tree = {"a": jnp.arange(6, dtype=jnp.bfloat16).reshape(2, 3),
            "b": np.float32([1.5, 2.5])}
    bus.send(0, 1, "t0", tree)
    out = bus.recv(0, 1, "t0")
    assert out["a"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(out["a"], np.float32),
                                  np.asarray(tree["a"], np.float32))
    np.testing.assert_array_equal(out["b"], tree["b"])
