"""Flight recorder tests: Span/Tracer model, request-lifecycle tracing
through the serving engine (chrome round-trip incl. evicted + shed),
the retry-after drain estimate, the telemetry HTTP endpoints scraped
over a real localhost socket, the resource sampler, import purity
(no side-effect threads/sockets), empty-histogram None semantics, and
the metric-naming lint."""
import dataclasses
import json
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models.gpt import GPT_CONFIGS, gpt_init
from paddle_tpu.observability import (Histogram, MetricsRegistry,
                                      ResourceSampler, Tracer,
                                      default_tracer,
                                      start_telemetry_server)
from paddle_tpu.serving import (Engine, RequestState, SamplingParams,
                                ServingMetrics)


class ManualClock:
    """Deterministic seconds source; ``auto`` advances a fixed dt per
    read so spans get nonzero, reproducible durations without sleeps."""

    def __init__(self, auto=0.0):
        self.t = 0.0
        self.auto = auto

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        self.t += self.auto
        return self.t


def _tiny_engine(clock=None, **kw):
    cfg = dataclasses.replace(GPT_CONFIGS["tiny"], dtype="float32")
    params = gpt_init(cfg, jax.random.key(0), dtype=jnp.float32)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 64)
    kw.setdefault("max_batch_size", 2)
    kw.setdefault("prefill_len", 32)
    return Engine(cfg, params, clock=clock, **kw)


# ----------------------------------------------------------------- tracer


class TestTracer:
    def test_span_tree_ids_and_ring(self):
        clk = ManualClock(auto=0.5)
        tr = Tracer(clock=clk, max_traces=3)
        root = tr.start_trace("op", attributes={"k": 1})
        child = tr.start_span("phase", root)
        grand = tr.start_span("inner", child)
        assert child.trace_id == root.trace_id == grand.trace_id
        assert child.parent_id == root.span_id
        assert grand.parent_id == child.span_id
        grand.end()
        child.end()
        assert tr.traces() == []             # root still open
        root.end()
        (done,) = tr.traces()
        assert done["name"] == "op"
        assert [s["name"] for s in done["spans"]] == ["op", "phase",
                                                      "inner"]
        assert done["duration_s"] > 0
        # ring keeps only the newest max_traces
        for i in range(5):
            tr.start_trace(f"t{i}").end()
        names = [t["name"] for t in tr.traces()]
        assert names == ["t2", "t3", "t4"]
        assert tr.summary()["completed"] == 6   # lifetime, not buffered

    def test_open_children_force_ended_with_root(self):
        tr = Tracer(clock=ManualClock(auto=1.0))
        root = tr.start_trace("op")
        tr.start_span("never_ended", root)
        root.end()
        (done,) = tr.traces()
        child = done["spans"][1]
        assert child["attributes"]["unfinished"] is True
        assert child["end_s"] == done["end_s"]

    def test_trace_context_manager_records_errors(self):
        tr = Tracer(clock=ManualClock(auto=1.0))
        with pytest.raises(ValueError):
            with tr.trace("boom"):
                raise ValueError("nope")
        (done,) = tr.traces()
        assert "ValueError" in done["spans"][0]["attributes"]["error"]

    def test_injectable_clock_stamps_exactly(self):
        clk = ManualClock()
        tr = Tracer(clock=clk)
        clk.advance(10.0)
        root = tr.start_trace("op")
        clk.advance(2.5)
        root.end()
        (done,) = tr.traces()
        assert done["start_s"] == 10.0 and done["end_s"] == 12.5


# -------------------------------------------------- engine request traces


class TestEngineRequestTracing:
    def test_request_span_tree_nests_chunk_and_decode(self):
        """Acceptance: a request traced through generate() yields a
        chrome-exportable span tree whose chunk/decode spans nest
        under the request root — injectable clock, no sleeps."""
        eng = _tiny_engine(clock=ManualClock(auto=0.001))
        eng.generate([[1, 2, 3]], SamplingParams(max_new_tokens=3))
        (tr,) = eng.tracer.traces()
        spans = {s["name"]: s for s in tr["spans"]}
        root = spans["request#0"]
        assert root["parent_id"] is None
        assert root["attributes"]["state"] == "finished"
        assert root["attributes"]["batch_slot"] == 0
        assert {"queued", "chunk[0]", "decode[1]", "decode[2]"} <= set(spans)
        for name, s in spans.items():
            if name == "request#0":
                continue
            assert s["parent_id"] == root["span_id"]
            assert root["start_s"] <= s["start_s"]
            assert s["end_s"] <= root["end_s"]
        # lifecycle order: queued → chunk[i] → decode[i]
        assert spans["queued"]["end_s"] <= spans["chunk[0]"]["start_s"]
        assert spans["chunk[0]"]["end_s"] <= spans["decode[1]"]["start_s"]
        # occupancy rides on the decode spans
        assert spans["decode[1]"]["attributes"]["page_occupancy"] > 0

    def test_chrome_round_trip_with_evicted_and_shed(self, tmp_path):
        clk = ManualClock()
        eng = _tiny_engine(clock=clk, shed_queue_high=2, max_batch_size=1)
        ok = eng.add_request([1, 2, 3], SamplingParams(max_new_tokens=2))
        doomed = eng.add_request([4, 5], SamplingParams(max_new_tokens=2,
                                                       ttl_s=0.5))
        shed = eng.add_request([6], SamplingParams(max_new_tokens=2))
        assert shed.state == RequestState.RETRY_AFTER
        clk.advance(0.01)
        eng.step()                       # admits+prefills ok
        clk.advance(1.0)                 # doomed's TTL passes while queued
        while eng.has_work():
            clk.advance(0.01)
            eng.step()
        assert ok.state == RequestState.FINISHED
        assert doomed.state == RequestState.EVICTED

        path = str(tmp_path / "flight.json")
        eng.tracer.export_chrome(path)
        with open(path) as f:
            trace = json.load(f)
        evs = trace["traceEvents"]
        # one labelled track per request
        labels = {e["tid"]: e["args"]["name"] for e in evs
                  if e["ph"] == "M" and e["name"] == "thread_name"}
        assert sorted(labels.values()) == ["request#0", "request#1",
                                           "request#2"]
        by_track = {}
        for e in evs:
            if e["ph"] == "X":
                by_track.setdefault(labels[e["tid"]], []).append(e)
        # finished request: full lifecycle nested inside the root X event
        req0 = {e["name"]: e for e in by_track["request#0"]}
        root = req0["request#0"]
        for name, e in req0.items():
            assert e["ts"] >= root["ts"]
            assert e["ts"] + e["dur"] <= root["ts"] + root["dur"] + 1e-6
        assert "chunk[0]" in req0 and "queued" in req0
        # evicted and shed requests still produce tracks
        assert any(e["name"] == "request#1" for e in by_track["request#1"])
        assert any(e["name"] == "request#2" for e in by_track["request#2"])

    def test_trace_states_for_terminal_paths(self):
        clk = ManualClock()
        eng = _tiny_engine(clock=clk, shed_queue_high=1, max_batch_size=1)
        rej = eng.add_request([], SamplingParams())     # infeasible
        q = eng.add_request([1, 2], SamplingParams(max_new_tokens=2,
                                                   ttl_s=0.1))
        shed = eng.add_request([3], SamplingParams())
        clk.advance(1.0)
        eng.step()                                      # evicts q
        states = {t["name"]: t["spans"][0]["attributes"]["state"]
                  for t in eng.tracer.traces()}
        assert states[f"request#{rej.id}"] == "rejected"
        assert states[f"request#{q.id}"] == "evicted"
        assert states[f"request#{shed.id}"] == "retry_after"
        shed_tr = [t for t in eng.tracer.traces()
                   if t["name"] == f"request#{shed.id}"][0]
        assert shed_tr["spans"][0]["attributes"]["retry_after_s"] > 0


# ------------------------------------------------------ retry-after hint


class TestRetryAfterHint:
    def test_shed_request_carries_finite_drain_estimate(self):
        """Acceptance: retry_after_s is finite, > 0, and derived from
        live queue depth ÷ the measured decode rate."""
        clk = ManualClock(auto=0.001)    # 1ms per clock read
        eng = _tiny_engine(clock=clk, shed_queue_high=3, shed_queue_low=0,
                           max_batch_size=1)
        for _ in range(3):
            eng.add_request([1, 2], SamplingParams(max_new_tokens=4))
        eng.step()                       # prefill + decode → EWMA rate
        assert eng.decode_rate() is not None and eng.decode_rate() > 0
        shed = eng.add_request([3, 4], SamplingParams(max_new_tokens=4))
        assert shed.state == RequestState.RETRY_AFTER
        assert shed.retry_after_s is not None
        assert 0 < shed.retry_after_s < float("inf")
        expected = eng.pending_decode_tokens() / eng.decode_rate()
        assert shed.retry_after_s == pytest.approx(expected, rel=1e-6)
        assert "retry in" in shed.finish_reason

    def test_drain_estimate_floored_before_decode_sample(self):
        eng = _tiny_engine(clock=ManualClock(auto=0.001),
                           shed_queue_high=1)
        # cold start: no EWMA sample yet — the conservative floor, not
        # a hammer-inviting 0 (the fleet router would otherwise dump
        # the whole backlog on a freshly restarted replica)
        assert eng.estimated_drain_s() == eng.drain_floor_s > 0
        assert eng.decode_rate() is None
        eng.add_request([1, 2], SamplingParams(max_new_tokens=8))
        # small backlog, still cold → the floor dominates the
        # ASSUMED_DECODE_RATE fallback (0.08s here)
        est = eng.estimated_drain_s()
        assert est == max(8 / Engine.ASSUMED_DECODE_RATE,
                          eng.drain_floor_s)
        shed = eng.add_request([3], SamplingParams(max_new_tokens=8))
        assert shed.state == RequestState.RETRY_AFTER
        assert shed.retry_after_s >= eng.drain_floor_s

    def test_health_and_gauges_publish_drain(self):
        clk = ManualClock(auto=0.001)
        # low watermark 0: hysteresis keeps the engine degraded until
        # the queue fully drains, so the post-step state is deterministic
        eng = _tiny_engine(clock=clk, shed_queue_high=2, shed_queue_low=0,
                           max_batch_size=1)
        eng.metrics = ServingMetrics(registry=MetricsRegistry())
        for _ in range(2):
            eng.add_request([1, 2], SamplingParams(max_new_tokens=4))
        eng.step()
        h = eng.health()
        assert h["healthy"] is False     # queue watermark crossed
        assert h["estimated_drain_s"] > 0
        assert h["queue_depth"] == 1
        snap = eng.metrics.registry.snapshot()
        assert snap["serving_estimated_drain_seconds"]["value"]["current"] > 0
        assert snap["serving_queue_depth"]["value"]["current"] == 1


# ----------------------------------------------------------- hapi spans


class TestHapiStepSpans:
    def test_fit_opens_per_step_spans(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.io import Dataset

        class Toy(Dataset):
            def __init__(self, n=8):
                rng = np.random.RandomState(0)
                self.x = rng.randn(n, 4).astype(np.float32)
                self.y = rng.randint(0, 2, (n,)).astype(np.int64)

            def __len__(self):
                return len(self.x)

            def __getitem__(self, i):
                return self.x[i], self.y[i]

        default_tracer().reset()
        model = paddle.Model(nn.Sequential(nn.Linear(4, 8), nn.ReLU(),
                                           nn.Linear(8, 2)))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss())
        model.fit(Toy(), batch_size=4, epochs=1, verbose=0)
        steps = [t for t in default_tracer().traces()
                 if t["name"] == "hapi::step"]
        assert len(steps) == 2
        attrs = [t["spans"][0]["attributes"] for t in steps]
        assert [a["step"] for a in attrs] == [0, 1]
        assert all(a["epoch"] == 0 for a in attrs)
        assert all(isinstance(a["loss"], float) for a in attrs)


# ------------------------------------------------------- resource sampler


class TestResourceSampler:
    def test_sample_once_populates_gauges(self):
        reg = MetricsRegistry()
        s = ResourceSampler(registry=reg)
        sample = s.sample_once()
        assert sample["rss_bytes"] is None or sample["rss_bytes"] > 0
        snap = reg.snapshot()
        if sample["rss_bytes"] is not None:
            assert snap["process_rss_bytes"]["value"]["current"] > 0
        if sample["open_fds"] is not None:
            assert snap["process_open_fds"]["value"]["current"] > 0
        # jax is imported in this process → live buffers are measurable
        assert sample["jax_live_buffer_bytes"] is not None
        assert "0" in sample["gc_collections"]
        json.dumps(sample)

    def test_thread_start_stop(self):
        import threading

        reg = MetricsRegistry()
        before = {t.name for t in threading.enumerate()}
        with ResourceSampler(interval_s=0.01, registry=reg) as s:
            for _ in range(200):
                if s.last_sample is not None:
                    break
                threading.Event().wait(0.01)
            assert s.last_sample is not None
        assert {t.name for t in threading.enumerate()} == before


# ----------------------------------------------- telemetry endpoints e2e


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.headers.get("Content-Type", ""), \
                r.read().decode()
    except urllib.error.HTTPError as e:      # non-2xx still has a body
        return e.code, e.headers.get("Content-Type", ""), \
            e.read().decode()


class TestTelemetryServerE2E:
    """End-to-end over a real localhost socket: scrape /metrics,
    /healthz, /varz and /traces during a generate() run."""

    def test_scrape_all_endpoints_during_generation(self):
        # private tracer: the process-wide one carries traces from other
        # tests, and this test counts exactly its own two requests
        eng = _tiny_engine(tracer=Tracer())
        eng.metrics = ServingMetrics()          # fresh global series
        with start_telemetry_server(port=0, engine=eng) as srv:
            assert srv.port > 0
            eng.generate([[1, 2, 3], [4, 5]],
                         SamplingParams(max_new_tokens=3))

            code, ctype, body = _get(srv.url + "/metrics")
            assert code == 200 and ctype.startswith("text/plain")
            assert "# TYPE serving_requests_submitted_total counter" \
                in body
            assert "serving_requests_submitted_total 2" in body
            assert "serving_ttft_seconds_bucket" in body

            code, ctype, body = _get(srv.url + "/healthz")
            health = json.loads(body)
            assert code == 200 and health["healthy"] is True
            assert set(health) >= {"queue_depth", "page_occupancy",
                                   "estimated_drain_s",
                                   "decode_rate_tok_s"}

            code, _, body = _get(srv.url + "/varz")
            varz = json.loads(body)
            assert "serving_requests_finished_total" in varz["metrics"]
            assert "jit" in varz and "pid" in varz

            code, _, body = _get(srv.url + "/traces")
            traces = json.loads(body)["traces"]
            assert len(traces) == 2
            for t in traces:
                names = [s["name"] for s in t["spans"]]
                assert names[0].startswith("request#")
                assert "chunk[0]" in names

            code, _, body = _get(srv.url + "/traces?limit=1")
            assert len(json.loads(body)["traces"]) == 1

            code, _, _ = _get(srv.url + "/nope")
            assert code == 404

    def test_healthz_503_while_shedding(self):
        eng = _tiny_engine(shed_queue_high=1)
        with start_telemetry_server(port=0, engine=eng) as srv:
            eng.add_request([1, 2], SamplingParams(max_new_tokens=4))
            assert eng._update_shedding()
            code, _, body = _get(srv.url + "/healthz")
            assert code == 503
            assert json.loads(body)["healthy"] is False

    def test_registry_fallback_without_engine(self):
        reg = MetricsRegistry()
        reg.gauge("serving_engine_healthy").set(1)
        reg.gauge("serving_queue_depth").set(7)
        with start_telemetry_server(port=0, registry=reg) as srv:
            code, _, body = _get(srv.url + "/healthz")
            health = json.loads(body)
            assert code == 200
            assert health["queue_depth"] == 7


# --------------------------------------------------------- import purity


class TestImportPurity:
    def test_import_paddle_tpu_spawns_no_threads_or_sockets(self):
        """Exporter and sampler are strictly opt-in: a bare import must
        not start a thread or open a listening socket (tier-1: a fleet
        binary embedding the framework owns its own ports)."""
        script = (
            "import json, os, threading\n"
            "def socket_fds():\n"
            "    out = []\n"
            "    for fd in os.listdir('/proc/self/fd'):\n"
            "        try:\n"
            "            t = os.readlink(f'/proc/self/fd/{fd}')\n"
            "        except OSError:\n"
            "            continue\n"
            "        if t.startswith('socket:'):\n"
            "            out.append(fd)\n"
            "    return out\n"
            "before_t = {t.name for t in threading.enumerate()}\n"
            "before_s = socket_fds()\n"
            "import paddle_tpu\n"
            "import paddle_tpu.observability.exporter\n"
            "after_t = {t.name for t in threading.enumerate()}\n"
            "after_s = socket_fds()\n"
            "print(json.dumps({'new_threads': sorted(after_t - before_t),"
            " 'new_sockets': sorted(set(after_s) - set(before_s))}))\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", script], capture_output=True,
            text=True, timeout=300,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr[-2000:]
        diff = json.loads(out.stdout.strip().splitlines()[-1])
        assert diff["new_threads"] == [], diff
        assert diff["new_sockets"] == [], diff


# ------------------------------------------------------ empty histograms


class TestEmptyHistogram:
    def test_percentile_and_summary_none_filled(self):
        h = Histogram("lat")
        assert h.percentile(50) is None
        s = h.summary()
        assert s == {"count": 0, "mean": None, "p50": None, "p95": None,
                     "p99": None}
        json.dumps(s)                    # JSON null, not a crash
        h.observe(0.5)
        assert h.percentile(50) == 0.5
        assert h.summary()["mean"] == 0.5

    def test_fresh_process_exposition_does_not_raise(self):
        reg = MetricsRegistry()
        reg.histogram("cold_series")
        text = reg.expose_prometheus()
        assert "cold_series_count 0" in text
        snap = reg.snapshot()
        assert snap["cold_series"]["value"]["p50"] is None

    def test_serving_summary_renders_empty_series(self):
        m = ServingMetrics(registry=MetricsRegistry())
        text = m.summary()               # nothing observed anywhere
        assert "queue_wait_s" in text and "-" in text


# ------------------------------------------------------ metric-name lint


class TestMetricNamesLint:
    def _tool(self):
        import importlib.util
        import os

        path = os.path.join(os.path.dirname(__file__), os.pardir,
                            "tools", "check_metric_names.py")
        spec = importlib.util.spec_from_file_location(
            "check_metric_names", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    # the repo-wide sweep now runs ONCE in the consolidated suite:
    # tests/test_static_analysis.py::TestTier1Suite

    def test_lint_catches_planted_violations(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "from paddle_tpu.observability import Counter, Gauge\n"
            "a = Counter('requests_served')\n"          # no _total
            "b = Gauge('CamelCaseName')\n"              # not snake_case
            "c = Counter(\n    'foo_total')\n"          # multi-line: seen
            "d = Gauge('foo_total')\n"                  # kind mismatch
            "# Counter('commented_out')\n")             # comment: ignored
        violations = self._tool().check(root=str(tmp_path))
        text = "\n".join(violations)
        assert "requests_served" in text and "_total" in text
        assert "CamelCaseName" in text
        assert "foo_total" in text and "one name, one type" in text
        assert "commented_out" not in text
        assert len(violations) == 3


# --------------------------------------------------- tracing overhead smoke


class TestTracingOverheadSmoke:
    def test_implied_request_overhead_under_bound(self):
        """Acceptance: a full request-shaped trace lifecycle, scaled to
        a documented 50 ms TTFT-class request, stays under the 1% bound
        ``bench --section tracing`` publishes — with tail retention at
        full sampling (the default posture)."""
        import importlib.util
        import os

        path = os.path.join(os.path.dirname(__file__), os.pardir,
                            "bench.py")
        spec = importlib.util.spec_from_file_location("bench_mod", path)
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        out = bench.bench_tracing(iters=900, reps=3)
        assert out["implied_request_overhead_ratio"] < \
            out["bound_ratio"], out
        # absolute sanity: tens of microseconds per request, not ms
        assert out["per_request_full_us"] < 1000, out
        # the disabled posture must be dramatically cheaper (null span)
        assert out["per_request_disabled_us"] < \
            out["per_request_full_us"], out
        # and sampled retention must actually shed boring traces
        assert out["ring_sampled"]["dropped"] > 0, out
