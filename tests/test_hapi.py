"""hapi Model.fit tests (reference: hapi/model.py Model surface + the
test_model.py MNIST-LeNet scenario, shrunk to CPU-test size)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi import (EarlyStopping, LRScheduler, Model,
                             ModelCheckpoint)
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy


class ToyDataset(Dataset):
    """Linearly-separable 2-class blobs."""

    def __init__(self, n=64, seed=0):
        rng = np.random.RandomState(seed)
        self.y = rng.randint(0, 2, (n,)).astype(np.int64)
        self.x = (rng.randn(n, 8) * 0.3 +
                  self.y[:, None].astype(np.float32) * 2.0
                  ).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _net(seed=3):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))


def _model(seed=3, lr=0.1):
    model = Model(_net(seed))
    opt = paddle.optimizer.SGD(learning_rate=lr,
                               parameters=model.parameters())
    model.prepare(opt, nn.CrossEntropyLoss(), Accuracy())
    return model


class TestFit:
    def test_fit_learns(self):
        model = _model()
        hist = model.fit(ToyDataset(), batch_size=16, epochs=4, verbose=0)
        assert len(hist) == 4
        assert hist[-1]["loss"] < hist[0]["loss"]
        assert hist[-1]["acc"] > 0.9

    def test_evaluate_and_predict(self):
        model = _model()
        model.fit(ToyDataset(), batch_size=16, epochs=3, verbose=0)
        logs = model.evaluate(ToyDataset(n=32, seed=9), batch_size=16,
                              verbose=0)
        assert logs["acc"] > 0.9
        preds = model.predict(ToyDataset(n=32, seed=9), batch_size=16)
        assert preds[0].shape == (32, 2)

    def test_save_load_roundtrip(self, tmp_path):
        model = _model()
        model.fit(ToyDataset(), batch_size=16, epochs=2, verbose=0)
        ref = model.evaluate(ToyDataset(n=32, seed=9), verbose=0)
        model.save(str(tmp_path / "ck"))
        assert os.path.exists(tmp_path / "ck.pdparams")

        fresh = _model(seed=99)   # different init
        fresh.load(str(tmp_path / "ck"))
        got = fresh.evaluate(ToyDataset(n=32, seed=9), verbose=0)
        np.testing.assert_allclose(got["loss"], ref["loss"], atol=1e-5)

    def test_checkpoint_callback(self, tmp_path):
        model = _model()
        model.fit(ToyDataset(), batch_size=16, epochs=2, verbose=0,
                  save_dir=str(tmp_path), save_freq=1)
        assert os.path.exists(tmp_path / "0.pdparams")
        assert os.path.exists(tmp_path / "final.pdparams")

    def test_early_stopping(self):
        model = _model(lr=0.0)   # loss cannot improve
        es = EarlyStopping(monitor="loss", patience=1, mode="min")
        hist = model.fit(ToyDataset(), batch_size=16, epochs=10, verbose=0,
                         callbacks=[es])
        assert len(hist) < 10
        assert es.stopped_epoch >= 0

    def test_lr_scheduler_callback(self):
        paddle.seed(3)
        net = _net()
        sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1,
                                              step_size=2, gamma=0.5)
        model = Model(net)
        opt = paddle.optimizer.SGD(learning_rate=sched,
                                   parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss())
        model.fit(ToyDataset(n=64), batch_size=16, epochs=1, verbose=0,
                  callbacks=[LRScheduler(by_step=True)])
        assert opt.get_lr() < 0.1   # 4 batches > step_size=2 -> decayed


class TestMetrics:
    def test_accuracy_topk(self):
        from paddle_tpu.metric import Accuracy

        m = Accuracy(topk=(1, 2))
        pred = np.array([[0.1, 0.7, 0.2], [0.8, 0.1, 0.1]])
        label = np.array([1, 2])
        m.update(m.compute(pred, label))
        top1, top2 = m.accumulate()
        assert top1 == 0.5       # second sample top1 wrong
        assert top2 == 1.0       # both labels inside the top-2 sets
        assert m.name() == ["acc_top1", "acc_top2"]

    def test_accuracy_rank3_sequence_logits(self):
        # [B, S, V] logits must count B*S samples (advisor r3 finding:
        # counting only B gave accuracies > 1)
        from paddle_tpu.metric import Accuracy

        m = Accuracy()
        pred = np.zeros((2, 3, 4), np.float32)
        pred[..., 0] = 1.0                       # argmax = 0 everywhere
        label = np.zeros((2, 3), np.int64)
        label[0, 0] = 1                          # one miss out of 6
        acc = m.update(m.compute(pred, label))
        assert abs(acc - 5 / 6) < 1e-6
        assert 0.0 <= acc <= 1.0

    def test_precision_recall(self):
        from paddle_tpu.metric import Precision, Recall

        p, r = Precision(), Recall()
        pred = np.array([0.9, 0.8, 0.2, 0.6])
        label = np.array([1, 0, 1, 1])
        assert abs(p.update(pred, label) - 2 / 3) < 1e-6
        assert abs(r.update(pred, label) - 2 / 3) < 1e-6


class TestModelEdgeCases:
    def test_fit_zero_epochs(self):
        model = _model()
        hist = model.fit(ToyDataset(), batch_size=16, epochs=0, verbose=0)
        assert hist == []

    def test_accuracy_topk_through_model(self):
        paddle.seed(3)
        model = Model(_net())
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss(), Accuracy(topk=(1, 2)))
        hist = model.fit(ToyDataset(), batch_size=16, epochs=1, verbose=0)
        assert "acc_top1" in hist[0] and "acc_top2" in hist[0]
        assert hist[0]["acc_top2"] == 1.0   # 2 classes: top2 is always hit

    def test_precision_through_model_protocol(self):
        """Base-class compute() returns (pred, label); Model must unpack."""
        from paddle_tpu.metric import Precision

        paddle.seed(3)
        net = nn.Sequential(nn.Linear(8, 1), nn.Sigmoid(), nn.Flatten(0))
        model = Model(net)
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=model.parameters())
        model.prepare(opt, nn.MSELoss(), Precision())
        ds = ToyDataset(n=32)
        ds.y = ds.y.astype(np.float32)
        hist = model.fit(ds, batch_size=16, epochs=1, verbose=0)
        assert "precision" in hist[0]

    def test_batchnorm_stats_update(self):
        """Running statistics must survive the jitted step (they are
        captured before swap_state restores the originals)."""
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 8), nn.BatchNorm1D(8),
                            nn.Linear(8, 2))
        model = Model(net)
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss())
        bn = net[1]
        before = np.asarray(bn._mean.data).copy()
        model.fit(ToyDataset(), batch_size=16, epochs=1, verbose=0)
        after = np.asarray(bn._mean.data)
        assert not np.allclose(before, after), "BN stats never updated"


class TestDistributedFit:
    """prepare(device_mesh=...) auto-DP (reference: hapi/model.py:191
    prepare_distributed_context): batch sharded over the dp mesh, params
    replicated, XLA all-reduces the grads — same losses as one device."""

    def _fit(self, device_mesh):
        model = Model(_net(7))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss(), Accuracy(),
                      device_mesh=device_mesh)
        hist = model.fit(ToyDataset(), batch_size=16, epochs=3,
                         shuffle=False, verbose=0)
        return [h["loss"] for h in hist]

    def test_dp_mesh_matches_single_device(self):
        import jax
        from jax.sharding import Mesh

        single = self._fit(None)
        mesh = Mesh(np.array(jax.devices()), ("dp",))
        dist = self._fit(mesh)
        np.testing.assert_allclose(dist, single, rtol=1e-5, atol=1e-6)

    def test_auto_mesh_and_eval(self):
        model = Model(_net(9))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss(), Accuracy(),
                      device_mesh="auto")
        model.fit(ToyDataset(), batch_size=16, epochs=1, verbose=0)
        logs = model.evaluate(ToyDataset(n=32, seed=1), batch_size=16,
                              verbose=0)
        assert "acc" in logs or any(k.startswith("acc") for k in logs)

    def test_indivisible_batch_trims_ragged_tail(self):
        """A user-supplied batch not divisible by dp is trimmed to the
        largest dp multiple (reference distributed-sampler drop
        semantics) instead of raising mid-epoch; a batch smaller than dp
        is padded by repeating the last sample."""
        model = Model(_net(9))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss(), device_mesh="auto")
        hist = model.fit(ToyDataset(n=12), batch_size=12, verbose=0)
        assert len(hist) == 1 and np.isfinite(hist[0]["loss"])
        # smaller than dp: padded, still runs
        x = np.random.rand(3, 8).astype(np.float32)
        y = np.random.randint(0, 2, (3,))
        loss, _ = model.train_batch(x, y)
        assert np.isfinite(loss)
