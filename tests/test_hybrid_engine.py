"""Hybrid-engine parity tests (the reference's hybrid_parallel_mp_*/pp_*
test strategy: every parallel config must match the single-device model).

Runs on the virtual 8-device CPU mesh from conftest.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # heavy parity matrix (VERDICT r3 item 9)

from paddle_tpu.distributed.engine import EngineConfig, HybridEngine
from paddle_tpu.models.gpt import GPTConfig, gpt_loss

CFG = GPTConfig(vocab_size=256, max_seq_len=64, hidden=64, num_layers=4,
                num_heads=4, ffn_hidden=128, dtype="float32",
                use_flash=False, remat="nothing")


def _batch(bs=8, seq=32, seed=0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, CFG.vocab_size, (bs, seq)).astype(np.int32)
    labels = np.concatenate([tokens[:, 1:], np.full((bs, 1), -100)],
                            axis=1).astype(np.int32)
    return tokens, labels


def _run_steps(engine, n=3, bs=8, seq=32):
    params, opt = engine.init(seed=0)
    losses = []
    tokens, labels = _batch(bs, seq, seed=0)
    for i in range(n):
        params, opt, loss = engine.step(params, opt, tokens, labels, lr=1e-3)
        losses.append(float(loss))
    return losses, engine.gather_params(params)


@pytest.fixture(scope="module")
def baseline():
    eng = HybridEngine(CFG, dp=1, pp=1, sharding=1, sep=1, mp=1,
                       devices=jax.devices()[:1])
    return _run_steps(eng)


def _assert_close(losses, base_losses, atol=2e-4):
    np.testing.assert_allclose(losses, base_losses, atol=atol, rtol=1e-4)


def test_single_device_loss_sane(baseline):
    losses, _ = baseline
    # cross-entropy near log(vocab) at init, decreasing
    assert abs(losses[0] - np.log(CFG.vocab_size)) < 1.0
    assert losses[-1] < losses[0]


def test_dp_matches(baseline):
    eng = HybridEngine(CFG, dp=8)
    losses, _ = _run_steps(eng)
    _assert_close(losses, baseline[0])


def test_mp_matches(baseline):
    eng = HybridEngine(CFG, mp=4, devices=jax.devices()[:4])
    losses, _ = _run_steps(eng)
    _assert_close(losses, baseline[0])


def test_sharding_zero2_matches(baseline):
    eng = HybridEngine(CFG, sharding=4, devices=jax.devices()[:4])
    losses, _ = _run_steps(eng)
    _assert_close(losses, baseline[0])


def test_pp_matches(baseline):
    eng = HybridEngine(CFG, pp=2, devices=jax.devices()[:2],
                       engine_cfg=EngineConfig(num_microbatches=4))
    losses, _ = _run_steps(eng)
    _assert_close(losses, baseline[0])


def test_sep_ulysses_matches(baseline):
    eng = HybridEngine(CFG, sep=2, devices=jax.devices()[:2])
    losses, _ = _run_steps(eng)
    _assert_close(losses, baseline[0])


def test_hybrid_2x2x2_matches(baseline):
    eng = HybridEngine(CFG, dp=2, pp=2, mp=2,
                       engine_cfg=EngineConfig(num_microbatches=2))
    losses, _ = _run_steps(eng)
    _assert_close(losses, baseline[0])


def test_hybrid_dp_sharding_mp(baseline):
    eng = HybridEngine(CFG, dp=2, sharding=2, mp=2)
    losses, _ = _run_steps(eng)
    _assert_close(losses, baseline[0])


def test_full_4axis(baseline):
    eng = HybridEngine(CFG, dp=1, pp=2, sharding=2, sep=1, mp=2,
                       engine_cfg=EngineConfig(num_microbatches=2))
    losses, _ = _run_steps(eng)
    _assert_close(losses, baseline[0])


def test_zero3_matches(baseline):
    eng = HybridEngine(CFG, sharding=4, devices=jax.devices()[:4],
                       engine_cfg=EngineConfig(zero_stage=3))
    losses, _ = _run_steps(eng)
    _assert_close(losses, baseline[0])


def test_zero3_hybrid_matches(baseline):
    eng = HybridEngine(CFG, dp=2, sharding=2, mp=2,
                       engine_cfg=EngineConfig(zero_stage=3))
    losses, _ = _run_steps(eng)
    _assert_close(losses, baseline[0])


def test_zero3_persistent_memory_smaller():
    """Stage-3 must hold strictly less persistent state per device than
    stage-2 (params sharded, not just opt state) — the HBM assertion from
    the reference's group_sharded_stage3 contract."""
    def device0_bytes(engine):
        params, opt = engine.init(seed=0)
        total = 0
        for leaf in (jax.tree_util.tree_leaves(params) +
                     jax.tree_util.tree_leaves(opt)):
            total += leaf.addressable_shards[0].data.nbytes
        return total

    devs = jax.devices()[:4]
    b2 = device0_bytes(HybridEngine(CFG, sharding=4, devices=devs,
                                    engine_cfg=EngineConfig(zero_stage=2)))
    b3 = device0_bytes(HybridEngine(CFG, sharding=4, devices=devs,
                                    engine_cfg=EngineConfig(zero_stage=3)))
    # opt state is sharded in both (3/7 of the f32 footprint per param);
    # stage-3 shards the working params too, taking a matrix leaf from
    # (4+3)/7 to (1+3)/7 ≈ 0.57 — small replicated leaves add a little
    assert b3 < 0.65 * b2, (b3, b2)


def test_zero3_param_leaves_sharded():
    eng = HybridEngine(CFG, sharding=4, devices=jax.devices()[:4],
                       engine_cfg=EngineConfig(zero_stage=3))
    params, _ = eng.init(seed=0)
    qkv = params["blocks"]["qkv_w"]
    assert qkv.addressable_shards[0].data.size * 4 == qkv.size


def test_grad_accum_matches(baseline):
    eng = HybridEngine(CFG, devices=jax.devices()[:1],
                       engine_cfg=EngineConfig(accum_steps=4))
    losses, _ = _run_steps(eng)
    _assert_close(losses, baseline[0])


def test_grad_accum_hybrid_matches(baseline):
    eng = HybridEngine(CFG, dp=2, sharding=2, mp=2,
                       engine_cfg=EngineConfig(accum_steps=2, zero_stage=3))
    losses, _ = _run_steps(eng)
    _assert_close(losses, baseline[0])


def test_params_stay_synced(baseline):
    _, base_params = baseline
    eng = HybridEngine(CFG, dp=2, mp=2, sharding=2)
    _, params = _run_steps(eng)
    flat_a = jax.tree_util.tree_leaves(base_params)
    flat_b = jax.tree_util.tree_leaves(params)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_bf16_opt_slots_train():
    """opt_dtype='bfloat16' (reference Adam multi_precision=False): slots
    store bf16, math in fp32 — training converges on the same curve
    shape as fp32 slots (loose tolerance: bf16 master loses mantissa)."""
    import jax

    cfg = GPTConfig(vocab_size=256, max_seq_len=64, hidden=64,
                    num_layers=2, num_heads=4, ffn_hidden=128,
                    dtype="float32", use_flash=False, remat="nothing")

    def run(opt_dtype):
        eng = HybridEngine(cfg, engine_cfg=EngineConfig(
            opt_dtype=opt_dtype), devices=jax.devices()[:1])
        params, opt = eng.init(seed=0)
        rng = np.random.RandomState(0)
        tokens = rng.randint(0, 256, (8, 32)).astype(np.int32)
        labels = np.concatenate([tokens[:, 1:], np.full((8, 1), -100)],
                                1).astype(np.int32)
        losses = []
        for _ in range(5):
            params, opt, loss = eng.step(params, opt, tokens, labels,
                                         lr=1e-3)
            losses.append(float(loss))
        return losses, opt

    l32, _ = run("float32")
    l16, opt16 = run("bfloat16")
    leaf = jax.tree_util.tree_leaves(opt16["slots"])[0]
    assert leaf.dtype == jnp.bfloat16
    assert all(np.isfinite(l16))
    assert l16[-1] < l16[0]
    np.testing.assert_allclose(l16, l32, rtol=0.05)


def test_windowed_adam_with_master_matches():
    """The fori_loop windowed optimizer path WITH a separate master slot
    (opt_dtype != model dtype): the fresh param-dtype output buffer must
    carry the slots' vma (caught live on gpt2-medium: invariant zeros vs
    sharding-varying windows -> fixed-carry type error)."""
    cfg = GPTConfig(vocab_size=256, max_seq_len=64, hidden=64,
                    num_layers=2, num_heads=4, ffn_hidden=128,
                    dtype="float32", use_flash=False, remat="nothing")

    def run(window):
        eng = HybridEngine(cfg, sharding=2, devices=jax.devices()[:2],
                           engine_cfg=EngineConfig(
                               opt_dtype="bfloat16",  # != dtype => master
                               opt_update_window=window))
        params, opt = eng.init(seed=0)
        tokens, labels = _batch(4, 32)
        losses = []
        for _ in range(3):
            params, opt, loss = eng.step(params, opt, tokens, labels,
                                         lr=1e-3)
            losses.append(float(loss))
        return losses

    np.testing.assert_allclose(run(1 << 24), run(1024), rtol=1e-6)
